"""Expression AST.

Reference: ``io.siddhi.query.api.expression`` (Expression, Variable, constants,
condition/Compare..., math/Add..., AttributeFunction). Redesigned as plain dataclasses;
the same tree is consumed by both the host interpreter executor builder
(``core/executor.py``) and the TPU expression compiler (``tpu/expr_compile.py``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional, Union

from .definition import DataType


class Expression:
    """Base class; factory helpers (``Expression.value``/``Expression.variable``,
    mirroring the reference's fluent API) are attached below the dataclass
    definitions to avoid colliding with dataclass field names."""

    # comparison / logic sugar
    def __and__(self, other: "Expression") -> "And":
        return And(self, other)

    def __or__(self, other: "Expression") -> "Or":
        return Or(self, other)


class CompareOp(enum.Enum):
    LT = "<"
    LE = "<="
    GT = ">"
    GE = ">="
    EQ = "=="
    NEQ = "!="


class MathOp(enum.Enum):
    ADD = "+"
    SUB = "-"
    MUL = "*"
    DIV = "/"
    MOD = "%"


@dataclass
class Constant(Expression):
    value: Any
    type: DataType

    # time constants (e.g. ``10 sec``) parse to Constant(millis, LONG) with is_time=True
    is_time: bool = False


# sentinel for ``e[last]`` style indexes
LAST_INDEX = -1


@dataclass
class Variable(Expression):
    attribute: str
    stream_id: Optional[str] = None       # stream id or pattern alias ("e1")
    stream_index: Optional[int] = None    # e1[0] / e1[last] (LAST_INDEX)
    function_id: Optional[str] = None     # aggregation function references


@dataclass
class Compare(Expression):
    left: Expression
    op: CompareOp
    right: Expression


@dataclass
class And(Expression):
    left: Expression
    right: Expression


@dataclass
class Or(Expression):
    left: Expression
    right: Expression


@dataclass
class Not(Expression):
    expr: Expression


@dataclass
class IsNull(Expression):
    expr: Optional[Expression] = None
    stream_id: Optional[str] = None       # ``e1 is null`` (pattern absent check)
    stream_index: Optional[int] = None


@dataclass
class In(Expression):
    expr: Expression
    source_id: str                        # table/window id


@dataclass
class MathExpr(Expression):
    left: Expression
    op: MathOp
    right: Expression


@dataclass
class Minus(Expression):                  # unary minus
    expr: Expression


@dataclass
class AttributeFunction(Expression):
    """``ns:name(arg, ...)`` — built-in function, aggregator, or extension call."""

    namespace: Optional[str]
    name: str
    args: list[Expression] = field(default_factory=list)


# -- fluent factory API (reference: Expression.value/variable static methods) ----

def _expr_value(v: Any) -> Constant:
    if isinstance(v, bool):
        return Constant(v, DataType.BOOL)
    if isinstance(v, int):
        return Constant(v, DataType.LONG if abs(v) > 2**31 - 1 else DataType.INT)
    if isinstance(v, float):
        return Constant(v, DataType.DOUBLE)
    if isinstance(v, str):
        return Constant(v, DataType.STRING)
    raise TypeError(f"unsupported constant {v!r}")


def _expr_variable(name: str, stream: Optional[str] = None,
                   index: Optional[int] = None) -> Variable:
    return Variable(attribute=name, stream_id=stream, stream_index=index)


Expression.value = staticmethod(_expr_value)
Expression.variable = staticmethod(_expr_variable)
