"""Annotations: ``@name(key='value', 'indexed-value', @nested(...))``.

Reference: ``io.siddhi.query.api.annotation.Annotation`` — used for @app, @async,
@OnError, @PrimaryKey, @Index, @store, @sink, @source, @map, @attributes, @dist, @info.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union


@dataclass
class Element:
    key: Optional[str]
    value: str


@dataclass
class Annotation:
    name: str
    elements: list[Element] = field(default_factory=list)
    annotations: list["Annotation"] = field(default_factory=list)  # nested
    # namespace of the `@ns:name(...)` form (e.g. @app:playback → "app");
    # the parser routes app-namespaced annotations to the SiddhiApp
    namespace: Optional[str] = None

    def element(self, key: Optional[str], value: str) -> "Annotation":
        self.elements.append(Element(key, value))
        return self

    def get(self, key: Optional[str], default: Optional[str] = None) -> Optional[str]:
        for e in self.elements:
            if e.key == key:
                return e.value
        return default

    def indexed_values(self) -> list[str]:
        return [e.value for e in self.elements if e.key is None]

    def nested(self, name: str) -> Optional["Annotation"]:
        for a in self.annotations:
            if a.name.lower() == name.lower():
                return a
        return None


def find_annotation(annotations: list[Annotation], name: str) -> Optional[Annotation]:
    for a in annotations:
        if a.name.lower() == name.lower():
            return a
    return None


def find_all_annotations(annotations: list[Annotation], name: str) -> list[Annotation]:
    return [a for a in annotations if a.name.lower() == name.lower()]
