"""Query API: the typed AST every front end lowers to.

Mirrors the role of the reference's ``siddhi-query-api`` module (92 files under
``modules/siddhi-query-api/src/main/java/io/siddhi/query/api/``): a programmatic
builder API plus the structures the SiddhiQL compiler produces.
"""

from .annotation import Annotation, Element, find_all_annotations, find_annotation
from .app import SiddhiApp
from .definition import (
    AbstractDefinition,
    AggregationDefinition,
    Attribute,
    DataType,
    FunctionDefinition,
    OutputEventType,
    StreamDefinition,
    TableDefinition,
    TimePeriodDuration,
    TriggerDefinition,
    WindowDefinition,
)
from .execution import (
    AbsentStreamStateElement,
    CountStateElement,
    DeleteStream,
    EventOutputRate,
    EventTrigger,
    EveryStateElement,
    Filter,
    InsertIntoStream,
    JoinInputStream,
    JoinType,
    LogicalStateElement,
    LogicalType,
    NextStateElement,
    OnDemandQuery,
    OnDemandQueryType,
    OrderByAttribute,
    OrderByOrder,
    OutputAttribute,
    OutputEventsFor,
    OutputRateType,
    Partition,
    PartitionType,
    Query,
    RangePartitionProperty,
    ReturnStream,
    Selector,
    SingleInputStream,
    SnapshotOutputRate,
    StateElement,
    StateInputStream,
    StateInputStreamType,
    StreamFunction,
    StreamStateElement,
    TimeOutputRate,
    UpdateOrInsertStream,
    UpdateSetAttribute,
    UpdateStream,
    Window,
)
from .expression import (
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Expression,
    In,
    IsNull,
    LAST_INDEX,
    MathExpr,
    MathOp,
    Minus,
    Not,
    Or,
    Variable,
)
