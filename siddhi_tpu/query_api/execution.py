"""Execution AST: queries, input streams, pattern state elements, selectors, outputs.

Reference: ``io.siddhi.query.api.execution`` — ``query/Query.java``,
``query/input/stream/{Single,Join,State}InputStream.java``,
``query/input/state/*StateElement.java``, ``query/selection/Selector.java``,
``query/output/stream/*``, ``query/output/ratelimit``, ``partition/Partition.java``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional, Union

from .annotation import Annotation
from .expression import Expression, Variable


# ---------------------------------------------------------------------------
# Stream handlers (things after '#' or '[...]' on an input stream)
# ---------------------------------------------------------------------------

@dataclass
class Filter:
    expr: Expression


@dataclass
class Window:
    namespace: Optional[str]
    name: str
    params: list[Expression] = field(default_factory=list)


@dataclass
class StreamFunction:
    namespace: Optional[str]
    name: str
    params: list[Expression] = field(default_factory=list)


StreamHandler = Union[Filter, Window, StreamFunction]


# ---------------------------------------------------------------------------
# Input streams
# ---------------------------------------------------------------------------

@dataclass
class SingleInputStream:
    stream_id: str
    handlers: list[StreamHandler] = field(default_factory=list)
    alias: Optional[str] = None          # `as a`
    is_fault_stream: bool = False        # `!stream`
    is_inner_stream: bool = False        # `#stream` (partition-local)

    @property
    def window(self) -> Optional[Window]:
        for h in self.handlers:
            if isinstance(h, Window):
                return h
        return None

    def ref(self) -> str:
        return self.alias or self.stream_id


class JoinType(enum.Enum):
    JOIN = "join"                    # inner
    INNER_JOIN = "inner join"
    LEFT_OUTER_JOIN = "left outer join"
    RIGHT_OUTER_JOIN = "right outer join"
    FULL_OUTER_JOIN = "full outer join"


class EventTrigger(enum.Enum):
    """Which side's arrivals trigger join output (``unidirectional``)."""
    LEFT = "left"
    RIGHT = "right"
    ALL = "all"


@dataclass
class JoinInputStream:
    left: SingleInputStream
    join_type: JoinType
    right: SingleInputStream
    on_condition: Optional[Expression] = None
    trigger: EventTrigger = EventTrigger.ALL
    within: Optional[Expression] = None
    per: Optional[Expression] = None


# ---------------------------------------------------------------------------
# Pattern / sequence state elements
# ---------------------------------------------------------------------------

class StateElement:
    pass


@dataclass
class StreamStateElement(StateElement):
    """`e1=StreamA[filter]` — a basic input stream with optional alias binding."""
    stream: SingleInputStream
    within: Optional[Expression] = None


@dataclass
class NextStateElement(StateElement):
    """`A -> B` (pattern) or `A , B` (sequence)."""
    first: StateElement
    next: StateElement
    within: Optional[Expression] = None


@dataclass
class EveryStateElement(StateElement):
    """`every (A -> B)` — re-seed matching on every occurrence."""
    inner: StateElement
    within: Optional[Expression] = None


class LogicalType(enum.Enum):
    AND = "and"
    OR = "or"


@dataclass
class LogicalStateElement(StateElement):
    """`A and B` / `A or B`."""
    first: StreamStateElement
    type: LogicalType
    second: StreamStateElement
    within: Optional[Expression] = None


@dataclass
class CountStateElement(StateElement):
    """`A<min:max>` (pattern) or `A*`, `A+`, `A?` (sequence)."""
    stream: StreamStateElement
    min_count: int = 1
    max_count: int = -1               # -1 = unbounded
    within: Optional[Expression] = None

    ANY = -1


@dataclass
class AbsentStreamStateElement(StateElement):
    """`not A [for 1 sec]` — non-occurrence."""
    stream: SingleInputStream
    waiting_time_ms: Optional[int] = None
    within: Optional[Expression] = None


class StateInputStreamType(enum.Enum):
    PATTERN = "pattern"    # skip-till-any-match between states
    SEQUENCE = "sequence"  # strict continuity


@dataclass
class StateInputStream:
    type: StateInputStreamType
    state: StateElement
    within: Optional[Expression] = None

    def single_streams(self) -> "list[SingleInputStream]":
        """Every SingleInputStream under the state tree, in walk order —
        THE walk for whole-surface audits (keep element-kind dispatch here
        so new StateElement kinds extend one place)."""
        out: list[SingleInputStream] = []

        def walk(el: StateElement) -> None:
            if isinstance(el, (StreamStateElement, AbsentStreamStateElement)):
                out.append(el.stream)
            elif isinstance(el, NextStateElement):
                walk(el.first)
                walk(el.next)
            elif isinstance(el, EveryStateElement):
                walk(el.inner)
            elif isinstance(el, LogicalStateElement):
                walk(el.first)
                walk(el.second)
            elif isinstance(el, CountStateElement):
                walk(el.stream)

        walk(self.state)
        return out

    def stream_ids(self) -> list[str]:
        seen: set[str] = set()
        uniq = []
        for s in self.single_streams():
            if s.stream_id not in seen:
                seen.add(s.stream_id)
                uniq.append(s.stream_id)
        return uniq


InputStream = Union[SingleInputStream, JoinInputStream, StateInputStream]


# ---------------------------------------------------------------------------
# Selector
# ---------------------------------------------------------------------------

@dataclass
class OutputAttribute:
    rename: Optional[str]
    expr: Expression

    @property
    def name(self) -> str:
        if self.rename:
            return self.rename
        if isinstance(self.expr, Variable):
            return self.expr.attribute
        raise ValueError("projection expression needs an 'as' rename")


class OrderByOrder(enum.Enum):
    ASC = "asc"
    DESC = "desc"


@dataclass
class OrderByAttribute:
    variable: Variable
    order: OrderByOrder = OrderByOrder.ASC


@dataclass
class Selector:
    select_all: bool = False                       # `select *`
    attributes: list[OutputAttribute] = field(default_factory=list)
    group_by: list[Variable] = field(default_factory=list)
    having: Optional[Expression] = None
    order_by: list[OrderByAttribute] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None


# ---------------------------------------------------------------------------
# Output streams & rate limiting
# ---------------------------------------------------------------------------

class OutputEventsFor(enum.Enum):
    """`insert into X for current events / expired events / all events`."""
    CURRENT_EVENTS = "current"
    EXPIRED_EVENTS = "expired"
    ALL_EVENTS = "all"


@dataclass
class InsertIntoStream:
    target_id: str
    events_for: OutputEventsFor = OutputEventsFor.CURRENT_EVENTS
    is_fault_stream: bool = False
    is_inner_stream: bool = False


@dataclass
class ReturnStream:
    events_for: OutputEventsFor = OutputEventsFor.CURRENT_EVENTS


@dataclass
class DeleteStream:
    target_id: str
    on_condition: Expression = None


@dataclass
class UpdateSetAttribute:
    table_variable: Variable
    value_expr: Expression


@dataclass
class UpdateStream:
    target_id: str
    on_condition: Expression = None
    set_attributes: list[UpdateSetAttribute] = field(default_factory=list)


@dataclass
class UpdateOrInsertStream:
    target_id: str
    on_condition: Expression = None
    set_attributes: list[UpdateSetAttribute] = field(default_factory=list)


OutputStream = Union[InsertIntoStream, ReturnStream, DeleteStream, UpdateStream, UpdateOrInsertStream]


class OutputRateType(enum.Enum):
    ALL = "all"
    FIRST = "first"
    LAST = "last"


@dataclass
class EventOutputRate:
    """`output [all|first|last] every N events`."""
    value: int
    type: OutputRateType = OutputRateType.ALL


@dataclass
class TimeOutputRate:
    """`output [all|first|last] every <time>`."""
    value_ms: int
    type: OutputRateType = OutputRateType.ALL


@dataclass
class SnapshotOutputRate:
    """`output snapshot every <time>`."""
    value_ms: int


OutputRate = Union[EventOutputRate, TimeOutputRate, SnapshotOutputRate, None]


# ---------------------------------------------------------------------------
# Query / partition / on-demand query
# ---------------------------------------------------------------------------

@dataclass
class Query:
    input_stream: InputStream = None
    selector: Selector = field(default_factory=Selector)
    output_stream: OutputStream = None
    output_rate: OutputRate = None
    annotations: list[Annotation] = field(default_factory=list)

    # fluent builder API (reference: Query.query().from_(...).select(...)...)
    @staticmethod
    def query() -> "Query":
        return Query()

    def from_(self, input_stream: InputStream) -> "Query":
        self.input_stream = input_stream
        return self

    def select(self, selector: Selector) -> "Query":
        self.selector = selector
        return self

    def insert_into(self, target: str,
                    events_for: OutputEventsFor = OutputEventsFor.CURRENT_EVENTS) -> "Query":
        self.output_stream = InsertIntoStream(target, events_for)
        return self

    def annotation(self, ann: Annotation) -> "Query":
        self.annotations.append(ann)
        return self

    def name(self) -> Optional[str]:
        from .annotation import find_annotation
        info = find_annotation(self.annotations, "info")
        return info.get("name") if info else None


@dataclass
class RangePartitionProperty:
    partition_key: str                 # range label, e.g. 'LessValue'
    condition: Expression = None


@dataclass
class PartitionType:
    stream_id: str
    # exactly one of:
    value_expr: Optional[Expression] = None
    ranges: list[RangePartitionProperty] = field(default_factory=list)


@dataclass
class Partition:
    partition_types: list[PartitionType] = field(default_factory=list)
    queries: list[Query] = field(default_factory=list)
    annotations: list[Annotation] = field(default_factory=list)


class OnDemandQueryType(enum.Enum):
    FIND = "find"
    INSERT = "insert"
    DELETE = "delete"
    UPDATE = "update"
    UPDATE_OR_INSERT = "update or insert"


@dataclass
class OnDemandQuery:
    """Pull query against a table/window/aggregation (`runtime.query(...)`)."""
    type: OnDemandQueryType
    input_store_id: Optional[str] = None
    on_condition: Optional[Expression] = None
    selector: Selector = field(default_factory=Selector)
    output_stream: OutputStream = None
    # aggregation on-demand extras: `within <t1>, <t2> per 'seconds'`
    within: Optional[tuple] = None
    per: Optional[Expression] = None
