"""Typed definitions: streams, tables, windows, triggers, aggregations, functions.

Covers the surface of the reference's ``io.siddhi.query.api.definition`` package
(``StreamDefinition.java``, ``TableDefinition.java``, ``WindowDefinition.java``,
``TriggerDefinition.java``, ``AggregationDefinition.java``, ``FunctionDefinition.java``,
``Attribute.java``) redesigned for a columnar, dtype-first runtime: every attribute type
maps to a fixed device dtype so event batches pack into SoA arrays.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional

from .annotation import Annotation


class DataType(enum.Enum):
    """Attribute types (reference: ``definition/Attribute.java`` Type enum).

    Each type carries its device representation: strings are dictionary-encoded to
    int32 codes at ingress; OBJECT attributes stay host-side only.
    """

    STRING = "string"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    BOOL = "bool"
    OBJECT = "object"

    @property
    def numpy_dtype(self) -> str:
        return {
            DataType.STRING: "int32",   # dictionary code
            DataType.INT: "int32",
            DataType.LONG: "int64",
            DataType.FLOAT: "float32",
            DataType.DOUBLE: "float64",
            DataType.BOOL: "bool",
            DataType.OBJECT: "object",
        }[self]

    @property
    def is_numeric(self) -> bool:
        return self in (DataType.INT, DataType.LONG, DataType.FLOAT, DataType.DOUBLE)


@dataclass(frozen=True)
class Attribute:
    name: str
    type: DataType

    def __repr__(self) -> str:
        return f"{self.name} {self.type.value}"


class AbstractDefinition:
    """Common base for all definitions (reference: ``definition/AbstractDefinition.java``)."""

    def __init__(self, id: str):
        self.id = id
        self.attributes: list[Attribute] = []
        self.annotations: list[Annotation] = []
        self._index: dict[str, int] = {}

    def attribute(self, name: str, type: DataType | str) -> "AbstractDefinition":
        if isinstance(type, str):
            type = DataType(type)
        if name in self._index:
            raise ValueError(f"duplicate attribute '{name}' in definition '{self.id}'")
        self._index[name] = len(self.attributes)
        self.attributes.append(Attribute(name, type))
        return self

    def annotation(self, ann: Annotation) -> "AbstractDefinition":
        self.annotations.append(ann)
        return self

    # -- lookups -------------------------------------------------------------
    def attribute_position(self, name: str) -> int:
        try:
            return self._index[name]
        except KeyError:
            raise KeyError(
                f"attribute '{name}' not found in '{self.id}' "
                f"(has {[a.name for a in self.attributes]})"
            ) from None

    def attribute_type(self, name: str) -> DataType:
        return self.attributes[self.attribute_position(name)].type

    @property
    def attribute_names(self) -> list[str]:
        return [a.name for a in self.attributes]

    def same_schema(self, other: "AbstractDefinition") -> bool:
        return [(a.name, a.type) for a in self.attributes] == [
            (a.name, a.type) for a in other.attributes
        ]

    def __repr__(self) -> str:
        attrs = ", ".join(repr(a) for a in self.attributes)
        return f"define {type(self).__name__.replace('Definition','').lower()} {self.id} ({attrs})"


class StreamDefinition(AbstractDefinition):
    """``define stream Name (attr type, ...)``."""


class TableDefinition(AbstractDefinition):
    """``define table Name (attr type, ...)`` with optional @PrimaryKey/@Index/@store."""


class WindowDefinition(AbstractDefinition):
    """``define window Name (attrs) window(params) [output <event-type> events]``.

    Reference: ``definition/WindowDefinition.java`` — carries the window handler and
    the output event type the named window publishes.
    """

    def __init__(self, id: str):
        super().__init__(id)
        self.window_handler: Any = None  # compiler sets a StreamHandler (Window)
        self.output_event_type: "OutputEventType" = OutputEventType.ALL_EVENTS


class OutputEventType(enum.Enum):
    CURRENT_EVENTS = "current"
    EXPIRED_EVENTS = "expired"
    ALL_EVENTS = "all"


@dataclass
class TriggerDefinition:
    """``define trigger T at {'start' | every <time> | '<cron>'}``."""

    id: str
    at_every_ms: Optional[int] = None  # periodic interval
    at_cron: Optional[str] = None      # cron expression
    at_start: bool = False
    annotations: list[Annotation] = field(default_factory=list)


class TimePeriodDuration(enum.Enum):
    SECONDS = "seconds"
    MINUTES = "minutes"
    HOURS = "hours"
    DAYS = "days"
    MONTHS = "months"
    YEARS = "years"

    @property
    def order(self) -> int:
        return list(TimePeriodDuration).index(self)


@dataclass
class AggregationDefinition:
    """``define aggregation A from S select ... group by ... aggregate [by ts] every sec...year``.

    Reference: ``definition/AggregationDefinition.java`` + ``aggregation/TimePeriod.java``.
    """

    id: str
    basic_single_input_stream: Any = None   # SingleInputStream
    selector: Any = None                    # Selector
    aggregate_attribute: Optional[str] = None  # timestamp attribute (None = event time)
    durations: list[TimePeriodDuration] = field(default_factory=list)
    annotations: list[Annotation] = field(default_factory=list)


@dataclass
class FunctionDefinition:
    """``define function f[lang] return type { body }`` (script functions)."""

    id: str
    language: str
    return_type: DataType
    body: str
    annotations: list[Annotation] = field(default_factory=list)
