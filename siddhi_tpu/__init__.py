"""siddhi_tpu — a TPU-native streaming CEP framework.

A brand-new framework with the capabilities of Siddhi (streaming SQL: filters,
windows, joins, pattern/sequence NFA matching, partitions, tables, aggregations,
snapshots, sources/sinks), designed TPU-first: queries compile to vectorized
micro-batch programs (JAX/XLA/Pallas) with all mutable state held in pytrees, and a
host interpreter runtime serves as the semantic oracle and cold-path fallback.
"""

__version__ = "0.1.0"

from . import query_api
from .compiler import SiddhiCompiler, parse, parse_on_demand_query, parse_query
from .core import (
    ErrorEntry,
    ErrorStore,
    Event,
    FileErrorStore,
    IncrementalFileSystemPersistenceStore,
    IncrementalPersistenceStore,
    InMemoryBroker,
    InMemoryConfigManager,
    InMemoryPersistenceStore,
    InputHandler,
    QueryCallback,
    RecordTableHandler,
    RecordTableHandlerManager,
    SinkHandler,
    SinkHandlerManager,
    SourceHandler,
    SourceHandlerManager,
    SiddhiAppRuntime,
    SiddhiManager,
    StreamCallback,
    YAMLConfigManager,
    extension,
)
