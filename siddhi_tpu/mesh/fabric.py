"""MeshFabric: the placement & live-migration layer fusing fleet lanes
with DCN lane-groups (ROADMAP item 3).

PRs 6/8/12 built the single-host tenant fleet (shared compilation, lane
batching, blast-radius isolation, the SLO autopilot) and PR 4 built
multi-host lane-group failover — but nothing composed them: a tenant ran
wherever its app happened to deploy. The fabric closes that gap:

- **hosts** — each :class:`MeshHost` is one engine shard: its own
  ``SiddhiManager`` (so its own FleetManager → its own plan cache → the
  compiled-programs-per-host number placement minimizes) bound to one
  accelerator device of the mesh;
- **placement** — a :class:`~siddhi_tpu.mesh.plan.PlacementPolicy` assigns
  every tenant a ``(host, lane-group, device)`` slot, locality-aware by
  shape fingerprint with capacity scoring fed by ``fleet.*``/``slo.*``
  evidence and the flight recorder (``plan.py``);
- **ingress routing** — :meth:`send` routes per-tenant row chunks to the
  owning host with per-tenant ``(epoch, seq)`` stamps and a monotone
  applied-mark — the receiver-side dedup that makes retries, migration
  replays and kill-recovery exactly-once (the ``K_ROWS`` discipline of
  ``tpu/dcn.py``, applied to tenants instead of lane groups);
- **live migration** — :meth:`migrate` moves a tenant between hosts under
  sustained ingest: fresh chunks spill (bounded, in order — the
  :class:`~siddhi_tpu.resilience.dcn_guard.SpillQueue`), the source host
  flushes + snapshots the tenant (the per-tenant snapshot/restore from
  PR 6, carried as whole-app state bytes), the revision lands in the
  :class:`~siddhi_tpu.resilience.dcn_guard.LaneGroupSnapshotStore` (keyed
  by the tenant's global id, dedup mark inside — durable before the
  hand-off, exactly like a lane-group takeover), the target host restores
  and ACKs the adoption (lost acks retry, the ``K_ADOPT`` discipline),
  ownership re-points, and the spill replays in order through the same
  dedup'd apply path. Zero loss, zero duplication, per-tenant oracle
  byte-identical — pinned by tests/test_mesh.py under chaos;
- **elasticity** — :meth:`add_host` / :meth:`remove_host` recompute the
  plan (sticky: surviving slots keep their tenants) and apply the diff as
  bulk migrations; :meth:`kill_host` + :meth:`recover_tenant` are the
  crash path (restore from the latest revision + spill replay — with
  ``snapshot_every_chunks=1`` an applied chunk is durable before its send
  returns, the ``snapshot_every_frames=1`` DCN contract);
- **the cross-host SLO rung** — an armed group's
  :class:`~siddhi_tpu.observability.slo.SLOController` gets a
  ``mesh_hook``: when its in-process ladder is exhausted it decides
  ``mesh_replace`` (recorded with evidence BEFORE dispatch, like every
  actuator) and the fabric re-places the violating tenant on the
  least-loaded host — the cross-host actuator PR 12 deferred.

Every fabric decision path records to the flight recorder(s) BEFORE
actuating (``scripts/check_guard_coverage.py`` pins it for the rebalancer
the same way it pins the SLO controller).

**Order caveat**: a migration inserts a flush boundary, and the fleet
tier's NFA match ORDER is flush-cadence-dependent (a pre-existing
property of every flush — adaptive resize, SLO shrink, drain). The match
MULTISET is exact (zero loss, zero duplication, pinned); stateless
shapes are byte-identical including order.

**Dictionary caveat** (the DCN layer's "codes do not cross hosts" rule,
inherited): a migrated tenant's state restores its string-dictionary
tables monotonically into the destination group
(:func:`~siddhi_tpu.fleet.group.restore_dicts_monotonic`). Destination
tables that EXTEND or match the snapshot's restore exactly; a conflicting
generation (same values minted in a different order on the target host)
keeps the live table and logs loudly — co-locate same-shape tenants over
one multiplexed feed (the locality policy's job) and the tables agree.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from ..observability.flight_recorder import FlightRecorder
from ..resilience.dcn_guard import LaneGroupSnapshotStore, SpillQueue
from .plan import HostSlot, MeshPlan, PlacementPolicy, TenantSpec, \
    shape_fingerprint

log = logging.getLogger("siddhi_tpu.mesh")

_DEF_CAPACITY = 256            # tenant slots per host
_DEF_SPILL_FRAMES = 4096
_ADOPT_RETRY_MAX = 3


class MeshChaosFault(Exception):
    """Raised by an armed chaos hook at a named fabric site."""


class MeshConfig:
    """Fabric knobs (kwargs-style; everything has a default)."""

    def __init__(self, capacity_per_host: int = _DEF_CAPACITY,
                 policy: str = "locality", seed: int = 17,
                 snapshot_every_chunks: Optional[int] = None,
                 spill_capacity_frames: int = _DEF_SPILL_FRAMES,
                 spill_policy: str = "block",
                 adopt_retry_max: int = _ADOPT_RETRY_MAX,
                 playback: bool = True,
                 mode: str = "inproc",
                 heartbeat_interval_s: float = 0.5,
                 worker_failure_threshold: int = 2,
                 restart_max: int = 5,
                 restart_base_s: float = 0.25,
                 restart_window_s: float = 60.0,
                 auto_restart: bool = True,
                 worker_env: Optional[dict] = None,
                 durable: bool = False,
                 journal_fsync: bool = False,
                 journal_checkpoint_every: int = 256,
                 trace_sample: Optional[int] = None,
                 trace_ring: int = 2048,
                 metrics_stale_after_s: float = 10.0,
                 io_timeout_s: Optional[float] = None,
                 connect_timeout_s: Optional[float] = None,
                 hedge_fraction: float = 0.45,
                 wedge_threshold: int = 3,
                 degrade_factor: float = 4.0,
                 degrade_floor_s: float = 0.05,
                 degrade_min_samples: int = 16,
                 drain_on_degrade: bool = True):
        if mode not in ("inproc", "process"):
            raise ValueError(f"mesh mode '{mode}' is not inproc|process")
        if durable and mode != "process":
            raise ValueError("durable=True requires mode='process' (the "
                             "fabric journal recovers real worker processes)")
        if trace_sample is not None and int(trace_sample) < 1:
            raise ValueError(f"bad trace_sample {trace_sample} (need >= 1)")
        self.capacity_per_host = int(capacity_per_host)
        self.policy = policy
        self.seed = seed
        # mode='process': every host is its OWN OS process (procmesh) —
        # same fabric ladder, dispatched over the control socket
        self.mode = mode
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.worker_failure_threshold = int(worker_failure_threshold)
        self.restart_max = int(restart_max)
        self.restart_base_s = float(restart_base_s)
        self.restart_window_s = float(restart_window_s)
        self.auto_restart = bool(auto_restart)
        self.worker_env = dict(worker_env or {})
        # None = snapshot only at migration/shutdown; N = persist the
        # tenant after every N applied chunks BEFORE the send returns (the
        # DCN snapshot_every_frames durability cadence: at 1, kill-recovery
        # is exactly-once; at None the loss bound is the chunks since the
        # last revision)
        self.snapshot_every_chunks = snapshot_every_chunks
        self.spill_capacity_frames = int(spill_capacity_frames)
        self.spill_policy = spill_policy
        self.adopt_retry_max = int(adopt_retry_max)
        self.playback = playback
        # durable control plane: every fabric mutation journals its intent
        # BEFORE actuating, so a SIGKILLed PARENT recovers — live workers
        # re-adopt without restore, dead ones restore from snapshots
        self.durable = bool(durable)
        self.journal_fsync = bool(journal_fsync)
        self.journal_checkpoint_every = int(journal_checkpoint_every)
        # cross-process trace stitching: 1-in-N ingress sampling on the
        # fabric's send path; sampled contexts ride the ingest op header
        # and the child's journey ships back on the flight tail. None =
        # tracing off (the default — sampling costs one counter per send)
        self.trace_sample = (int(trace_sample)
                             if trace_sample is not None else None)
        self.trace_ring = int(trace_ring)
        # federation freshness ceiling: a worker whose last good scrape is
        # older than this renders NO federated families (zombie expiry)
        self.metrics_stale_after_s = float(metrics_stale_after_s)
        # gray-failure surface (process mode): control-socket deadline base
        # (None = protocol default / SIDDHI_PROCMESH_IO_TIMEOUT_S env),
        # hedged-retry trigger fraction for idempotent ops, and the
        # latency-evidence ladder — N consecutive op timeouts while
        # heartbeats stay green = wedged (treated as down), a windowed op
        # p99 above degrade_factor x the fleet-median p99 (floored at
        # degrade_floor_s) = degraded, which drains the host's tenants
        # away when drain_on_degrade is set
        self.io_timeout_s = (float(io_timeout_s)
                             if io_timeout_s is not None else None)
        self.connect_timeout_s = (float(connect_timeout_s)
                                  if connect_timeout_s is not None else None)
        self.hedge_fraction = float(hedge_fraction)
        self.wedge_threshold = int(wedge_threshold)
        self.degrade_factor = float(degrade_factor)
        self.degrade_floor_s = float(degrade_floor_s)
        self.degrade_min_samples = int(degrade_min_samples)
        self.drain_on_degrade = bool(drain_on_degrade)


class MeshHost:
    """One engine shard of the mesh: an isolated ``SiddhiManager`` (own
    FleetManager → own shared-plan cache) bound to one device ordinal."""

    def __init__(self, index: int, capacity: int,
                 device: Optional[int] = None, playback: bool = True):
        from ..core.manager import SiddhiManager
        self.index = index
        self.capacity = capacity
        self.device = device
        self.playback = playback
        self.manager = SiddhiManager()
        self.runtimes: dict = {}        # tenant_id -> app runtime
        self.rows_in = 0                # routed rows (load evidence)
        self.reserved = 0               # in-flight adoption slots (capacity
        # admission is check-then-deploy; the reservation closes the race
        # between concurrent movers targeting the same destination)
        self.alive = True
        self.draining = False           # degrade drain: no NEW placements

    @property
    def free_slots(self) -> int:
        return self.capacity - len(self.runtimes) - self.reserved

    @property
    def slot(self) -> HostSlot:
        return HostSlot(self.index, self.capacity, self.device)

    def deploy(self, spec: TenantSpec):
        rt = self.manager.create_siddhi_app_runtime(
            spec.app_text, playback=self.playback)
        rt.start()
        self.runtimes[spec.tenant_id] = rt
        return rt

    def undeploy(self, tenant_id: str) -> None:
        rt = self.runtimes.pop(tenant_id, None)
        if rt is not None:
            rt.shutdown()
            self.manager.runtimes.pop(tenant_id, None)

    def compiled_programs(self) -> int:
        return self.manager.fleet.plan_cache.stats()["size"]

    def evidence(self) -> dict:
        """The capacity-scoring/rebalancing evidence for this host — the
        fleet tier's aggregate (:meth:`FleetManager.mesh_evidence`:
        events, lane packing, guard shed/eject pressure, violated SLO
        budgets) plus the host's own routing load. The same numbers the
        ``mesh.*`` metric families export."""
        return {
            "host": self.index, "device": self.device,
            "alive": self.alive,
            "tenants": len(self.runtimes),
            "capacity": self.capacity,
            "rows_in": self.rows_in,
            **self.manager.fleet.mesh_evidence(),
        }

    def kill(self) -> None:
        """Simulated SIGKILL: runtimes are DISCARDED, no flush, no
        hand-off — process memory is gone (``ProcMeshHost.kill`` is the
        real-process twin of this surface)."""
        self.runtimes.clear()
        # the manager registry too: a later close() must not "flush"
        # runtimes whose process memory this kill simulates losing
        self.manager.runtimes.clear()

    def close(self) -> None:
        self.alive = False
        self.manager.shutdown()
        self.runtimes.clear()


class _TenantState:
    """Fabric-side runtime state of one tenant: routing, the exactly-once
    seq/applied marks, and the migration spill queue."""

    __slots__ = ("spec", "gid", "host", "lock", "migrate_lock", "seq",
                 "applied", "spill", "migrating", "callbacks", "epoch",
                 "raw_hooks", "raw_streams")

    def __init__(self, spec: TenantSpec, gid: int, host: int, cfg: MeshConfig):
        self.spec = spec
        self.gid = gid                  # global tenant id → snapshot store key
        self.host = host                # LIVE owner (plan is the target)
        self.lock = threading.RLock()
        # admission guard for migrate(): one in-flight move per tenant —
        # a second mover (operator + rebalancer + SLO escalation can race)
        # must bounce, not interleave snapshot/undeploy/adopt
        self.migrate_lock = threading.Lock()
        self.seq = 0                    # last assigned chunk seq
        self.applied = 0                # last APPLIED chunk seq (dedup mark)
        self.epoch = 0                  # bumped per restore-from-revision
        self.spill = SpillQueue(cfg.spill_capacity_frames, cfg.spill_policy)
        self.migrating = False
        self.callbacks: list = []       # (stream_id, fn) — re-attached on move
        # durable sinks: fn([(epoch, idx, sid, ts, row), ...]) — re-armed
        # on every proxy (re)creation, replayed across a parent crash
        self.raw_hooks: list = []
        self.raw_streams: set = set()   # streams captured for raw hooks


class MeshFabric:
    """The mesh control plane: hosts, the plan, ingress routing, live
    migration, elasticity. One fabric per mesh."""

    def __init__(self, num_hosts: int, store_root: str,
                 config: Optional[MeshConfig] = None,
                 devices: Optional[list] = None):
        self.cfg = config or MeshConfig()
        if devices is None:
            devices = self._probe_devices(num_hosts)
        # the fabric's own control-plane ring (created before the hosts:
        # process-mode supervision records its spawn/restart decisions
        # here); migration decisions ALSO fan out to the involved tenant
        # apps' recorders (their operators read their own timelines)
        self.flight = FlightRecorder(app_name="mesh")
        # fabric-side tracer (host=0: ids mint in the parent namespace and
        # local journeys register as stitch targets, so child spans coming
        # back on the flight tail land on the SAME trace object)
        self.tracer = None
        if self.cfg.trace_sample is not None:
            from ..observability.tracing import PipelineTracer
            self.tracer = PipelineTracer(sample_n=self.cfg.trace_sample,
                                         ring_size=self.cfg.trace_ring,
                                         host=0)
        # durable control plane: the journal replays BEFORE anything is
        # spawned — worker give-up budgets and tenant ownership come out
        # of it, and the supervisor's adopt-or-spawn pass consumes them
        self.journal = None
        self._recovery: dict = {}       # parent-recovery stats (report())
        self._staged_outputs: dict = {}  # tid -> journaled undelivered rows
        self._resync_tids: list = []    # re-adopted tenants to re-snapshot
        jstate = None
        t0 = time.monotonic()
        if self.cfg.durable:
            from ..procmesh.journal import FabricJournal
            self.journal = FabricJournal(
                os.path.join(store_root, "journal"),
                fsync=self.cfg.journal_fsync)
            ckpt, tail = self.journal.replay()
            if ckpt is not None or tail:
                jstate = self._merge_journal(ckpt, tail)
        self.supervisor = None
        if self.cfg.mode == "process":
            # procmesh: one OS process per host, the fabric ladder
            # dispatching over control sockets (lazy import — inproc
            # meshes never pay the subprocess machinery)
            from ..procmesh.supervisor import (
                ProcMeshSupervisor,
                SupervisorConfig,
            )
            self.supervisor = ProcMeshSupervisor(
                num_hosts,
                SupervisorConfig(
                    heartbeat_interval_s=self.cfg.heartbeat_interval_s,
                    failure_threshold=self.cfg.worker_failure_threshold,
                    restart_base_s=self.cfg.restart_base_s,
                    restart_window_s=self.cfg.restart_window_s,
                    restart_max=self.cfg.restart_max,
                    auto_restart=self.cfg.auto_restart,
                    env=self.cfg.worker_env,
                    run_dir=(os.path.join(store_root, "run")
                             if self.cfg.durable else None),
                    io_timeout_s=self.cfg.io_timeout_s,
                    connect_timeout_s=self.cfg.connect_timeout_s,
                    hedge_fraction=self.cfg.hedge_fraction,
                    wedge_threshold=self.cfg.wedge_threshold,
                    degrade_factor=self.cfg.degrade_factor,
                    degrade_floor_s=self.cfg.degrade_floor_s,
                    degrade_min_samples=self.cfg.degrade_min_samples),
                flight=self.flight, playback=self.cfg.playback,
                journal=self.journal,
                worker_state=(jstate or {}).get("workers"))
            self.supervisor.on_failed = self.host_failed
            self.supervisor.on_restarted = self.host_restarted
            self.supervisor.on_escalation = self._slo_escalate
            self.supervisor.on_degraded = self.host_degraded
            self.supervisor.on_undegraded = self.host_undegraded
            self.hosts: dict = {
                i: self.supervisor.host(
                    i, self.cfg.capacity_per_host,
                    device=(devices[i] if i < len(devices) else None))
                for i in range(num_hosts)}
        else:
            self.hosts = {
                i: MeshHost(i, self.cfg.capacity_per_host,
                            device=(devices[i] if i < len(devices)
                                    else None),
                            playback=self.cfg.playback)
                for i in range(num_hosts)}
        self.store = LaneGroupSnapshotStore(store_root)
        self.policy = PlacementPolicy(self.cfg.policy, self.cfg.seed)
        self.plan = MeshPlan(policy=self.cfg.policy)
        self.tenants: dict = {}         # tenant_id -> _TenantState
        self._next_gid = 0
        self._lock = threading.RLock()  # hosts/plan/tenants maps
        self.migrations = 0
        self.migration_failures = 0
        self.recoveries = 0
        self.drains = 0                 # degrade-triggered host drains
        self.spilled_chunks = 0
        self.shed_chunks = 0            # spill overflow the policy DROPPED
        self.replayed_chunks = 0
        self.dup_chunks = 0
        self.plan_recomputes = 0
        self.chaos: Optional[Callable[[str], None]] = None  # test hook
        self._sm = None
        # windowed-load marks: rows_in at the last PLACEMENT-consuming
        # evidence read (cumulative shares would let an hour-old burst
        # repel placements forever)
        self._ev_last_rows: dict = {}
        if jstate is not None and jstate.get("tenants"):
            self._recover_parent(jstate, t0)
        if self.journal is not None:
            # recovery (or a clean boot) compacts the inherited tail away:
            # the next parent crash replays from this checkpoint
            self._journal_checkpoint()
        # liveness monitoring starts LAST: a death callback must never
        # observe a half-built fabric
        if self.supervisor is not None:
            self.supervisor.start_monitor()

    @staticmethod
    def _probe_devices(n: int) -> list:
        """Best-effort device binding: host i steps on jax device i of the
        mesh (the forced-host CPU mesh in tests/bench, chips on hardware).
        Without a live backend the binding stays None — placement and
        migration are device-agnostic."""
        try:
            import jax
            devs = jax.devices()
            return [devs[i % len(devs)].id for i in range(n)]
        except Exception:   # noqa: BLE001 — metadata only, never fatal
            return [None] * n

    def _site(self, site: str) -> None:
        if self.chaos is not None:
            self.chaos(site)

    def _crash(self, site: str) -> None:
        """``SIDDHI_CRASH_AT`` hook at an actuate boundary (armed for
        durable fabrics only — the journal is what makes a SIGKILL here
        recoverable; the journal-side boundaries fire inside
        :meth:`FabricJournal.append` itself)."""
        if self.journal is not None:
            from ..procmesh.journal import crash_point
            crash_point(site)

    def _journal(self, kind: str, **fields) -> int:
        if self.journal is None:
            return -1
        return self.journal.append(kind, **fields)

    def _wire_proxy(self, st: "_TenantState", rt) -> None:
        """Arm a (re)created worker proxy's durability taps: the epoch its
        outbox indices are namespaced under, the raw sink hooks, and the
        delivery-cursor journal callback (``delivered`` records are what a
        recovering parent reconciles child outboxes against)."""
        if self.journal is None or not getattr(rt, "procmesh_proxy", False):
            return
        rt.out_epoch = st.epoch
        rt.raw_hooks = list(st.raw_hooks)
        for sid in sorted(st.raw_streams):
            rt.subscribe(sid)           # idempotent on the child
        tid = st.spec.tenant_id
        rt.on_delivered = lambda idx, tid=tid, rt=rt: self._journal(
            "delivered", tenant=tid, epoch=rt.out_epoch, idx=idx)

    # -- deployment ----------------------------------------------------------
    def add_tenants(self, app_texts: list) -> MeshPlan:
        """Place + deploy a tenant population (placement sees the WHOLE
        batch, so shape locality packs globally). Tenant id = app name."""
        from ..compiler import parse as _parse
        specs = []
        with self._lock:
            for text in app_texts:
                app = _parse(text)
                tid = app.name()
                if tid in self.tenants:
                    raise ValueError(f"tenant '{tid}' already deployed")
                specs.append(TenantSpec(tid, text,
                                        shapes=shape_fingerprint(app)))
            all_specs = [t.spec for t in self.tenants.values()] + specs
            new_plan = self.policy.recompute(
                self.plan, all_specs,
                [h.slot for h in self.hosts.values() if h.alive],
                self.evidence(window=True))
            for spec in specs:
                host = new_plan.host_of(spec.tenant_id)
                st = _TenantState(spec, self._next_gid, host, self.cfg)
                self._next_gid += 1
                # INTENT FIRST: the deploy is in the journal before any
                # worker sees it — a parent crash in the gap re-resolves
                # to a (re)deploy on recovery, never a ghost tenant
                self._journal("deploy", tenant=spec.tenant_id, gid=st.gid,
                              host=host, app_text=spec.app_text)
                self.tenants[spec.tenant_id] = st
                rt = self.hosts[host].deploy(spec)
                self._crash("deploy.actuated")
                self._wire_proxy(st, rt)
                self._arm_slo_hook(rt)
            self.plan = new_plan
        return new_plan

    def remove_tenant(self, tenant_id: str) -> bool:
        """Undeploy one tenant fabric-wide (journaled before the worker
        op, so a recovering parent never resurrects it)."""
        with self._lock:
            st = self.tenants.get(tenant_id)
            if st is None:
                return False
            self._journal("undeploy", tenant=tenant_id)
            host = self.hosts.get(st.host)
            if host is not None and tenant_id in host.runtimes:
                host.undeploy(tenant_id)
            del self.tenants[tenant_id]
            self.plan.assignment.pop(tenant_id, None)
        return True

    def add_callback(self, tenant_id: str, stream_id: str, fn) -> None:
        """Attach an output callback that SURVIVES migration (re-attached
        on every deploy of the tenant)."""
        from ..core.stream import StreamCallback
        st = self.tenants[tenant_id]
        with st.lock:
            st.callbacks.append((stream_id, fn))
            rt = self.hosts[st.host].runtimes.get(tenant_id)
            if rt is not None:
                rt.add_callback(stream_id, StreamCallback(fn))

    def add_output_hook(self, tenant_id: str, fn, streams=()) -> None:
        """Durable-sink tap (process mode): ``fn`` receives raw outbox
        batches ``[(epoch, idx, sid, ts, row), ...]`` BEFORE the
        event-callback dispatch; ``streams`` names the output streams to
        capture (child-side capture arms per stream). Delivery is
        at-least-once across a parent crash (the dispatched-but-uncursored
        window re-ships on recovery) — sinks dedup by the ``(epoch,
        idx)`` identity, which is unique per emission across restores
        (``epoch`` bumps per incarnation)."""
        st = self.tenants[tenant_id]
        with st.lock:
            st.raw_hooks.append(fn)
            st.raw_streams.update(streams)
            rt = self.hosts[st.host].runtimes.get(tenant_id)
            if rt is not None and getattr(rt, "procmesh_proxy", False):
                rt.raw_hooks.append(fn)
                for sid in streams:
                    rt.subscribe(sid)

    def _reattach(self, rt, st: _TenantState) -> None:
        from ..core.stream import StreamCallback
        for stream_id, fn in st.callbacks:
            rt.add_callback(stream_id, StreamCallback(fn))

    def _arm_slo_hook(self, rt) -> None:
        """Give every SLO controller among this tenant's groups the
        cross-host rung: when its in-process ladder is exhausted it can
        decide ``mesh_replace`` and the fabric re-places the tenant.
        Takes the runtime DIRECTLY — during a migration the tenant's
        ``host`` field still points at the source until adoption
        completes, so a lookup through it would arm nothing."""
        for b in getattr(rt, "fleet_bridges", []):
            group = b.member.group
            if group is not None and group.slo is not None:
                group.slo.mesh_hook = self._slo_escalate

    def _slo_escalate(self, decision: dict) -> bool:
        """The SLO controller's ``mesh_replace`` actuator (its decision is
        already on the member's flight ring — the controller records before
        dispatching). Runs the move on a background thread: the evaluation
        slot rides tenant ingress and must never block on a migration."""
        tid = decision.get("tenant")
        st = self.tenants.get(tid)
        if st is None:
            return False
        dst = self._least_loaded_host(exclude=st.host)
        if dst is None:
            return False
        threading.Thread(
            target=self._migrate_logged, args=(tid, dst),
            kwargs={"reason": "slo:mesh_replace", "decided": decision},
            daemon=True).start()
        return True

    def _migrate_logged(self, tid: str, dst: int, **kw) -> None:
        try:
            self.migrate(tid, dst, **kw)
        except Exception:   # noqa: BLE001 — logged; the autopilot retries
            log.exception("mesh: slo-escalated migration of '%s' failed", tid)

    def _least_loaded_host(self, exclude: Optional[int] = None
                           ) -> Optional[int]:
        cands = [h for h in self.hosts.values()
                 if h.alive and h.index != exclude and h.free_slots > 0
                 and not getattr(h, "draining", False)]
        if not cands:
            return None
        # occupancy first (cumulative rows_in would bias against any host
        # that absorbed traffic once, forever), routed load as tie-break
        return min(cands, key=lambda h: (len(h.runtimes) + h.reserved,
                                         h.rows_in, h.index)).index

    # -- ingress routing (exactly-once) --------------------------------------
    def send(self, tenant_id: str, stream_id: str, rows: list,
             timestamps) -> None:
        """Route one per-tenant chunk to its owning host. Chunks get a
        per-tenant monotone seq; the apply path dedups (seq <= applied →
        already applied, ack again, apply nothing) so migration replays and
        kill-recovery replays stay exactly-once. A migrating (or
        dead-hosted) tenant's chunks spill in order — bounded by the
        spill policy: ``block`` (default) waits up to the queue's bounded
        window with NO tenant lock held (the replay drain needs it — the
        DCN ``_forward`` discipline), then force-admits (counted);
        ``shed``/``drop_oldest`` trade loss for memory, every dropped
        chunk counted in ``shed_chunks``/the queue's counters — loss is a
        visible policy choice, never silent."""
        j = self.journal
        if j is not None and \
                j.records_since_ckpt >= self.cfg.journal_checkpoint_every:
            # amortized compaction on the ingest path (no locks held):
            # replay cost after a parent crash stays bounded
            self._journal_checkpoint()
        st = self.tenants[tenant_id]
        host = self.hosts.get(st.host)
        if st.migrating or host is None or not host.alive:
            # cheap racy pre-check — the locked decision below is
            # authoritative; a miss costs one forced admit, counted
            st.spill.wait_for_space()
        with st.lock:
            st.seq += 1
            seq = st.seq
            host = self.hosts.get(st.host)
            # "runtime missing" covers the process-mode restart window: a
            # respawned worker is alive but EMPTY until recover_tenant
            # restores the tenant — its chunks spill like a dead host's
            if st.migrating or host is None or not host.alive \
                    or st.spec.tenant_id not in host.runtimes:
                self._spill_locked(st, seq, stream_id, rows, timestamps)
                return
            try:
                # 1-in-N ingress sampling happens HERE, on the direct-apply
                # path only: a spilled chunk replays without a context (its
                # trace simply records no dispatch), and the replay/recovery
                # applies never re-sample — exactly-once spans ride on the
                # seq dedup downstream
                tr = (self.tracer.maybe_trace(stream_id)
                      if self.tracer is not None else None)
                self._apply_locked(st, seq, stream_id, rows, timestamps,
                                   trace=tr)
            except ConnectionError:
                # the worker process died under this very chunk (procmesh
                # WorkerDown is a ConnectionError): the chunk spills and
                # the recovery replay applies it through the dedup mark
                self._spill_locked(st, seq, stream_id, rows, timestamps)

    def _spill_locked(self, st: "_TenantState", seq: int, stream_id: str,
                      rows: list, timestamps) -> None:
        if st.spill.append((seq, stream_id, rows, list(timestamps)),
                           len(rows)):
            self.spilled_chunks += 1
        else:
            self.shed_chunks += 1        # policy chose to drop: counted

    def _apply_locked(self, st: _TenantState, seq: int, stream_id: str,
                      rows: list, timestamps, trace=None) -> bool:
        """Apply one chunk under the tenant lock through the dedup mark;
        returns True when the chunk actually applied. With a snapshot
        cadence armed, the tenant persists BEFORE the ack (return) — the
        acked-chunk-is-durable contract kill-recovery leans on. ``trace``
        (a fabric-tracer Trace) rides the ingest header as a packed
        context; the child adopts it only on actual apply."""
        if seq <= st.applied:
            self.dup_chunks += 1
            return False                 # replay of an applied chunk: dedup
        host = self.hosts[st.host]
        rt = host.runtimes[st.spec.tenant_id]
        if getattr(rt, "procmesh_proxy", False):
            # process mode: the chunk crosses the control socket (child
            # dedups by seq — the retried-op side of exactly-once) and its
            # OUTPUT events come back buffered; they dispatch parent-side
            # only after the durability step below, so a child SIGKILLed
            # between apply and ack re-applies from the restored pre-chunk
            # state and every output is delivered exactly once
            trace_hex = None
            if trace is not None and self.tracer is not None:
                trace_hex = self.tracer.context_of(trace).pack().hex()
            t0 = time.perf_counter_ns()
            rt.send_chunk(seq, stream_id, [list(r) for r in rows],
                          list(timestamps), trace=trace_hex)
            if trace is not None:
                # the parent-side dispatch span: socket round-trip to the
                # child's applied ack (the child's own transit span covers
                # dispatch wall-clock → apply, including retry delay)
                trace.add_span("procmesh", f"dispatch:h{st.host}",
                               time.perf_counter_ns() - t0, len(rows))
            # applied on the child, not yet cursored in the journal: a
            # parent crash here re-adopts the live child and takes ITS
            # applied mark as authoritative (resync)
            self._crash("ingest.applied")
            host.rows_in += len(rows)
            prev, st.applied = st.applied, seq
            n = self.cfg.snapshot_every_chunks
            if n and seq % n == 0:
                try:
                    self._save_tenant_locked(st, rt)
                except Exception:
                    # not durable: the applied mark rolls back so the
                    # spill/recovery replay re-applies this chunk
                    st.applied = prev
                    raise
            rt.deliver_pending()
            return True
        rt.input_handler(stream_id).send_rows(
            [list(r) for r in rows], list(timestamps))
        host.rows_in += len(rows)
        st.applied = seq
        n = self.cfg.snapshot_every_chunks
        if n and seq % n == 0:
            self._save_tenant_locked(st, rt)
        return True

    def _save_tenant_locked(self, st: _TenantState, rt) -> int:
        """Persist the tenant's state bytes (flushed first — staged fleet
        rows resolve before the walk) as a snapshot-store blob revision
        keyed by its global id, with the applied mark riding the
        revision's dedup table — restore resumes the exactly-once window
        exactly."""
        rt.flush_host()
        rev = self.store.save_blob(st.gid, rt.snapshot(),
                                   {0: (st.epoch, st.applied)})
        if getattr(rt, "procmesh_proxy", False):
            # cursor AFTER the revision landed, BEFORE delivery: a parent
            # crash in either gap recovers — the journaled undelivered
            # outputs are the only copy once the child dies, so they ride
            # the cursor record (staged replay re-ships them)
            if self.journal is not None:
                self._journal("cursor", tenant=st.spec.tenant_id,
                              applied=st.applied, epoch=st.epoch,
                              outputs=[[rt.out_epoch] + e
                                       for e in rt.pending_outputs()])
            # flush-resolved outputs buffered on the proxy are covered by
            # the revision that just landed — deliver before any teardown
            # (migration undeploys the source right after saving)
            rt.deliver_pending()
        return rev

    # -- live migration ------------------------------------------------------
    def migrate(self, tenant_id: str, dst: int, reason: str = "operator",
                decided: Optional[dict] = None) -> bool:
        """Move one tenant between hosts under sustained ingest. The
        decision (with its evidence) hits the flight recorder(s) BEFORE any
        state moves; the data path is spill → flush+snapshot → revision
        durable → restore on dst → adoption ack (retried) → owner re-point
        → in-order spill replay through the dedup'd apply. One in-flight
        move per tenant: a concurrent mover (operator, rebalancer, SLO
        escalation) returns False instead of interleaving."""
        st = self.tenants[tenant_id]
        if not st.migrate_lock.acquire(blocking=False):
            log.info("mesh: migration of '%s' already in flight", tenant_id)
            return False
        try:
            return self._migrate_admitted(st, tenant_id, dst, reason,
                                          decided)
        finally:
            st.migrate_lock.release()

    def _migrate_admitted(self, st: "_TenantState", tenant_id: str,
                          dst: int, reason: str,
                          decided: Optional[dict]) -> bool:
        with self._lock:
            src = st.host
            dst_host = self.hosts.get(dst)
            if dst_host is None or not dst_host.alive:
                raise ValueError(f"mesh host {dst} is not alive")
            if src == dst:
                return False
            if dst_host.free_slots <= 0:
                raise ValueError(f"mesh host {dst} is at capacity")
            # RESERVE the slot under the lock: concurrent movers of
            # DIFFERENT tenants to the same destination must not both
            # pass a check-then-deploy capacity test
            dst_host.reserved += 1
        try:
            return self._migrate_reserved(st, tenant_id, src, dst, reason,
                                          decided)
        finally:
            with self._lock:
                dst_host.reserved = max(0, dst_host.reserved - 1)

    def _migrate_reserved(self, st: "_TenantState", tenant_id: str,
                          src: int, dst: int, reason: str,
                          decided: Optional[dict]) -> bool:
        # EVIDENCE FIRST: the decision lands on the fabric ring and the
        # tenant's own app timeline before the knob moves
        self._record_move(tenant_id, src, dst, reason, decided)
        src_rt = self.hosts[src].runtimes.get(tenant_id)
        try:
            # intent → committed two-record protocol: a parent crash
            # anywhere between these resolves to exactly one owner (src —
            # recovery scrubs any half-adopted dst copy and restores from
            # the pre-undeploy revision)
            self._journal("migrate_intent", tenant=tenant_id, src=src,
                          dst=dst)
            with st.lock:
                st.migrating = True      # fresh chunks spill from here on
            self._site("mesh.migrate.freeze")
            # quiesce + snapshot on the source (senders spill, not block)
            if src_rt is not None:
                self._save_tenant_migration(st, src_rt)
            self._site("mesh.migrate.snapshot")
            if src_rt is not None:
                self.hosts[src].undeploy(tenant_id)
            self._site("mesh.migrate.src_down")
            self._adopt(st, dst)
            self._crash("migrate.adopted")
            with st.lock:
                st.host = dst
                # the dst child is a fresh incarnation whose outbox indices
                # restart at 0: without an epoch bump its outputs would
                # collide with the pre-move (epoch, idx) identities and an
                # idempotent sink would drop them as duplicates
                st.epoch += 1
                new_rt = self.hosts[dst].runtimes.get(tenant_id)
                if new_rt is not None:
                    self._wire_proxy(st, new_rt)
                slot = self.plan.assignment.get(tenant_id)
                if slot is not None:
                    from .plan import MeshSlot
                    self.plan.assignment[tenant_id] = MeshSlot(
                        dst, slot.shape, self.hosts[dst].device)
                self._journal("migrate_commit", tenant=tenant_id, dst=dst,
                              applied=st.applied, epoch=st.epoch)
                st.migrating = False
                self._replay_spill_locked(st)
            self.migrations += 1
            self.flight.record("mesh", "migrated", site=f"tenant:{tenant_id}",
                               detail={"src": src, "dst": dst})
            return True
        except Exception:
            self.migration_failures += 1
            raise

    def _save_tenant_migration(self, st: _TenantState, rt) -> int:
        with st.lock:
            return self._save_tenant_locked(st, rt)

    def _adopt(self, st: _TenantState, dst: int) -> None:
        """Deploy + restore the tenant on ``dst`` from its latest revision
        and confirm the adoption. A lost ack retries against the SAME
        restored runtime — the restore is idempotent (re-restore from the
        same revision) and the seq dedup makes the replay side safe, the
        ``K_ADOPT`` two-attempt discipline."""
        last_err = None
        for attempt in range(self.cfg.adopt_retry_max):
            try:
                self._restore_on(st, dst)
                self._site("mesh.migrate.adopt_ack")   # lost-ack chaos site
                return
            except MeshChaosFault as e:
                last_err = e            # ack lost: retry the hand-off
                continue
        raise last_err if last_err is not None else \
            RuntimeError("adoption failed")

    def _restore_on(self, st: _TenantState, dst: int) -> None:
        tid = st.spec.tenant_id
        host = self.hosts[dst]
        rt = host.runtimes.get(tid)
        if rt is None:
            rt = host.deploy(st.spec)
            self._reattach(rt, st)
        snap = self.store.latest_blob(st.gid)
        if snap is not None:
            mark = snap["dedup"].get(0)
            if getattr(rt, "procmesh_proxy", False):
                # the worker's ingest dedup mark rides the restore op so
                # the child resumes the exactly-once window exactly
                rt.restore(snap["blob"],
                           applied=int(mark[1]) if mark else 0)
            else:
                rt.restore(snap["blob"])
            if mark is not None:
                # the saved mark never LOWERS the live incarnation (a
                # recovery's bump must survive restoring a pre-bump mark)
                st.epoch = max(st.epoch, int(mark[0]))
                st.applied = int(mark[1])
        self._wire_proxy(st, rt)
        self._arm_slo_hook(rt)

    def _replay_spill_locked(self, st: _TenantState) -> None:
        """Drain the tenant's spill in order through the dedup'd apply —
        chunks the source applied before the snapshot dedup away, the rest
        apply on the new owner exactly once."""
        while True:
            item = st.spill.pop_front()
            if item is None:
                return
            (seq, sid, rows, tss), n = item
            try:
                self._apply_locked(st, seq, sid, rows, tss)
            except Exception:
                st.spill.push_front(item)   # never lose a popped chunk
                raise
            st.spill.mark_replayed(n)
            self.replayed_chunks += 1

    def _record_move(self, tenant_id: str, src: int, dst: int, reason: str,
                     decided: Optional[dict]) -> None:
        detail = {"tenant": tenant_id, "src": src, "dst": dst,
                  "reason": reason}
        if decided:
            detail["decided_by"] = {
                k: v for k, v in decided.items()
                if isinstance(v, (str, int, float, bool, type(None)))}
        self.flight.record("mesh", "decision:migrate_tenant",
                           site=f"tenant:{tenant_id}", detail=detail)
        rt = self.hosts[src].runtimes.get(tenant_id) \
            if src in self.hosts else None
        fl = getattr(getattr(rt, "ctx", None), "flight", None)
        if fl is not None:
            fl.record("mesh", "decision:migrate_tenant",
                      site=f"tenant:{tenant_id}", detail=detail)

    # -- crash / recovery ----------------------------------------------------
    def kill_host(self, host: int) -> list:
        """Host SIGKILL: its runtimes are DISCARDED (no flush, no
        hand-off). In-process mode simulates the loss
        (:meth:`MeshHost.kill`); process mode delivers an ACTUAL signal 9
        to the worker (:meth:`ProcMeshHost.kill`) — same fabric path
        either way. Its tenants' fresh chunks spill until recovery;
        returns the orphaned tenant ids."""
        with self._lock:
            h = self.hosts.get(host)
            if h is None:
                return []
            h.alive = False
            orphans = sorted(h.runtimes)
            # EVIDENCE FIRST: the kill is on the ring before the signal
            self.flight.record("mesh", "host_killed", site=f"host:{host}",
                               detail={"tenants": orphans,
                                       "mode": self.cfg.mode})
            h.kill()                     # state is gone, like the process
            return orphans

    def host_failed(self, index: int) -> list:
        """Supervisor death callback (process mode): the worker's proxies
        are stale the instant the process dies — drop them so no caller
        dispatches into a dead incarnation. Tenants spill until recovery;
        returns the orphaned tenant ids."""
        with self._lock:
            h = self.hosts.get(index)
            if h is None:
                return []
            h.alive = False
            orphans = sorted(h.runtimes)
            self.flight.record("mesh", "host_failed", site=f"host:{index}",
                               detail={"tenants": orphans})
            if hasattr(h, "drop_runtimes"):
                h.drop_runtimes()
            else:
                h.kill()
            return orphans

    def host_degraded(self, index: int) -> None:
        """Supervisor degrade callback (latency-evidence ladder): the
        worker answers, but its windowed op p99 is a fleet-relative
        outlier. Proactive containment, not execution: mark the host
        draining (no NEW placements land on it) and migrate its tenants
        away. Runs the moves on a background thread — the monitor sweep
        that classified the outlier must never block on a migration
        (the ``_slo_escalate`` discipline)."""
        if not self.cfg.drain_on_degrade:
            return
        threading.Thread(target=self.drain_host, args=(index,),
                         kwargs={"reason": "degraded"}, daemon=True).start()

    def host_undegraded(self, index: int) -> None:
        """Degrade recovery (hysteresis rung): the host takes NEW
        placements again. Tenants already moved off stay where they
        are — re-spreading is the rebalancer's call, not the ladder's."""
        with self._lock:
            h = self.hosts.get(index)
            if h is None or not getattr(h, "draining", False):
                return
            h.draining = False
            self.flight.record("mesh", "host_undrained",
                               site=f"host:{index}")

    def drain_host(self, index: int, reason: str = "operator") -> int:
        """Drain actuator: record the decision, fence the host from new
        placements, then migrate every tenant it owns to the least-loaded
        non-draining peer. EVIDENCE FIRST — the ``decision:drain_host``
        entry is on the ring BEFORE ``draining`` flips and before any
        tenant moves (the ``mesh_replace`` record-before-actuate
        discipline). Returns the number of tenants moved."""
        with self._lock:
            h = self.hosts.get(index)
            if h is None or not h.alive:
                return 0
            tenants = sorted(h.runtimes)
            self.flight.record("mesh", "decision:drain_host",
                               site=f"host:{index}",
                               detail={"reason": reason,
                                       "tenants": tenants})
            h.draining = True
            self.drains += 1
        moved = 0
        for tid in tenants:
            st = self.tenants.get(tid)
            if st is None or st.host != index:
                continue
            dst = self._least_loaded_host(exclude=index)
            if dst is None:
                # nowhere to put it — the tenant stays; the fence still
                # keeps NEW work off the sick host, which is the point
                log.warning("mesh: drain of host %d has no destination "
                            "for '%s'", index, tid)
                continue
            try:
                self.migrate(tid, dst, reason=f"drain:{reason}")
                moved += 1
            except Exception:   # noqa: BLE001 — best-effort drain; the
                # tenant stays on the draining host, still served
                log.exception("mesh: drain migration of '%s' off host %d "
                              "failed", tid, index)
        return moved

    def host_restarted(self, index: int) -> int:
        """Supervisor restart callback: the respawned worker is ALIVE and
        EMPTY — replay the fabric's own recovery ladder
        (:meth:`recover_tenant`) for every tenant the dead incarnation
        owned, exactly like the simulated-chaos tests drive it by hand.
        Returns the number of tenants recovered."""
        with self._lock:
            h = self.hosts.get(index)
            if h is None:
                return 0
            h.alive = True
            # a fresh incarnation starts clean: whatever latency evidence
            # condemned the old process died with it
            h.draining = False
            self.flight.record("mesh", "host_restarted",
                               site=f"host:{index}")
            if self._sm is not None and hasattr(h, "register_child_metrics"):
                # fresh incarnation → fresh child gauge families (the old
                # generation's were torn down with the process)
                h.register_child_metrics(self._sm)
            orphans = [tid for tid, st in self.tenants.items()
                       if st.host == index
                       and tid not in h.runtimes
                       and not st.migrating]
        recovered = 0
        for tid in orphans:
            try:
                # back onto the respawned (empty) worker: its state
                # restores from the snapshot store, its spill replays
                self.recover_tenant(tid, index)
                recovered += 1
            except Exception:   # noqa: BLE001 — best-effort heal; the
                # tenant keeps spilling and an operator recover still works
                log.exception("mesh: auto-recovery of '%s' after worker %d "
                              "restart failed", tid, index)
        return recovered

    def recover_tenant(self, tenant_id: str,
                       dst: Optional[int] = None) -> int:
        """Re-place one orphaned tenant from its latest snapshot revision
        (restore → dedup mark resumes → spill replays in order). With
        ``snapshot_every_chunks=1`` this is exactly-once; at a looser
        cadence the loss bound is the chunks applied since the last
        revision (the DCN ``<= N-1`` frames contract). Shares the
        per-tenant admission lock with :meth:`migrate` — a recovery
        racing an in-flight move of the same tenant waits for it to
        finish or unwind instead of interleaving restores."""
        st = self.tenants[tenant_id]
        with st.migrate_lock:
            return self._recover_admitted(st, tenant_id, dst)

    def _recover_admitted(self, st: "_TenantState", tenant_id: str,
                          dst: Optional[int]) -> int:
        if dst is None:
            dst = self._least_loaded_host(exclude=st.host)
        if dst is None:
            raise ValueError("no live host with capacity to recover onto")
        self.flight.record("mesh", "decision:recover_tenant",
                           site=f"tenant:{tenant_id}",
                           detail={"dst": dst, "from": st.host})
        self._journal("recover", tenant=tenant_id, dst=dst)
        with st.lock:
            self._restore_on(st, dst)
            # incarnation bump AFTER the restore (which re-reads the saved
            # mark — bumping first would be silently overwritten and the
            # counter would never advance); the next snapshot persists it
            st.epoch += 1
            st.host = dst
            st.migrating = False
            slot = self.plan.assignment.get(tenant_id)
            if slot is not None:
                from .plan import MeshSlot
                self.plan.assignment[tenant_id] = MeshSlot(
                    dst, slot.shape, self.hosts[dst].device)
            rt = self.hosts[dst].runtimes.get(tenant_id)
            if rt is not None:
                self._wire_proxy(st, rt)    # fresh incarnation, fresh epoch
            self._journal("cursor", tenant=tenant_id, applied=st.applied,
                          epoch=st.epoch)
            self._replay_spill_locked(st)
        self.recoveries += 1
        return dst

    # -- parent recovery (durable control plane) -----------------------------
    @staticmethod
    def _merge_journal(ckpt: Optional[dict], tail: list) -> dict:
        """Fold a checkpoint plus its journal tail into the recovered
        control-plane state: ``{next_gid, tenants, workers, records}``.
        Per-tenant: ``host`` (owner), ``applied``/``epoch`` (the
        exactly-once window), ``delivered`` (the ``(epoch, idx)`` delivery
        high-water), ``outputs`` (journaled undelivered outbox entries —
        the only copy once a child dies) and ``intent`` (an uncommitted
        migration, resolved to the src owner)."""
        state = {"next_gid": 0, "tenants": {}, "workers": {}, "records": 0}
        if ckpt:
            state["next_gid"] = int(ckpt.get("next_gid", 0))
            for tid, t in (ckpt.get("tenants") or {}).items():
                state["tenants"][tid] = dict(t)
            for w, s in (ckpt.get("workers") or {}).items():
                state["workers"][int(w)] = dict(s)
        ts = state["tenants"]
        for rec in tail:
            state["records"] += 1
            k = rec.get("k")
            if k == "deploy":
                ts[rec["tenant"]] = {
                    "app_text": rec["app_text"], "gid": int(rec["gid"]),
                    "host": int(rec["host"]), "applied": 0, "epoch": 0,
                    "delivered": [-1, -1], "outputs": [], "intent": None}
                state["next_gid"] = max(state["next_gid"],
                                        int(rec["gid"]) + 1)
            elif k == "undeploy":
                ts.pop(rec["tenant"], None)
            elif k == "cursor":
                t = ts.get(rec["tenant"])
                if t is not None:
                    t["applied"] = int(rec["applied"])
                    t["epoch"] = int(rec["epoch"])
                    if "outputs" in rec:
                        t["outputs"] = rec["outputs"]
            elif k == "delivered":
                t = ts.get(rec["tenant"])
                if t is not None:
                    cur = tuple(int(x) for x in
                                (t.get("delivered") or (-1, -1)))
                    new = (int(rec["epoch"]), int(rec["idx"]))
                    if new > cur:
                        t["delivered"] = list(new)
            elif k == "migrate_intent":
                t = ts.get(rec["tenant"])
                if t is not None:
                    t["intent"] = {"src": int(rec["src"]),
                                   "dst": int(rec["dst"])}
            elif k == "migrate_commit":
                t = ts.get(rec["tenant"])
                if t is not None:
                    t["host"] = int(rec["dst"])
                    t["applied"] = int(rec.get("applied", t["applied"]))
                    t["epoch"] = int(rec.get("epoch", t["epoch"]))
                    t["intent"] = None
            elif k == "recover":
                t = ts.get(rec["tenant"])
                if t is not None:
                    t["host"] = int(rec["dst"])
            elif k == "worker_restart":
                w = state["workers"].setdefault(
                    int(rec["worker"]),
                    {"restarts": 0, "gave_up": False, "attempt_ages_s": []})
                w["restarts"] = int(w.get("restarts", 0)) + 1
                w["attempt_ages_s"] = list(rec.get("attempt_ages_s", ()))
            elif k == "worker_gave_up":
                w = state["workers"].setdefault(
                    int(rec["worker"]),
                    {"restarts": 0, "gave_up": False, "attempt_ages_s": []})
                w["gave_up"] = True
        return state

    def _recover_parent(self, state: dict, t0: float) -> None:
        """Rebuild the control plane after a PARENT crash (the journal's
        raison d'être): workers the supervisor re-adopted keep their live
        tenants WITHOUT restore — a resync op reconciles their outbox
        cursor against the journaled delivery cursor and their applied
        mark is authoritative; tenants on dead/respawned workers flow
        through the existing snapshot-restore + spill-replay ladder, with
        journaled-but-undelivered outputs staged for
        :meth:`resume_output_delivery`."""
        from ..compiler import parse as _parse
        sup = self.supervisor
        stats = {
            "readopted_workers": sum(
                1 for h in sup.handles.values() if h.adopted),
            "restored_workers": sum(
                1 for h in sup.handles.values()
                if not h.adopted and not h.gave_up),
            "readopted_tenants": 0, "restored_tenants": 0,
            "journal_records_replayed": int(state.get("records", 0)),
            "recover_s": 0.0,
        }
        # EVIDENCE FIRST: the recovery decision is on the ring before any
        # worker op moves state
        self.flight.record(
            "procmesh", "decision:parent_recovery", site="fabric",
            detail={"tenants": len(state.get("tenants", {})),
                    **{k: stats[k] for k in (
                        "readopted_workers", "restored_workers",
                        "journal_records_replayed")}})
        self._next_gid = max(self._next_gid, int(state.get("next_gid", 0)))
        for tid, t in sorted(state.get("tenants", {}).items()):
            try:
                if self._recover_tenant_record(tid, t, _parse):
                    stats["readopted_tenants"] += 1
                else:
                    stats["restored_tenants"] += 1
            except Exception:   # noqa: BLE001 — one tenant's turmoil must
                # not strand the rest of the fleet in __init__
                log.exception("mesh: parent recovery of tenant '%s' failed",
                              tid)
        stats["recover_s"] = round(time.monotonic() - t0, 6)
        self._recovery = stats
        self.flight.record("procmesh", "parent_recovered", site="fabric",
                           detail=dict(stats))

    def _recover_tenant_record(self, tid: str, t: dict, _parse) -> bool:
        """Recover ONE journaled tenant; True when re-adopted live (no
        restore), False when restored from the snapshot store."""
        from .plan import MeshSlot
        spec = TenantSpec(tid, t["app_text"],
                          shapes=shape_fingerprint(_parse(t["app_text"])))
        st = _TenantState(spec, int(t["gid"]), int(t["host"]), self.cfg)
        st.applied = int(t.get("applied", 0))
        st.epoch = int(t.get("epoch", 0))
        st.seq = st.applied             # the feeder resumes from applied
        self.tenants[tid] = st
        delivered = tuple(int(x) for x in (t.get("delivered") or (-1, -1)))
        intent = t.get("intent")
        if intent:
            # intent without commit: the move never happened — exactly one
            # owner (src); scrub any half-adopted dst copy first
            st.host = int(intent["src"])
            self._scrub_dst_copy(spec, int(intent["dst"]))
        host = self.hosts.get(st.host)
        readopted = False
        if host is not None and \
                getattr(getattr(host, "handle", None), "adopted", False):
            readopted = self._readopt_tenant(st, host, delivered)
        if not readopted:
            # dead, respawned-empty, or journaled-but-never-actuated: the
            # existing restore ladder (snapshot store + dedup mark + epoch
            # bump so the fresh incarnation's outbox indices never collide)
            dst = st.host if (host is not None and host.alive
                              and not getattr(getattr(host, "handle", None),
                                              "gave_up", False)) \
                else self._least_loaded_host(exclude=st.host)
            if dst is None:
                raise ValueError(f"no live host to restore '{tid}' onto")
            with st.lock:
                self._restore_on(st, dst)
                st.epoch += 1
                st.host = dst
                st.seq = st.applied
                rt = self.hosts[dst].runtimes.get(tid)
                if rt is not None:
                    self._wire_proxy(st, rt)
                self._journal("cursor", tenant=tid, applied=st.applied,
                              epoch=st.epoch)
            # the dead child's outbox died with it: the journaled
            # undelivered outputs are the only copy — stage past the
            # delivery high-water for resume_output_delivery()
            staged = [list(o) for o in t.get("outputs", ())
                      if (int(o[0]), int(o[1])) > delivered]
            if staged:
                self._staged_outputs[tid] = staged
            self.recoveries += 1
        self.plan.assignment[tid] = MeshSlot(
            st.host, spec.primary_shape,
            getattr(self.hosts.get(st.host), "device", None))
        return readopted

    def _readopt_tenant(self, st: "_TenantState", host,
                        delivered: tuple) -> bool:
        """Re-adopt a live child's tenant without restore: attach a fresh
        proxy, resync its outbox against the journaled delivery cursor,
        and take the child's applied mark as authoritative (it may have
        applied chunks whose journal cursor never landed)."""
        tid = st.spec.tenant_id
        ack = delivered[1] if delivered[0] == st.epoch else -1
        rt = host.adopt_runtime(st.spec)
        try:
            rh = rt.resync(ack)
        except (ConnectionError, RuntimeError):
            rh = {"present": False}
        if not rh.get("present"):
            # the child does not host it (a deploy journaled but never
            # actuated, or an undeploy raced the crash): fall through to
            # the restore path, which (re)deploys fresh
            host.runtimes.pop(tid, None)
            host._specs.pop(tid, None)
            return False
        st.applied = max(st.applied, int(rh.get("applied", 0)))
        st.seq = st.applied
        self._wire_proxy(st, rt)
        # the snapshot store may trail the child's live applied mark —
        # re-snapshot once delivery hooks are back (resume_output_delivery)
        self._resync_tids.append(tid)
        self.flight.record("procmesh", "tenant_readopt",
                           site=f"tenant:{tid}",
                           detail={"host": host.index,
                                   "applied": st.applied, "ack": ack})
        return True

    def _scrub_dst_copy(self, spec: TenantSpec, dst: int) -> None:
        """Uncommitted-migration cleanup: if the move's target child is
        live (re-adopted) and holds a half-adopted copy, undeploy it — the
        journal says the move never committed, so src is the one owner."""
        h = self.hosts.get(dst)
        if h is None or not getattr(getattr(h, "handle", None),
                                    "adopted", False):
            return
        try:
            h.adopt_runtime(spec)
            h.undeploy(spec.tenant_id)   # tolerant child op: no-op if absent
        except (ConnectionError, RuntimeError):
            log.warning("mesh: could not scrub half-adopted copy of '%s' "
                        "on host %d", spec.tenant_id, dst)

    def resume_output_delivery(self) -> dict:
        """Second half of parent recovery, called once the caller has
        re-attached its callbacks and output hooks (a fresh parent process
        has none at construction): replays journal-staged outputs from
        dead incarnations (at-least-once — sinks dedup by ``(epoch,
        idx)``), then re-snapshots re-adopted tenants so the store catches
        up to the child's authoritative applied mark (their resync'd
        outbox tails dispatch through the normal delivery path here)."""
        from ..core.event import Event
        out = {"replayed_outputs": 0, "resnapshotted": 0}
        staged, self._staged_outputs = self._staged_outputs, {}
        for tid in sorted(staged):
            st = self.tenants.get(tid)
            entries = staged[tid]
            if st is None or not entries:
                continue
            with st.lock:
                for hook in st.raw_hooks:
                    hook([tuple(e) for e in entries])
                i = 0
                while i < len(entries):
                    sid = entries[i][2]
                    j = i
                    while j < len(entries) and entries[j][2] == sid:
                        j += 1
                    evs = [Event(e[3], e[4]) for e in entries[i:j]]
                    for cb_sid, fn in st.callbacks:
                        if cb_sid == sid:
                            fn(evs)
                    i = j
                last = entries[-1]
                self._journal("delivered", tenant=tid,
                              epoch=int(last[0]), idx=int(last[1]))
                out["replayed_outputs"] += len(entries)
        resync, self._resync_tids = self._resync_tids, []
        for tid in resync:
            st = self.tenants.get(tid)
            if st is None:
                continue
            with st.lock:
                rt = self.hosts[st.host].runtimes.get(tid)
                if rt is not None:
                    self._save_tenant_locked(st, rt)
                    out["resnapshotted"] += 1
        return out

    def _journal_checkpoint(self) -> None:
        """Fold the whole control plane into one ``ckpt`` record and
        truncate the acked segments behind it (the journal's compaction
        contract — replay cost stays bounded by
        ``journal_checkpoint_every``)."""
        if self.journal is None:
            return
        with self._lock:
            tenants = {}
            for tid, st in self.tenants.items():
                h = self.hosts.get(st.host)
                rt = h.runtimes.get(tid) if h is not None else None
                rec = {"app_text": st.spec.app_text, "gid": st.gid,
                       "host": st.host, "applied": st.applied,
                       "epoch": st.epoch, "intent": None,
                       "delivered": [st.epoch, -1], "outputs": []}
                if rt is not None and getattr(rt, "procmesh_proxy", False):
                    rec["delivered"] = [rt.out_epoch, rt.delivered]
                    rec["outputs"] = [[rt.out_epoch] + e
                                      for e in rt.pending_outputs()]
                staged = self._staged_outputs.get(tid)
                if staged:
                    # recovered-but-not-yet-replayed outputs must survive
                    # another crash: carry them (pre-filtered, so a reset
                    # high-water replays exactly this set)
                    rec["delivered"] = [-1, -1]
                    rec["outputs"] = [list(o) for o in staged]
                # a checkpoint racing a live migration journals the
                # still-src owner with no intent: a crash before the
                # commit record rolls the move back (restore on src)
                tenants[tid] = rec
            state = {"next_gid": self._next_gid, "tenants": tenants,
                     "workers": (self.supervisor.worker_state()
                                 if self.supervisor is not None else {})}
        self.journal.checkpoint(state)

    # -- elasticity ----------------------------------------------------------
    def add_host(self, capacity: Optional[int] = None) -> int:
        """Host join: a new shard enters, the plan recomputes (sticky), and
        the diff applies as bulk migrations onto the newcomer."""
        if self.supervisor is not None:
            # the process fleet is sized at boot (the supervisor owns the
            # worker population); growing it live is a follow-up
            raise ValueError(
                "process-mode mesh has a fixed worker fleet; size it at "
                "MeshFabric construction")
        with self._lock:
            idx = (max(self.hosts) + 1) if self.hosts else 0
            dev = self._probe_devices(idx + 1)[-1]
            self.hosts[idx] = MeshHost(
                idx, capacity or self.cfg.capacity_per_host, device=dev,
                playback=self.cfg.playback)
            if self._sm is not None:      # metrics track elasticity live
                self._register_host_metrics(self._sm, self.hosts[idx])
        self.flight.record("mesh", "host_join", site=f"host:{idx}")
        # balanced recompute: without the retain cap, sticky slots would
        # leave the newcomer empty — a join must trigger bulk adoption
        self._apply_recompute(balance=True)
        return idx

    def remove_host(self, host: int) -> int:
        """Graceful host leave: recompute the plan without it and bulk-
        migrate its tenants out (each move is a full live migration —
        spill/snapshot/restore/replay), then close the shard. Returns the
        number of tenants moved."""
        if self.supervisor is not None:
            raise ValueError(
                "process-mode mesh has a fixed worker fleet; size it at "
                "MeshFabric construction")
        with self._lock:
            h = self.hosts.get(host)
            if h is None:
                return 0
            h.alive = False              # placement stops targeting it
        self.flight.record("mesh", "host_leave", site=f"host:{host}")
        moved = self._apply_recompute()
        with self._lock:
            self.hosts[host].close()
            del self.hosts[host]
            if self._sm is not None:      # no zombie gauges on a closed
                self._sm.unregister(f"mesh.h{host}.")   # MeshHost closure
        return moved

    def _apply_recompute(self, balance: bool = False) -> int:
        """Plan recompute + bulk adoption: every move in the diff runs as a
        live migration (the decision trail names the elasticity event)."""
        with self._lock:
            specs = [t.spec for t in self.tenants.values()]
            slots = [h.slot for h in self.hosts.values() if h.alive]
            new_plan = self.policy.recompute(self.plan, specs, slots,
                                             self.evidence(window=True),
                                             balance=balance)
            moves = self.plan.diff(new_plan)
            self.plan_recomputes += 1
        moved = 0
        for tid, _src, dst in moves:
            st = self.tenants[tid]
            if st.host == dst:
                continue
            src_host = self.hosts.get(st.host)
            if src_host is not None and tid in src_host.runtimes:
                # the source runtime is INTACT (a draining host counts —
                # alive=False only stops placement): a full live migration
                # flushes + snapshots the current state. Routing by
                # aliveness here would silently restore a graceful
                # leaver's tenants from STALE revisions — duplicates for
                # every stateful shape.
                self.migrate(tid, dst, reason="elasticity")
            else:
                self.recover_tenant(tid, dst)   # process truly gone
            moved += 1
        with self._lock:
            self.plan = new_plan
        return moved

    # -- evidence / introspection --------------------------------------------
    def evidence(self, window: bool = False) -> dict:
        """Per-host evidence map (the placement scorer's and rebalancer's
        input). ``load_share`` is each live host's share of rows routed
        SINCE the last placement-consuming read (``window=True`` advances
        the marks — placement/recompute callers pass it; plain reads like
        ``GET /mesh`` observe the same window without consuming it). A
        cumulative lifetime share would let an hour-old burst repel new
        placements forever."""
        with self._lock:
            hosts = list(self.hosts.values())
            deltas = {h.index: max(0, h.rows_in
                                   - self._ev_last_rows.get(h.index, 0))
                      for h in hosts}
            if window:
                for h in hosts:
                    self._ev_last_rows[h.index] = h.rows_in
        total = sum(d for h, d in deltas.items()
                    if self.hosts.get(h) is not None
                    and self.hosts[h].alive) or 1
        out = {}
        for h in hosts:
            ev = h.evidence() if h.alive else {
                "host": h.index, "alive": False, "tenants": 0,
                "rows_in": h.rows_in}
            ev["load_share"] = deltas[h.index] / total if h.alive else 0.0
            out[h.index] = ev
        return out

    def flush(self) -> None:
        for h in self.hosts.values():
            if not h.alive:
                continue
            for rt in list(h.runtimes.values()):
                rt.flush_host()
                if getattr(rt, "procmesh_proxy", False):
                    # a flush resolves staged rows into outputs — the
                    # buffered outbox tail dispatches now
                    rt.deliver_pending()

    def sync_children(self) -> dict:
        """Process-mode observability pull: scrape every live worker's
        full tracker state (gauges + counters + latency histograms) and
        absorb its flight-ring tail into the fabric's timeline
        (site-prefixed ``h{i}:``, child stamps clock-offset-corrected).
        Trace journeys riding the tail stitch into the fabric tracer.
        Inproc hosts share the parent recorder already — this is a no-op
        for them."""
        out = {"scraped": 0, "forwarded": 0}
        for h in list(self.hosts.values()):
            if not h.alive or not hasattr(h, "forward_flight"):
                continue
            out["scraped"] += len(h.scrape_metrics())
            out["forwarded"] += h.forward_flight(self.flight,
                                                 tracer=self.tracer)
        return out

    # -- observability federation (ISSUE 18) ---------------------------------
    def _federated_hosts(self) -> list:
        """Process-backed hosts whose scrape is FRESH enough to render:
        dead, gave-up, or stale-scrape workers are excluded, so their
        families age out of the exposition instead of rendering zombie
        values; a re-adopted/restarted worker re-enters under the same
        ``h{i}`` label on its first good scrape."""
        out = []
        for h in list(self.hosts.values()):
            if not hasattr(h, "latency_states"):
                continue
            handle = getattr(h, "handle", None)
            if not h.alive or (handle is not None and handle.gave_up):
                continue
            if h.scrape_age_s() > self.cfg.metrics_stale_after_s:
                continue
            out.append(h)
        return out

    @staticmethod
    def _phase_of_key(key: str) -> Optional[str]:
        """Scraped latency key → phase name, for keys on the X-Ray phase
        vocabulary (``{tenant}.phase.{query}.{phase}`` plus the
        ``end_to_end`` distribution); None for other latency sites."""
        from ..observability.phases import PHASES
        parts = key.split(".")
        leaf = parts[-1]
        if "phase" in parts[:-1] and leaf in PHASES:
            return leaf
        if "detection" in parts[:-1] and leaf == "end_to_end":
            return "end_to_end"
        return None

    def collect_federated(self, families: dict,
                          app: Optional[str] = None) -> None:
        """Prometheus ``render`` collector hook: per-worker federated
        families (``worker="h{i}"``) plus the fabric-level merge
        (``worker="fabric"``) — bounded worker-label cardinality (host
        count + one), histogram merges exact on the shared ladder."""
        from ..observability.prometheus import collect_scraped
        app = app or "mesh"
        fabric_lat: list = []
        fabric_ctr: list = []
        for h in self._federated_hosts():
            lat, ctr = h.latency_states(), h.counter_states()
            collect_scraped(families, app, f"h{h.index}",
                            lat.items(), ctr.items())
            fabric_lat.extend(lat.items())
            fabric_ctr.extend(ctr.items())
        if fabric_lat or fabric_ctr:
            collect_scraped(families, app, "fabric", fabric_lat, fabric_ctr)

    def federation(self) -> dict:
        """``GET /mesh/latency``: the federated latency breakdown as JSON
        — per worker (scrape age, staleness, per-phase p50/p99) plus the
        fabric-level merge. Scrapes first, so one call is one consistent
        pull of every live worker."""
        if self.supervisor is not None:
            self.sync_children()
        workers: dict = {}
        merged_states: dict = {}        # phase -> [state, ...]
        for h in list(self.hosts.values()):
            if not hasattr(h, "latency_states"):
                continue
            handle = getattr(h, "handle", None)
            age = h.scrape_age_s()
            stale = (not h.alive
                     or (handle is not None and handle.gave_up)
                     or age > self.cfg.metrics_stale_after_s)
            entry = {"scrape_age_s": round(age, 3), "stale": stale,
                     "alive": bool(h.alive), "phases": {}}
            if not stale:
                by_phase: dict = {}
                for key, state in h.latency_states().items():
                    phase = self._phase_of_key(key)
                    if phase is None:
                        continue
                    by_phase.setdefault(phase, []).append(state)
                for phase, states in by_phase.items():
                    entry["phases"][phase] = self._phase_stats(states)
                    merged_states.setdefault(phase, []).extend(states)
            workers[f"h{h.index}"] = entry
        return {
            "workers": workers,
            "merged": {phase: self._phase_stats(states)
                       for phase, states in merged_states.items()},
            "stale_after_s": self.cfg.metrics_stale_after_s,
            "clock_offsets_ns": (
                {f"h{i}": h.clock_offset_ns
                 for i, h in self.supervisor.handles.items()}
                if self.supervisor is not None else {}),
        }

    @staticmethod
    def _phase_stats(states: list) -> dict:
        from ..observability.histogram import LogHistogram
        hist = LogHistogram.merge(states)
        snap = hist.snapshot()
        return {"count": snap["count"],
                "p50_ms": round(snap["p50"] * 1e3, 6),
                "p99_ms": round(snap["p99"] * 1e3, 6),
                "avg_ms": round(snap["avg"] * 1e3, 6)}

    def report(self) -> dict:
        """Service-facing state (``GET /mesh``)."""
        if self.supervisor is not None:
            self.sync_children()        # fold worker timelines in first
        with self._lock:
            backlog = {t: len(st.spill) for t, st in self.tenants.items()
                       if len(st.spill)}
            return {
                "mode": self.cfg.mode,
                "supervisor": (self.supervisor.report()
                               if self.supervisor is not None else None),
                "hosts": self.evidence(),
                "plan": self.plan.report(),
                "tenants": len(self.tenants),
                "migrations": self.migrations,
                "migration_failures": self.migration_failures,
                "recoveries": self.recoveries,
                "drains": self.drains,
                "draining_hosts": sorted(
                    h.index for h in self.hosts.values()
                    if getattr(h, "draining", False)),
                "plan_recomputes": self.plan_recomputes,
                "spilled_chunks": self.spilled_chunks,
                "shed_chunks": self.shed_chunks,
                "replayed_chunks": self.replayed_chunks,
                "dup_chunks": self.dup_chunks,
                "spill_backlog": backlog,
                "journal": (self.journal.position()
                            if self.journal is not None else None),
                "recovery": (self._recovery or None),
                "decisions": [e for e in self.flight.export(category="mesh")
                              if e["kind"].startswith("decision:")][-16:],
            }

    def register_metrics(self, sm) -> None:
        """Expose fabric state as ``mesh.*`` trackers → the
        ``siddhi_tpu_mesh_*`` Prometheus families (label ``host`` = host
        index, ``self`` for fabric-level; lint-pinned by
        ``scripts/check_metric_names.py``). Host leave/rejoin cycles tear
        the families down through ``sm.unregister('mesh.')`` — pinned in
        tests/test_metrics.py so dead gauges never leak — as are the
        elasticity edges: a host joining AFTER registration gets its
        ``mesh.h{i}.*`` gauges on arrival, a removed host's are
        unregistered with it (no permanent blind spots or zombie gauges
        across an elasticity event)."""
        for h in list(self.hosts.values()):
            self._register_host_metrics(sm, h)
        sm.gauge_tracker("mesh.self.hosts",
                         lambda: sum(1 for h in self.hosts.values()
                                     if h.alive))
        sm.gauge_tracker("mesh.self.tenants", lambda: len(self.tenants))
        sm.gauge_tracker("mesh.self.plan_epoch", lambda: self.plan.epoch)
        sm.gauge_tracker("mesh.self.migrations_total",
                         lambda: self.migrations)
        sm.gauge_tracker("mesh.self.migration_failures_total",
                         lambda: self.migration_failures)
        sm.gauge_tracker("mesh.self.recoveries_total",
                         lambda: self.recoveries)
        sm.gauge_tracker("mesh.self.drains_total",
                         lambda: self.drains)
        sm.gauge_tracker("mesh.self.draining_hosts",
                         lambda: sum(1 for h in self.hosts.values()
                                     if getattr(h, "draining", False)))
        sm.gauge_tracker("mesh.self.spilled_chunks_total",
                         lambda: self.spilled_chunks)
        sm.gauge_tracker("mesh.self.shed_chunks_total",
                         lambda: self.shed_chunks)
        sm.gauge_tracker("mesh.self.replayed_chunks_total",
                         lambda: self.replayed_chunks)
        sm.gauge_tracker("mesh.self.dup_chunks_total",
                         lambda: self.dup_chunks)
        sm.gauge_tracker("mesh.self.spill_backlog_chunks",
                         lambda: sum(len(st.spill)
                                     for st in self.tenants.values()))
        sm.gauge_tracker("mesh.self.process_mode",
                         lambda: 1 if self.cfg.mode == "process" else 0)
        if self.supervisor is not None:
            # procmesh.w{i}.* / procmesh.self.* + the per-child scraped
            # families (mesh.h{i}.child.*) — torn down with their worker
            self.supervisor.register_metrics(sm)
            for h in list(self.hosts.values()):
                if hasattr(h, "register_child_metrics"):
                    h.register_child_metrics(sm)
        if self.journal is not None:
            # parent-recovery outcome + journal position → the
            # siddhi_tpu_procmesh_*{worker="recovery"} families
            for k in ("readopted_workers", "restored_workers",
                      "readopted_tenants", "restored_tenants",
                      "journal_records_replayed"):
                sm.gauge_tracker(f"procmesh.recovery.{k}",
                                 lambda k=k: int(self._recovery.get(k, 0)))
            sm.gauge_tracker(
                "procmesh.recovery.recover_s",
                lambda: float(self._recovery.get("recover_s", 0.0)))
            sm.gauge_tracker(
                "procmesh.recovery.journal_lsn",
                lambda: (self.journal.position()["lsn"]
                         if self.journal is not None else 0))
        self._sm = sm

    @staticmethod
    def _register_host_metrics(sm, h: MeshHost) -> None:
        hi = h.index
        sm.gauge_tracker(f"mesh.h{hi}.tenants",
                         lambda h=h: len(h.runtimes))
        sm.gauge_tracker(f"mesh.h{hi}.rows_in_total",
                         lambda h=h: h.rows_in)
        sm.gauge_tracker(f"mesh.h{hi}.compiled_programs",
                         lambda h=h: h.compiled_programs()
                         if h.alive else 0)
        sm.gauge_tracker(f"mesh.h{hi}.alive",
                         lambda h=h: 1 if h.alive else 0)

    def close(self) -> None:
        if self._sm is not None:
            self._sm.unregister("mesh.")
            if self.supervisor is not None or self.journal is not None:
                self._sm.unregister("procmesh.")
            self._sm = None
        if self.journal is not None:
            # final compaction while the workers still answer ops: a clean
            # restart replays one ckpt record instead of the whole tail
            try:
                self._journal_checkpoint()
            except Exception:   # noqa: BLE001 — teardown must not wedge on
                # a dead worker mid-checkpoint
                log.exception("mesh: final journal checkpoint failed")
        if self.supervisor is not None:
            # monitor first: a restart racing the teardown would respawn
            # workers the loop below is stopping
            self.supervisor.shutdown()
        for h in list(self.hosts.values()):
            h.close()
        if self.journal is not None:
            self.journal.close()
            self.journal = None
        self.hosts.clear()
        self.tenants.clear()
