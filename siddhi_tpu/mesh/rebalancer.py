"""MeshRebalancer: one cross-host move per decision, evidence first.

The fabric's closed loop: watch the per-host evidence the fabric
aggregates (routed-row load shares, fleet guard eject/shed pressure, SLO
compliance — ``MeshFabric.evidence()``), and when one host's load share
runs past the imbalance ratio, propose exactly ONE tenant move toward the
least-loaded host — the Hazelcast-Jet discipline (PAPERS.md 2103.10169):
move load *before* the hot host saturates, one bounded step at a time, so
the control loop can judge each move before the next.

Decision hygiene is the ``observability/slo.py`` contract, pinned by the
same lint (``scripts/check_guard_coverage.py``): every actuator is
reachable ONLY through :meth:`_actuate`, which records the decision — the
hot host, its measured share vs the threshold, the chosen tenant and
destination — to the fabric's flight recorder (and the moved tenant's own
app timeline, via ``MeshFabric.migrate``) BEFORE the move runs. Cooldown
between moves is the hysteresis that keeps the loop from thrashing
tenants back and forth.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Optional

log = logging.getLogger("siddhi_tpu.mesh")

_DEF_INTERVAL_S = 1.0         # min wall-clock between evaluations
_DEF_COOLDOWN_S = 5.0         # min wall-clock between moves
_DEF_IMBALANCE = 2.0          # hot = load share > imbalance × fair share


class MeshRebalancer:
    """One fabric's rebalancing loop. Drive :meth:`evaluate` explicitly
    (tests, bench, an operator cron) or :meth:`start` the background
    thread."""

    def __init__(self, fabric, interval_s: float = _DEF_INTERVAL_S,
                 cooldown_s: float = _DEF_COOLDOWN_S,
                 imbalance: float = _DEF_IMBALANCE,
                 min_rows: int = 1024):
        self.fabric = fabric
        self.interval_s = float(interval_s)
        self.cooldown_s = float(cooldown_s)
        self.imbalance = float(imbalance)
        self.min_rows = int(min_rows)   # ignore cold meshes (no evidence)
        self.decisions = 0
        self.evaluations = 0
        self.decision_log: deque = deque(maxlen=64)
        self._last_rows: dict = {}      # host -> rows_in at last evaluation
        self._last_eval_t = 0.0
        self._last_act_t = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the loop -------------------------------------------------------------
    def evaluate(self, force: bool = False) -> Optional[dict]:
        """One decision step: windowed load deltas per host, at most one
        proposed move. Never raises into the caller — a rebalancer bug
        must degrade to "no decision"."""
        now = time.monotonic()
        if not force and now - self._last_eval_t < self.interval_s:
            return None
        if not self._lock.acquire(blocking=False):
            return None
        try:
            self._last_eval_t = now
            return self._evaluate(now, force)
        except Exception:   # noqa: BLE001 — keep-alive, like the SLO loop
            log.exception("mesh rebalancer evaluation failed")
            return None
        finally:
            self._lock.release()

    def _evaluate(self, now: float, force: bool) -> Optional[dict]:
        ev = self.fabric.evidence()
        live = {h: e for h, e in ev.items() if e.get("alive")}
        if len(live) < 2:
            return None
        self.evaluations += 1
        # windowed load: routed rows since the last evaluation (cumulative
        # counters flatten exactly like cumulative percentiles would)
        deltas = {}
        for h, e in live.items():
            cur = int(e.get("rows_in", 0))
            deltas[h] = max(0, cur - self._last_rows.get(h, 0))
            self._last_rows[h] = cur
        total = sum(deltas.values())
        if total < self.min_rows:
            return None                  # cold window: no evidence, no move
        if not force and now - self._last_act_t < self.cooldown_s:
            return None                  # actuator cooldown: hysteresis
        fair = 1.0 / len(live)
        hot = max(live, key=lambda h: deltas[h])
        share = deltas[hot] / total
        # the threshold must stay satisfiable: on a 2-host mesh
        # imbalance×fair reaches 1.0 and a share can never exceed it —
        # clamp below 1 so total one-host concentration always triggers
        if share <= min(self.imbalance * fair, 0.95):
            return None
        dst = self._target(live, deltas, exclude=hot)
        if dst is None:
            return None
        tenant = self._pick_tenant(hot, dst)
        if tenant is None:
            return None
        decision = {"actuator": "migrate_tenant", "tenant": tenant,
                    "src": hot, "dst": dst,
                    "load_share": round(share, 3),
                    "threshold": round(self.imbalance * fair, 3),
                    "window_rows": total,
                    "src_pressure": {
                        k: live[hot].get(k, 0)
                        for k in ("ejections", "sheds", "slo_violations")}}
        self._actuate(decision)
        return decision

    def _target(self, live: dict, deltas: dict,
                exclude: int) -> Optional[int]:
        cands = [h for h, e in live.items()
                 if h != exclude
                 and e.get("tenants", 0) < e.get("capacity", 0)]
        if not cands:
            return None
        # process mode: a recently-respawned worker ranks behind a stable
        # one at equal load (inproc hosts report no restarts — no change)
        return min(cands, key=lambda h: (deltas[h],
                                         live[h].get("restarts", 0),
                                         live[h].get("tenants", 0), h))

    def _pick_tenant(self, hot: int, dst: int) -> Optional[str]:
        """The move that costs locality least: prefer a tenant whose shape
        the destination already compiles (its lanes join an existing
        FleetGroup — no new program), smallest first so one decision stays
        a bounded step."""
        fabric = self.fabric
        host = fabric.hosts.get(hot)
        if host is None or not host.runtimes:
            return None
        dst_shapes = {s.shape for t, s in fabric.plan.assignment.items()
                      if s.host == dst}
        cands = []
        for tid in host.runtimes:
            st = fabric.tenants.get(tid)
            if st is None or st.migrating:
                continue
            shape = st.spec.primary_shape
            cands.append((0 if shape in dst_shapes else 1, tid))
        if not cands:
            return None
        return min(cands)[1]

    # -- actuation (decision recorded BEFORE the knob moves) ------------------
    def _actuate(self, decision: dict) -> None:
        """THE single actuation gate (the ``SLOController._actuate``
        contract, pinned by ``scripts/check_guard_coverage.py``): record
        the decision with its evidence, THEN dispatch."""
        self._record_decision(decision)
        getattr(self, f"_act_{decision['actuator']}")(decision)
        self._last_act_t = time.monotonic()

    def _record_decision(self, decision: dict) -> None:
        self.decisions += 1
        self.fabric.flight.record(
            "mesh", f"decision:{decision['actuator']}",
            site=f"rebalance:h{decision.get('src')}", detail=dict(decision))
        self.decision_log.append({"t": time.time(), **decision})
        log.info("mesh rebalancer: %s (%s)", decision["actuator"], decision)

    def _act_migrate_tenant(self, decision: dict) -> None:
        self.fabric.migrate(decision["tenant"], decision["dst"],
                            reason="rebalance", decided=decision)

    # -- background loop ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.evaluate()

    def report(self) -> dict:
        return {"decisions": self.decisions,
                "evaluations": self.evaluations,
                "interval_s": self.interval_s,
                "cooldown_s": self.cooldown_s,
                "imbalance": self.imbalance,
                "recent_decisions": list(self.decision_log)}
