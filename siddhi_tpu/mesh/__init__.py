"""Mesh fabric: tenant placement & live migration across engine shards.

Fuses the two halves that existed separately — single-process fleet lanes
(PRs 6/8/12: shared compilation, lane batching, FleetGuard, the SLO
autopilot) and DCN lane-groups with failover (PR 4) — into one placement
layer (ROADMAP item 3):

- :mod:`plan` — :class:`MeshPlan` / :class:`PlacementPolicy`: tenants get
  ``(host, lane-group, device)`` slots, locality-aware by shape
  fingerprint, with evidence-fed capacity scoring;
- :mod:`fabric` — :class:`MeshFabric`: host shards, exactly-once ingress
  routing, live tenant migration over the snapshot-store/adoption
  machinery, host join/leave elasticity, the SLO autopilot's cross-host
  ``mesh_replace`` rung;
- :mod:`rebalancer` — :class:`MeshRebalancer`: one move per decision,
  recorded with its evidence before actuating.

``MeshConfig(mode='process')`` swaps the in-process host shards for REAL
OS processes (:mod:`siddhi_tpu.procmesh`): each host is its own
interpreter + JAX runtime behind a control socket, supervised with
heartbeats and backoff-paced restarts — the same fabric ladder,
byte-compatible, with actual SIGKILL chaos instead of simulated kills.
"""

from .fabric import MeshChaosFault, MeshConfig, MeshFabric, MeshHost
from .plan import (
    HostSlot,
    MeshPlan,
    MeshSlot,
    PlacementPolicy,
    TenantSpec,
    shape_fingerprint,
)
from .rebalancer import MeshRebalancer

__all__ = [
    "HostSlot",
    "MeshChaosFault",
    "MeshConfig",
    "MeshFabric",
    "MeshHost",
    "MeshPlan",
    "MeshRebalancer",
    "MeshSlot",
    "PlacementPolicy",
    "TenantSpec",
    "shape_fingerprint",
]
