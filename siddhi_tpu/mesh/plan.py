"""MeshPlan / PlacementPolicy: who runs where, decided by shape.

The mesh's unit of placement is the TENANT (one SiddhiApp); its slot is a
``(host, lane-group, device)`` triple — the host that owns its runtime, the
shape lane-group (its queries' fleet shape fingerprints, which decide WHICH
of the host's FleetGroups its lanes join) and the accelerator device bound
to that host. Placement is **locality-aware by shape fingerprint**
(``fleet/shape.py``): same-shape tenants co-locate into the same host's
FleetGroup, so each host compiles the fewest programs and steps the widest
lane batches (the PR 6 economics — N tenants of one shape cost 1 compile
and execute as lanes of one program — only pay off when the N tenants
actually land on one host).

Scoring is evidence-fed: a :class:`PlacementPolicy` consults the per-host
evidence dict the fabric aggregates from ``fleet.*``/``slo.*`` gauges and
the flight recorder (load EMA, eject/shed pressure, SLO violations) so a
struggling host stops attracting tenants before it saturates — the
Hazelcast-Jet lesson (PAPERS.md 2103.10169): move load *before* the node
saturates, not after.

Plans are DATA (compare, diff, recompute): elasticity is
``recompute(current, tenants, hosts)`` — sticky for tenants whose slot
survives, minimal moves for the rest — and the diff of two plans IS the
bulk-adoption work list a host join/leave triggers.
"""

from __future__ import annotations

import hashlib
import random
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["TenantSpec", "HostSlot", "MeshSlot", "MeshPlan",
           "PlacementPolicy", "shape_fingerprint"]


def shape_fingerprint(app_text_or_parsed, stream_defs: Optional[dict] = None,
                      ) -> tuple:
    """The tenant's placement key: the tuple of its queries' fleet shape
    fingerprints in definition order. Queries with no fleet shape (joins,
    exotic expressions) contribute a ``solo:`` digest of their text — they
    still cluster identical copies, they just never share a program."""
    from ..compiler import parse as _parse
    from ..fleet.shape import (FleetShapeError, normalize_partition_query,
                               normalize_query)
    from ..query_api import Query

    app = _parse(app_text_or_parsed) \
        if isinstance(app_text_or_parsed, str) else app_text_or_parsed
    defs = dict(stream_defs or app.stream_definitions)
    keys = []
    for el in app.execution_elements:
        if isinstance(el, Query):
            try:
                keys.append(normalize_query(el, defs).shape_key)
            except FleetShapeError:
                keys.append(_solo_key(el))
        elif hasattr(el, "queries"):          # partition block
            for q in el.queries:
                try:
                    keys.append(
                        normalize_partition_query(el, q, defs).shape_key)
                except FleetShapeError:
                    keys.append(_solo_key(q))
    return tuple(keys)


def _solo_key(query) -> str:
    digest = hashlib.sha256(repr(query).encode()).hexdigest()[:20]
    return f"solo:{digest}"


@dataclass
class TenantSpec:
    """One tenant as the placement layer sees it."""

    tenant_id: str                      # == the SiddhiApp name
    app_text: str
    shapes: tuple = ()                  # shape_fingerprint() of the app
    weight: float = 1.0                 # fair-share weight (capacity units)

    @property
    def primary_shape(self) -> str:
        return self.shapes[0] if self.shapes else "solo:empty"


@dataclass
class HostSlot:
    """One host of the mesh: capacity in tenant slots plus its device
    binding (the jax device ordinal this host's lane-groups step on — on a
    forced-host CPU mesh these are the 8 virtual devices, on hardware the
    chips)."""

    host: int
    capacity: int
    device: Optional[int] = None


@dataclass(frozen=True)
class MeshSlot:
    """A tenant's assigned ``(host, lane-group, device)`` slot."""

    host: int
    shape: str                          # the lane-group key on that host
    device: Optional[int] = None


@dataclass
class MeshPlan:
    """Assignment of the tenant population to mesh slots (pure data)."""

    assignment: dict = field(default_factory=dict)   # tenant_id -> MeshSlot
    epoch: int = 0
    policy: str = "locality"

    def host_of(self, tenant_id: str) -> Optional[int]:
        slot = self.assignment.get(tenant_id)
        return slot.host if slot is not None else None

    def tenants_of(self, host: int) -> list:
        return sorted(t for t, s in self.assignment.items()
                      if s.host == host)

    def tenants_per_host(self, hosts: list) -> dict:
        return {h.host: len(self.tenants_of(h.host)) for h in hosts}

    def shapes_per_host(self, hosts: list) -> dict:
        """How many DISTINCT shapes each host must compile under this plan —
        the placement-quality number the locality policy minimizes."""
        out: dict = {}
        for h in hosts:
            shapes = {s.shape for t, s in self.assignment.items()
                      if s.host == h.host}
            out[h.host] = len(shapes)
        return out

    def diff(self, other: "MeshPlan") -> list:
        """Moves to turn ``self`` into ``other``:
        ``[(tenant_id, src_host|None, dst_host)]`` — the bulk-adoption work
        list of an elasticity event."""
        moves = []
        for t, slot in other.assignment.items():
            cur = self.assignment.get(t)
            if cur is None or cur.host != slot.host:
                moves.append((t, cur.host if cur else None, slot.host))
        return moves

    def report(self) -> dict:
        hosts: dict = {}
        for t, s in self.assignment.items():
            hosts.setdefault(s.host, []).append(t)
        return {"epoch": self.epoch, "policy": self.policy,
                "tenants": len(self.assignment),
                "hosts": {str(h): sorted(ts) for h, ts in hosts.items()}}


class PlacementPolicy:
    """Shape-locality placement with evidence-fed capacity scoring.

    ``kind='locality'`` (the default): tenants group by primary shape,
    shapes place largest-population first, and each shape's tenants pack
    onto the fewest hosts — preferring hosts that already hold the shape —
    so per-host compiled-program counts stay near (shapes ÷ hosts) and
    FleetGroups step wide. ``kind='random'`` is the control arm the bench
    compares against (seeded shuffle, round-robin over free slots).
    """

    def __init__(self, kind: str = "locality", seed: int = 17):
        if kind not in ("locality", "random"):
            raise ValueError(f"unknown placement policy '{kind}'")
        self.kind = kind
        self.seed = seed

    # -- scoring -------------------------------------------------------------
    @staticmethod
    def _pressure(ev: Optional[dict]) -> float:
        """Evidence → a load penalty in tenant-slot units. ``load_share``
        is the host's share of recently routed rows; ejections/sheds and
        SLO violations (flight-recorder and guard evidence) push the score
        down further so a struggling host stops attracting placements."""
        if not ev:
            return 0.0
        return (4.0 * float(ev.get("load_share", 0.0))
                + 1.0 * min(4, int(ev.get("ejections", 0)))
                + 0.5 * min(4, int(ev.get("slo_violations", 0)))
                + 0.25 * min(4, int(ev.get("sheds", 0)))
                # process-mode only (inproc hosts report no restarts): a
                # worker that has been respawned recently is a worse home
                # — every restart re-pays compile and replay cost
                + 0.5 * min(4, int(ev.get("restarts", 0))))

    def _score(self, host: HostSlot, free: int, has_shape: bool,
               evidence: Optional[dict]) -> tuple:
        # sort key (descending): shape locality first, then free capacity
        # net of evidence pressure, host index as the deterministic tie-break
        ev = (evidence or {}).get(host.host)
        return (1 if has_shape else 0,
                free - self._pressure(ev),
                -host.host)

    # -- placement -----------------------------------------------------------
    def place(self, tenants: list, hosts: list,
              evidence: Optional[dict] = None,
              sticky: Optional[MeshPlan] = None,
              max_keep_per_host: Optional[int] = None) -> MeshPlan:
        """Compute a plan. With ``sticky`` (the current plan), tenants whose
        host survives with capacity keep their slot — elasticity recomputes
        move only what must move. ``max_keep_per_host`` caps the PER-HOST
        fill of this whole recompute at the balanced target (a host join
        passes ⌈tenants ÷ hosts⌉: without a cap on PLACEMENT too, sticky
        retention — and shape locality pulling the overflow right back —
        would leave the newcomer empty)."""
        if not hosts:
            raise ValueError("cannot place tenants on an empty mesh")
        by_host_shapes: dict = {h.host: set() for h in hosts}
        used: dict = {h.host: 0 for h in hosts}
        cap: dict = {h.host: h.capacity if max_keep_per_host is None
                     else min(h.capacity, max_keep_per_host)
                     for h in hosts}
        assignment: dict = {}
        device_of = {h.host: h.device for h in hosts}

        pending = list(tenants)
        if sticky is not None:
            kept = []
            for t in pending:
                slot = sticky.assignment.get(t.tenant_id)
                keep_cap = cap.get(slot.host) if slot is not None else None
                if slot is not None and keep_cap is not None \
                        and used[slot.host] < keep_cap:
                    assignment[t.tenant_id] = MeshSlot(
                        slot.host, t.primary_shape, device_of[slot.host])
                    used[slot.host] += 1
                    by_host_shapes[slot.host].add(t.primary_shape)
                else:
                    kept.append(t)
            pending = kept

        if self.kind == "random":
            rng = random.Random(self.seed)
            order = list(pending)
            rng.shuffle(order)
            hosts_ring = [h.host for h in hosts]
            i = 0
            for t in order:
                for _ in range(len(hosts_ring)):
                    h = hosts_ring[i % len(hosts_ring)]
                    i += 1
                    if used[h] < cap[h]:
                        assignment[t.tenant_id] = MeshSlot(
                            h, t.primary_shape, device_of[h])
                        used[h] += 1
                        by_host_shapes[h].add(t.primary_shape)
                        break
                else:
                    raise ValueError("mesh capacity exhausted")
            return MeshPlan(assignment,
                            epoch=(sticky.epoch + 1 if sticky else 0),
                            policy=self.kind)

        # locality: largest shape populations place first so the big
        # fleets get contiguous hosts before the tail fragments them
        by_shape: dict = {}
        for t in pending:
            by_shape.setdefault(t.primary_shape, []).append(t)
        for shape in sorted(by_shape,
                            key=lambda s: (-len(by_shape[s]), s)):
            for t in by_shape[shape]:
                candidates = [h for h in hosts if used[h.host] < cap[h.host]]
                if not candidates:
                    raise ValueError("mesh capacity exhausted")
                best = max(candidates, key=lambda h: self._score(
                    h, cap[h.host] - used[h.host],
                    shape in by_host_shapes[h.host], evidence))
                assignment[t.tenant_id] = MeshSlot(
                    best.host, shape, device_of[best.host])
                used[best.host] += 1
                by_host_shapes[best.host].add(shape)
        return MeshPlan(assignment,
                        epoch=(sticky.epoch + 1 if sticky else 0),
                        policy=self.kind)

    def recompute(self, current: MeshPlan, tenants: list,
                  hosts: list, evidence: Optional[dict] = None,
                  balance: bool = False) -> MeshPlan:
        """Elasticity entry point: re-place against the NEW host set,
        keeping every slot that survives (host still in the mesh, capacity
        still available). With ``balance=True`` each host retains at most
        the balanced target ⌈tenants ÷ hosts⌉ — the overflow re-places, so
        a freshly joined host adopts its share. The caller applies
        ``current.diff(new)``."""
        max_keep = None
        if balance and hosts and tenants:
            max_keep = -(-len(tenants) // len(hosts))
        return self.place(tenants, hosts, evidence, sticky=current,
                          max_keep_per_host=max_keep)
