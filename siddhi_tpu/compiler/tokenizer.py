"""SiddhiQL tokenizer.

Reference grammar: ``modules/siddhi-query-compiler/src/main/antlr4/io/siddhi/query/
compiler/SiddhiQL.g4`` (lexer rules at the bottom of the file). Hand-rolled here —
no ANTLR — producing a flat token list the recursive-descent parser consumes.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

from ..query_api.definition import DataType


class TokenType:
    IDENT = "IDENT"
    STRING = "STRING"
    INT = "INT"
    LONG = "LONG"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    OP = "OP"
    SCRIPT = "SCRIPT"   # `{ ... }` raw function body
    EOF = "EOF"


@dataclass
class Token:
    type: str
    value: str
    line: int
    col: int

    def __repr__(self) -> str:
        return f"{self.type}({self.value!r}@{self.line}:{self.col})"


class TokenizeError(SyntaxError):
    pass


# multi-char operators first so maximal munch wins
_OPERATORS = [
    "->", "<=", ">=", "==", "!=", "...",
    "(", ")", "[", "]", "<", ">", ",", ";", ":", "#", "@",
    "+", "-", "*", "/", "%", "?", "!", ".", "=",
]

_NUMBER_RE = re.compile(
    r"""
    (?P<num>
        (?:\d+\.\d+(?:[eE][+-]?\d+)?)   # 1.5, 1.5e3
      | (?:\d+[eE][+-]?\d+)             # 1e3
      | (?:\d+)                         # 42
    )
    (?P<suffix>[lLfFdD]?)
    """,
    re.VERBOSE,
)

_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def tokenize(text: str) -> list[Token]:
    tokens: list[Token] = []
    i, n = 0, len(text)
    line, line_start = 1, 0

    def pos() -> tuple[int, int]:
        return line, i - line_start + 1

    def advance_newlines(chunk: str, start: int) -> None:
        nonlocal line, line_start
        for m in re.finditer(r"\n", chunk):
            line += 1
            line_start = start + m.end()

    while i < n:
        c = text[i]
        # whitespace
        if c in " \t\r\n":
            if c == "\n":
                line += 1
                line_start = i + 1
            i += 1
            continue
        # comments: -- line, // line, /* block */
        if text.startswith("--", i) or text.startswith("//", i):
            j = text.find("\n", i)
            i = n if j < 0 else j
            continue
        if text.startswith("/*", i):
            j = text.find("*/", i + 2)
            if j < 0:
                raise TokenizeError(f"unterminated block comment at line {line}")
            advance_newlines(text[i:j + 2], i)
            i = j + 2
            continue
        ln, col = pos()
        # strings: ''' """ ' "
        if text.startswith("'''", i) or text.startswith('"""', i):
            q = text[i:i + 3]
            j = text.find(q, i + 3)
            if j < 0:
                raise TokenizeError(f"unterminated string at line {ln}")
            val = text[i + 3:j]
            advance_newlines(text[i:j + 3], i)
            tokens.append(Token(TokenType.STRING, val, ln, col))
            i = j + 3
            continue
        if c in "'\"":
            j = i + 1
            buf = []
            while j < n and text[j] != c:
                if text[j] == "\n":
                    raise TokenizeError(f"unterminated string at line {ln}")
                if text[j] == "\\" and j + 1 < n:
                    buf.append(text[j + 1])
                    j += 2
                else:
                    buf.append(text[j])
                    j += 1
            if j >= n:
                raise TokenizeError(f"unterminated string at line {ln}")
            tokens.append(Token(TokenType.STRING, "".join(buf), ln, col))
            i = j + 1
            continue
        # backtick-quoted identifier
        if c == "`":
            j = text.find("`", i + 1)
            if j < 0:
                raise TokenizeError(f"unterminated quoted identifier at line {ln}")
            tokens.append(Token(TokenType.IDENT, text[i + 1:j], ln, col))
            i = j + 1
            continue
        # script body `{ ... }` (define function); nesting + quote aware
        if c == "{":
            depth = 0
            j = i
            while j < n:
                ch = text[j]
                if ch in "'\"":
                    q = ch
                    j += 1
                    while j < n and text[j] != q:
                        j += 2 if text[j] == "\\" else 1
                elif ch == "{":
                    depth += 1
                elif ch == "}":
                    depth -= 1
                    if depth == 0:
                        break
                j += 1
            if j >= n:
                raise TokenizeError(f"unterminated '{{' block at line {ln}")
            body = text[i + 1:j]
            advance_newlines(text[i:j + 1], i)
            tokens.append(Token(TokenType.SCRIPT, body, ln, col))
            i = j + 1
            continue
        # numbers
        m = _NUMBER_RE.match(text, i)
        if m and c.isdigit():
            num, suffix = m.group("num"), m.group("suffix")
            if suffix in ("l", "L"):
                tokens.append(Token(TokenType.LONG, num, ln, col))
            elif suffix in ("f", "F"):
                tokens.append(Token(TokenType.FLOAT, num, ln, col))
            elif suffix in ("d", "D"):
                tokens.append(Token(TokenType.DOUBLE, num, ln, col))
            elif "." in num or "e" in num or "E" in num:
                tokens.append(Token(TokenType.DOUBLE, num, ln, col))
            else:
                tokens.append(Token(TokenType.INT, num, ln, col))
            i = m.end()
            continue
        # identifiers / keywords
        m = _IDENT_RE.match(text, i)
        if m:
            tokens.append(Token(TokenType.IDENT, m.group(0), ln, col))
            i = m.end()
            continue
        # operators
        for op in _OPERATORS:
            if text.startswith(op, i):
                tokens.append(Token(TokenType.OP, op, ln, col))
                i += len(op)
                break
        else:
            raise TokenizeError(f"unexpected character {c!r} at line {ln}:{col}")
    tokens.append(Token(TokenType.EOF, "", line, 1))
    return tokens


# time units → milliseconds (reference: SiddhiQL.g4 time_value rules)
TIME_UNITS: dict[str, int] = {}
for _names, _ms in [
    (("millisecond", "milliseconds", "millisec", "ms"), 1),
    (("second", "seconds", "sec"), 1000),
    (("minute", "minutes", "min"), 60_000),
    (("hour", "hours"), 3_600_000),
    (("day", "days"), 86_400_000),
    (("week", "weeks"), 7 * 86_400_000),
    (("month", "months"), 30 * 86_400_000),
    (("year", "years"), 365 * 86_400_000),
]:
    for _nm in _names:
        TIME_UNITS[_nm] = _ms


PRIMITIVE_TYPES = {
    "string": DataType.STRING,
    "int": DataType.INT,
    "long": DataType.LONG,
    "float": DataType.FLOAT,
    "double": DataType.DOUBLE,
    "bool": DataType.BOOL,
    "object": DataType.OBJECT,
}
