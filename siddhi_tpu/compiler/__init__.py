"""SiddhiQL compiler front end.

Reference: ``modules/siddhi-query-compiler`` — ``SiddhiCompiler.parse`` at
``SiddhiCompiler.java:61`` plus ``updateVariables`` (``${var}`` substitution used by
``SiddhiManager.createSiddhiAppRuntime``, ``SiddhiManager.java:94-97``).
"""

from __future__ import annotations

import os
import re

from ..query_api import OnDemandQuery, Query, SiddhiApp
from .parser import Parser, SiddhiParserError
from .tokenizer import TokenizeError, tokenize

__all__ = [
    "SiddhiCompiler",
    "SiddhiParserError",
    "TokenizeError",
    "parse",
    "parse_query",
    "parse_on_demand_query",
    "update_variables",
]

_VAR_RE = re.compile(r"\$\{(\w+)\}")


def update_variables(app_text: str, env: dict | None = None,
                     config_manager=None) -> str:
    """Substitute ``${var}`` from env/system properties (SiddhiCompiler.updateVariables),
    falling back to the ConfigManager's properties."""
    source = env if env is not None else os.environ

    def sub(m: re.Match) -> str:
        name = m.group(1)
        if name in source:
            return str(source[name])
        if config_manager is not None:
            v = config_manager.extract_property(name)
            if v is not None:
                return v
        raise SiddhiParserError(f"no system/environment variable found for ${{{name}}}")

    return _VAR_RE.sub(sub, app_text)


def parse(app_text: str) -> SiddhiApp:
    app = Parser(app_text).parse_app()
    # retain the source for process-parallel tiers: a procmesh lane-pool
    # child rebuilds an identical engine by re-parsing the SAME text (the
    # compile-order determinism that keeps dictionary constant codes in
    # agreement across processes)
    app.source_text = app_text
    return app


def parse_query(query_text: str) -> Query:
    p = Parser(query_text)
    anns = p.parse_annotations()
    q = p.parse_query()
    q.annotations = anns + q.annotations
    return q


def parse_on_demand_query(text: str) -> OnDemandQuery:
    return Parser(text).parse_on_demand_query()


class SiddhiCompiler:
    parse = staticmethod(parse)
    parse_query = staticmethod(parse_query)
    parse_on_demand_query = staticmethod(parse_on_demand_query)
    update_variables = staticmethod(update_variables)
