"""SiddhiQL recursive-descent parser → query_api AST.

Plays the role of the reference's ANTLR visitor
(``modules/siddhi-query-compiler/src/main/java/io/siddhi/query/compiler/internal/
SiddhiQLBaseVisitorImpl.java``, 3,080 LoC) and grammar (``SiddhiQL.g4``, 918 lines),
re-expressed as a hand-rolled parser over the tokenizer's output. Covers: stream /
table / window / trigger / aggregation / function definitions, annotations, single /
join / pattern / sequence queries, partitions, output rate limiting, insert / delete /
update / update-or-insert / return actions, and on-demand (store) queries.
"""

from __future__ import annotations

from typing import Any, Optional, Union

from ..query_api import (
    AbsentStreamStateElement,
    AggregationDefinition,
    And,
    Annotation,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    CountStateElement,
    DataType,
    DeleteStream,
    EventOutputRate,
    EventTrigger,
    EveryStateElement,
    Expression,
    Filter,
    FunctionDefinition,
    In,
    InsertIntoStream,
    IsNull,
    JoinInputStream,
    JoinType,
    LAST_INDEX,
    LogicalStateElement,
    LogicalType,
    MathExpr,
    MathOp,
    Minus,
    NextStateElement,
    Not,
    OnDemandQuery,
    OnDemandQueryType,
    Or,
    OrderByAttribute,
    OrderByOrder,
    OutputAttribute,
    OutputEventsFor,
    OutputEventType,
    OutputRateType,
    Partition,
    PartitionType,
    Query,
    RangePartitionProperty,
    ReturnStream,
    Selector,
    SiddhiApp,
    SingleInputStream,
    SnapshotOutputRate,
    StateElement,
    StateInputStream,
    StateInputStreamType,
    StreamDefinition,
    StreamFunction,
    StreamStateElement,
    TableDefinition,
    TimeOutputRate,
    TimePeriodDuration,
    TriggerDefinition,
    UpdateOrInsertStream,
    UpdateSetAttribute,
    UpdateStream,
    Variable,
    Window,
    WindowDefinition,
)
from .tokenizer import PRIMITIVE_TYPES, TIME_UNITS, Token, TokenType, tokenize


class SiddhiParserError(SyntaxError):
    pass


_DURATIONS = {
    "sec": TimePeriodDuration.SECONDS, "seconds": TimePeriodDuration.SECONDS,
    "second": TimePeriodDuration.SECONDS,
    "min": TimePeriodDuration.MINUTES, "minutes": TimePeriodDuration.MINUTES,
    "minute": TimePeriodDuration.MINUTES,
    "hour": TimePeriodDuration.HOURS, "hours": TimePeriodDuration.HOURS,
    "day": TimePeriodDuration.DAYS, "days": TimePeriodDuration.DAYS,
    "month": TimePeriodDuration.MONTHS, "months": TimePeriodDuration.MONTHS,
    "year": TimePeriodDuration.YEARS, "years": TimePeriodDuration.YEARS,
}

# keywords that terminate an input-stream section
_QUERY_SECTION_KW = {"select", "insert", "delete", "update", "return", "output"}


class Parser:
    def __init__(self, text: str):
        self.text = text
        self.tokens = tokenize(text)
        self.pos = 0

    # ------------------------------------------------------------------ utils
    def peek(self, offset: int = 0) -> Token:
        i = min(self.pos + offset, len(self.tokens) - 1)
        return self.tokens[i]

    def next(self) -> Token:
        t = self.peek()
        self.pos += 1
        return t

    def at_kw(self, *kws: str, offset: int = 0) -> bool:
        t = self.peek(offset)
        return t.type == TokenType.IDENT and t.value.lower() in kws

    def at_op(self, *ops: str, offset: int = 0) -> bool:
        t = self.peek(offset)
        return t.type == TokenType.OP and t.value in ops

    def accept_kw(self, *kws: str) -> Optional[str]:
        if self.at_kw(*kws):
            return self.next().value.lower()
        return None

    def accept_op(self, *ops: str) -> Optional[str]:
        if self.at_op(*ops):
            return self.next().value
        return None

    def expect_kw(self, *kws: str) -> str:
        v = self.accept_kw(*kws)
        if v is None:
            self.fail(f"expected {'/'.join(kws)!r}")
        return v

    def expect_op(self, *ops: str) -> str:
        v = self.accept_op(*ops)
        if v is None:
            self.fail(f"expected {'/'.join(ops)!r}")
        return v

    def expect_ident(self) -> str:
        t = self.peek()
        if t.type != TokenType.IDENT:
            self.fail("expected identifier")
        return self.next().value

    def fail(self, msg: str) -> None:
        t = self.peek()
        raise SiddhiParserError(
            f"{msg}, got {t.type}({t.value!r}) at line {t.line}:{t.col}"
        )

    # -------------------------------------------------------------- top level
    def parse_app(self) -> SiddhiApp:
        app = SiddhiApp()
        while self.peek().type != TokenType.EOF:
            anns = self.parse_annotations()
            if self.at_kw("define"):
                self.parse_definition(app, anns)
            elif self.at_kw("partition"):
                app.add_partition(self.parse_partition(anns))
            elif self.at_kw("from"):
                q = self.parse_query()
                q.annotations = anns + q.annotations
                app.add_query(q)
            else:
                # app-level annotations with no following element
                if anns:
                    app.annotations.extend(anns)
                    if self.accept_op(";"):
                        continue
                    if self.peek().type == TokenType.EOF:
                        break
                    continue
                self.fail("expected definition, partition, query, or annotation")
                anns = []
            app.annotations.extend(
                a for a in anns
                if a.name.lower() == "app" or a.namespace == "app")
            self.accept_op(";")
        return app

    # ------------------------------------------------------------ annotations
    def parse_annotations(self) -> list[Annotation]:
        anns = []
        while self.at_op("@"):
            anns.append(self.parse_annotation())
        return anns

    def parse_annotation(self) -> Annotation:
        self.expect_op("@")
        name = self.expect_ident()
        ann = Annotation(name)
        if self.accept_op(":"):
            key = self.expect_ident()
            ann.name = name.lower()
            if self.accept_op("("):
                if self._at_annotation_kv():
                    # `@app:playback(idle.time='…', increment='…')` — the
                    # namespaced form with key=value content is its own
                    # annotation named after the sub-key (reference parses
                    # the `app:` prefix as a namespace)
                    sub = Annotation(key.lower(), namespace=name.lower())
                    self._parse_annotation_elements(sub)
                    self.expect_op(")")
                    return sub
                # `@App:name('x')` form → Annotation('app').element(key, v)
                val = self.parse_annotation_value()
                self.expect_op(")")
                ann.element(key, val)
            else:
                ann.element(key, "true")
            return ann
        if self.accept_op("("):
            self._parse_annotation_elements(ann)
            self.expect_op(")")
        return ann

    def _kv_key_len(self) -> int:
        """Token count of a (dotted) identifier key at the cursor, else 0."""
        if self.peek().type != TokenType.IDENT:
            return 0
        klen = 1
        while (self.peek(klen).type == TokenType.OP
               and self.peek(klen).value == "."
               and self.peek(klen + 1).type == TokenType.IDENT):
            klen += 2
        return klen

    def _at_annotation_kv(self) -> bool:
        klen = self._kv_key_len()
        return bool(klen) and self.peek(klen).type == TokenType.OP \
            and self.peek(klen).value == "="

    def _parse_annotation_elements(self, ann: Annotation) -> None:
        """Comma-separated annotation body: nested @annotations, key=value
        pairs (keys may be dotted: buffer.size, cache.policy), bare values."""
        while not self.at_op(")"):
            if self.at_op("@"):
                ann.annotations.append(self.parse_annotation())
            elif self._at_annotation_kv():
                klen = self._kv_key_len()
                key = "".join(self.next().value for _ in range(klen))
                self.next()  # '='
                ann.element(key, self.parse_annotation_value())
            else:
                ann.element(None, self.parse_annotation_value())
            if not self.accept_op(","):
                break

    def parse_annotation_value(self) -> str:
        t = self.peek()
        if t.type == TokenType.STRING:
            return self.next().value
        if t.type in (TokenType.INT, TokenType.LONG, TokenType.FLOAT, TokenType.DOUBLE):
            return self.next().value
        if t.type == TokenType.IDENT:
            return self.next().value
        self.fail("expected annotation value")

    # ------------------------------------------------------------ definitions
    def parse_definition(self, app: SiddhiApp, anns: list[Annotation]) -> None:
        self.expect_kw("define")
        anns = [a for a in anns
                if a.name.lower() != "app" and a.namespace != "app"]
        kind = self.expect_kw(
            "stream", "table", "window", "trigger", "aggregation", "function"
        )
        if kind == "stream":
            d = StreamDefinition(self.expect_ident())
            d.annotations = anns
            self.parse_attribute_list(d)
            app.define_stream(d)
        elif kind == "table":
            d = TableDefinition(self.expect_ident())
            d.annotations = anns
            self.parse_attribute_list(d)
            app.define_table(d)
        elif kind == "window":
            d = WindowDefinition(self.expect_ident())
            d.annotations = anns
            self.parse_attribute_list(d)
            if self.peek().type == TokenType.IDENT and not self.at_kw("output"):
                ns = None
                name = self.expect_ident()
                if self.accept_op("."):
                    ns, name = name, self.expect_ident()
                params: list[Expression] = []
                if self.accept_op("("):
                    params = self.parse_expression_list()
                    self.expect_op(")")
                d.window_handler = Window(None if ns in (None, "window") else ns, name, params)
            if self.accept_kw("output"):
                which = self.expect_kw("current", "expired", "all")
                self.expect_kw("events")
                d.output_event_type = {
                    "current": OutputEventType.CURRENT_EVENTS,
                    "expired": OutputEventType.EXPIRED_EVENTS,
                    "all": OutputEventType.ALL_EVENTS,
                }[which]
            app.define_window(d)
        elif kind == "trigger":
            tid = self.expect_ident()
            self.expect_kw("at")
            d = TriggerDefinition(tid, annotations=anns)
            if self.at_kw("every"):
                self.next()
                d.at_every_ms = self.parse_time_value()
            elif self.peek().type == TokenType.STRING:
                s = self.next().value
                if s.lower() == "start":
                    d.at_start = True
                else:
                    d.at_cron = s
            else:
                self.fail("expected 'start', cron string, or every <time>")
            app.define_trigger(d)
        elif kind == "aggregation":
            d = AggregationDefinition(self.expect_ident())
            d.annotations = anns
            self.expect_kw("from")
            d.basic_single_input_stream = self.parse_single_stream()
            d.selector = self.parse_selector()
            self.expect_kw("aggregate")
            if self.accept_kw("by"):
                d.aggregate_attribute = self.expect_ident()
            self.expect_kw("every")
            durations = [self._parse_duration()]
            if self.accept_op("..."):
                end = self._parse_duration()
                durations = [
                    td for td in TimePeriodDuration
                    if durations[0].order <= td.order <= end.order
                ]
            else:
                while self.accept_op(","):
                    durations.append(self._parse_duration())
            d.durations = durations
            app.define_aggregation(d)
        elif kind == "function":
            fid = self.expect_ident()
            self.expect_op("[")
            lang = self.expect_ident()
            self.expect_op("]")
            self.expect_kw("return")
            rtype = PRIMITIVE_TYPES[self.expect_kw(*PRIMITIVE_TYPES)]
            t = self.peek()
            if t.type != TokenType.SCRIPT:
                self.fail("expected function body { ... }")
            body = self.next().value
            app.define_function(FunctionDefinition(fid, lang, rtype, body, anns))

    def _parse_duration(self) -> TimePeriodDuration:
        name = self.expect_ident().lower()
        if name not in _DURATIONS:
            self.fail(f"unknown aggregation duration {name!r}")
        return _DURATIONS[name]

    def parse_attribute_list(self, d) -> None:
        self.expect_op("(")
        while not self.at_op(")"):
            name = self.expect_ident()
            tname = self.expect_kw(*PRIMITIVE_TYPES)
            d.attribute(name, PRIMITIVE_TYPES[tname])
            if not self.accept_op(","):
                break
        self.expect_op(")")

    # -------------------------------------------------------------- partition
    def parse_partition(self, anns: list[Annotation]) -> Partition:
        self.expect_kw("partition")
        self.expect_kw("with")
        self.expect_op("(")
        p = Partition(annotations=anns)
        while not self.at_op(")"):
            p.partition_types.append(self.parse_partition_type())
            if not self.accept_op(","):
                break
        self.expect_op(")")
        self.expect_kw("begin")
        while not self.at_kw("end"):
            q_anns = self.parse_annotations()
            q = self.parse_query()
            q.annotations = q_anns + q.annotations
            p.queries.append(q)
            self.accept_op(";")
        self.expect_kw("end")
        return p

    def parse_partition_type(self) -> PartitionType:
        # value: `expr of Stream`; range: `cond as 'label' or cond as 'label' ... of Stream`
        first = self.parse_expression()
        if self.at_kw("as"):
            ranges = []
            while True:
                self.expect_kw("as")
                label = self.next().value  # string literal
                ranges.append(RangePartitionProperty(label, first))
                if self.accept_kw("or"):
                    first = self.parse_expression()
                else:
                    break
            self.expect_kw("of")
            return PartitionType(self.expect_ident(), ranges=ranges)
        self.expect_kw("of")
        return PartitionType(self.expect_ident(), value_expr=first)

    # ------------------------------------------------------------------ query
    def parse_query(self) -> Query:
        q = Query()
        self.expect_kw("from")
        q.input_stream = self.parse_input_stream()
        q.selector = self.parse_selector()
        q.output_rate = self.parse_output_rate()
        q.output_stream = self.parse_output_action()
        return q

    # -- input stream dispatch ------------------------------------------------
    def parse_input_stream(self):
        kind = self._sniff_input_kind()
        if kind == "state":
            return self.parse_state_stream()
        if kind == "join":
            return self.parse_join_stream()
        return self.parse_single_stream()

    def _sniff_input_kind(self) -> str:
        """Lookahead: classify the from-clause as single / join / state."""
        if self.at_kw("every", "not"):
            return "state"
        if self.at_op("("):
            # `from (every e1=... -> e2=...) within 1 sec` — a parenthesized
            # whole-pattern: markers live at depth 1 (WithinPatternTestCase
            # testQuery2/3 shape)
            i, depth = self.pos, 0
            toks = self.tokens
            while i < len(toks):
                t = toks[i]
                if t.type == TokenType.OP:
                    if t.value in ("(", "["):
                        depth += 1
                    elif t.value in (")", "]"):
                        depth -= 1
                        if depth == 0:
                            break
                    elif depth == 1 and t.value in ("->", ",", "="):
                        return "state"
                elif t.type == TokenType.IDENT and depth == 1 and \
                        t.value.lower() in ("every", "not"):
                    return "state"
                i += 1
        depth = 0
        i = self.pos
        toks = self.tokens
        while i < len(toks):
            t = toks[i]
            if t.type == TokenType.OP:
                if t.value in ("(", "["):
                    depth += 1
                elif t.value in (")", "]"):
                    depth -= 1
                elif depth == 0 and t.value == "->":
                    return "state"
                elif depth == 0 and t.value == ",":
                    return "state"  # sequence
                elif depth == 0 and t.value == "=":
                    return "state"  # event binding e1=S
            elif t.type == TokenType.IDENT and depth == 0:
                v = t.value.lower()
                if v in _QUERY_SECTION_KW:
                    break
                nxt = toks[i + 1].value.lower() if i + 1 < len(toks) else ""
                if v == "join" or (v == "inner" and nxt == "join") or (
                    v in ("left", "right", "full") and nxt == "outer"
                ):
                    return "join"
            i += 1
        return "single"

    # -- single stream --------------------------------------------------------
    def parse_single_stream(self) -> SingleInputStream:
        is_inner = bool(self.accept_op("#"))
        is_fault = bool(self.accept_op("!"))
        sid = self.expect_ident()
        s = SingleInputStream(sid, is_fault_stream=is_fault, is_inner_stream=is_inner)
        self._parse_stream_handlers(s)
        if self.accept_kw("as"):
            s.alias = self.expect_ident()
        return s

    def _parse_stream_handlers(self, s: SingleInputStream) -> None:
        while True:
            if self.at_op("["):
                self.next()
                s.handlers.append(Filter(self.parse_expression()))
                self.expect_op("]")
            elif self.at_op("#"):
                self.next()
                ns = None
                name = self.expect_ident()
                if self.accept_op(":"):
                    ns, name = name, self.expect_ident()
                if self.accept_op("."):
                    # `#window.length(..)` → window; `#ns.name` keeps ns
                    sub = self.expect_ident()
                    if name.lower() == "window" and ns is None:
                        ns, name = None, sub
                        is_window = True
                    else:
                        ns, name = name, sub
                        is_window = False
                else:
                    is_window = False
                params: list[Expression] = []
                if self.accept_op("("):
                    params = self.parse_expression_list()
                    self.expect_op(")")
                if is_window:
                    s.handlers.append(Window(None, name, params))
                else:
                    s.handlers.append(StreamFunction(ns, name, params))
            else:
                break

    # -- join stream ----------------------------------------------------------
    def parse_join_stream(self) -> JoinInputStream:
        left = self.parse_single_stream()
        trigger = EventTrigger.ALL
        if self.accept_kw("unidirectional"):
            trigger = EventTrigger.LEFT
        jt = self._parse_join_type()
        right = self.parse_single_stream()
        if self.accept_kw("unidirectional"):
            trigger = EventTrigger.RIGHT
        on = None
        within = None
        per = None
        if self.accept_kw("on"):
            on = self.parse_expression()
        if self.accept_kw("within"):
            first = self.parse_expression()
            if self.accept_op(","):
                within = (first, self.parse_expression())
            else:
                within = first
        if self.accept_kw("per"):
            per = self.parse_expression()
        return JoinInputStream(left, jt, right, on, trigger, within, per)

    def _parse_join_type(self) -> JoinType:
        if self.accept_kw("join"):
            return JoinType.JOIN
        if self.accept_kw("inner"):
            self.expect_kw("join")
            return JoinType.INNER_JOIN
        side = self.expect_kw("left", "right", "full")
        self.expect_kw("outer")
        self.expect_kw("join")
        return {
            "left": JoinType.LEFT_OUTER_JOIN,
            "right": JoinType.RIGHT_OUTER_JOIN,
            "full": JoinType.FULL_OUTER_JOIN,
        }[side]

    # -- pattern / sequence ---------------------------------------------------
    def parse_state_stream(self) -> StateInputStream:
        # detect sequence by a top-level ',' before query-section keywords
        is_sequence = self._state_is_sequence()
        sep = "," if is_sequence else "->"
        state = self._parse_state_chain(sep, is_sequence)
        within = None
        if self.accept_kw("within"):
            within = self._parse_within_value()
        return StateInputStream(
            StateInputStreamType.SEQUENCE if is_sequence else StateInputStreamType.PATTERN,
            state,
            within,
        )

    def _state_is_sequence(self) -> bool:
        depth = 0
        i = self.pos
        toks = self.tokens
        while i < len(toks):
            t = toks[i]
            if t.type == TokenType.OP:
                if t.value in ("(", "["):
                    depth += 1
                elif t.value in (")", "]"):
                    depth -= 1
                elif depth == 0 and t.value == "->":
                    return False
                elif depth == 0 and t.value == ",":
                    return True
            elif t.type == TokenType.IDENT and depth == 0 and t.value.lower() in _QUERY_SECTION_KW:
                break
            i += 1
        return False

    def _try_element_within(self) -> Optional["Constant"]:
        """Consume a per-element `within <t>` only when the pattern continues after
        it; a trailing `within` belongs to the whole state stream (rollback)."""
        if not self.at_kw("within"):
            return None
        saved = self.pos
        self.next()
        w = self._parse_within_value()
        if self.at_kw(*_QUERY_SECTION_KW) or self.peek().type == TokenType.EOF:
            self.pos = saved
            return None
        return w

    def _parse_state_chain(self, sep: str, is_sequence: bool) -> StateElement:
        elems = [self._parse_state_unit(is_sequence)]
        while self.at_op(sep):
            self.next()
            elems.append(self._parse_state_unit(is_sequence))
        # right-fold into NextStateElement chain
        state = elems[-1]
        for e in reversed(elems[:-1]):
            state = NextStateElement(e, state)
        return state

    def _parse_state_unit(self, is_sequence: bool) -> StateElement:
        if self.accept_kw("every"):
            if self.at_op("("):
                self.next()
                inner = self._parse_state_chain("," if is_sequence else "->", is_sequence)
                self.expect_op(")")
                el: StateElement = EveryStateElement(inner)
            else:
                el = EveryStateElement(self._parse_logical_unit(is_sequence))
            el.within = self._try_element_within()
            return el
        if self.at_op("("):
            self.next()
            inner = self._parse_state_chain("," if is_sequence else "->", is_sequence)
            self.expect_op(")")
            w = self._try_element_within()
            if w is not None:
                inner.within = w
            return inner
        return self._parse_logical_unit(is_sequence)

    def _parse_logical_unit(self, is_sequence: bool) -> StateElement:
        first = self._parse_state_primary(is_sequence)
        if self.at_kw("and", "or"):
            op = LogicalType(self.next().value.lower())
            second = self._parse_state_primary(is_sequence)
            el = LogicalStateElement(first, op, second)
            el.within = self._try_element_within()
            return el
        return first

    def _parse_state_primary(self, is_sequence: bool) -> StateElement:
        if self.accept_kw("not"):
            stream = self._parse_state_basic_stream()
            waiting = None
            if self.accept_kw("for"):
                waiting = self.parse_time_value()
            return AbsentStreamStateElement(stream, waiting)
        # optional event binding `e1=`
        alias = None
        if (
            self.peek().type == TokenType.IDENT
            and self.at_op("=", offset=1)
        ):
            alias = self.next().value
            self.next()  # '='
        stream = self._parse_state_basic_stream()
        if alias:
            stream.alias = alias
        sse = StreamStateElement(stream)
        # counting / kleene postfix: <n>, <n:m>, <n:>, <:m>
        if self.at_op("<"):
            self.next()
            if self.at_op(":"):          # `<:m>` — unspecified min is 0
                mn = 0
            else:
                mn = int(self.next().value)
            mx = mn
            if self.accept_op(":"):
                if self.peek().type == TokenType.INT:
                    mx = int(self.next().value)
                else:
                    mx = -1
            self.expect_op(">")
            el: StateElement = CountStateElement(sse, mn, mx)
        elif self.at_op("*") and is_sequence:
            self.next()
            el = CountStateElement(sse, 0, -1)
        elif self.at_op("+") and is_sequence:
            self.next()
            el = CountStateElement(sse, 1, -1)
        elif self.at_op("?") and is_sequence:
            self.next()
            el = CountStateElement(sse, 0, 1)
        else:
            el = sse
        el.within = self._try_element_within()
        return el

    def _parse_state_basic_stream(self) -> SingleInputStream:
        is_inner = bool(self.accept_op("#"))
        sid = self.expect_ident()
        s = SingleInputStream(sid, is_inner_stream=is_inner)
        self._parse_stream_handlers(s)
        return s

    def _parse_within_value(self) -> Constant:
        ms = self.parse_time_value()
        return Constant(ms, DataType.LONG, is_time=True)

    # --------------------------------------------------------------- selector
    def parse_selector(self) -> Selector:
        sel = Selector()
        if self.accept_kw("select"):
            if self.accept_op("*"):
                sel.select_all = True
            else:
                while True:
                    expr = self.parse_expression()
                    rename = None
                    if self.accept_kw("as"):
                        rename = self.expect_ident()
                    sel.attributes.append(OutputAttribute(rename, expr))
                    if not self.accept_op(","):
                        break
        else:
            sel.select_all = True
        if self.at_kw("group"):
            self.next()
            self.expect_kw("by")
            while True:
                v = self.parse_variable_ref()
                sel.group_by.append(v)
                if not self.accept_op(","):
                    break
        if self.accept_kw("having"):
            sel.having = self.parse_expression()
        if self.at_kw("order"):
            self.next()
            self.expect_kw("by")
            while True:
                v = self.parse_variable_ref()
                order = OrderByOrder.ASC
                if self.at_kw("asc", "desc"):
                    order = OrderByOrder(self.next().value.lower())
                sel.order_by.append(OrderByAttribute(v, order))
                if not self.accept_op(","):
                    break
        if self.accept_kw("limit"):
            sel.limit = int(self.next().value)
        if self.accept_kw("offset"):
            sel.offset = int(self.next().value)
        return sel

    # ------------------------------------------------------------ output rate
    def parse_output_rate(self):
        if not self.at_kw("output"):
            return None
        # don't consume `output` of `output snapshot`? both are rates; handle all here
        self.next()
        if self.accept_kw("snapshot"):
            self.expect_kw("every")
            return SnapshotOutputRate(self.parse_time_value())
        rtype = OutputRateType.ALL
        kw = self.accept_kw("all", "first", "last")
        if kw:
            rtype = OutputRateType(kw)
        self.expect_kw("every")
        t = self.peek()
        if t.type == TokenType.INT and self.at_kw("events", offset=1):
            n = int(self.next().value)
            self.expect_kw("events")
            return EventOutputRate(n, rtype)
        ms = self.parse_time_value()
        return TimeOutputRate(ms, rtype)

    # ---------------------------------------------------------- output action
    def parse_output_action(self):
        if self.accept_kw("insert"):
            events_for = self._parse_events_for()
            self.expect_kw("into")
            is_inner = bool(self.accept_op("#"))
            is_fault = bool(self.accept_op("!"))
            target = self.expect_ident()
            return InsertIntoStream(target, events_for, is_fault, is_inner)
        if self.accept_kw("delete"):
            target = self.expect_ident()
            self._parse_events_for()
            self.expect_kw("on")
            return DeleteStream(target, self.parse_expression())
        if self.accept_kw("update"):
            if self.accept_kw("or"):
                self.expect_kw("insert")
                self.expect_kw("into")
                target = self.expect_ident()
                sets = self._parse_set_clause()
                on = None
                if self.accept_kw("on"):
                    on = self.parse_expression()
                return UpdateOrInsertStream(target, on, sets)
            target = self.expect_ident()
            self._parse_events_for()
            sets = self._parse_set_clause()
            self.expect_kw("on")
            return UpdateStream(target, self.parse_expression(), sets)
        if self.accept_kw("return"):
            events_for = self._parse_events_for()
            return ReturnStream(events_for)
        return ReturnStream()

    def _parse_events_for(self) -> OutputEventsFor:
        kw = self.accept_kw("current", "expired", "all")
        if kw:
            self.expect_kw("events")
            return OutputEventsFor(kw)
        if self.at_kw("for"):
            self.next()
            kw = self.expect_kw("current", "expired", "all")
            self.expect_kw("events")
            return OutputEventsFor(kw)
        return OutputEventsFor.CURRENT_EVENTS

    def _parse_set_clause(self) -> list[UpdateSetAttribute]:
        sets: list[UpdateSetAttribute] = []
        if self.accept_kw("set"):
            while True:
                var = self.parse_variable_ref()
                self.expect_op("=")
                sets.append(UpdateSetAttribute(var, self.parse_expression()))
                if not self.accept_op(","):
                    break
        return sets

    # --------------------------------------------------------- on-demand query
    def parse_on_demand_query(self) -> OnDemandQuery:
        anns = self.parse_annotations()
        if self.accept_kw("from"):
            store = self.expect_ident()
            on = None
            if self.accept_kw("on"):
                on = self.parse_expression()
            within = None
            per = None
            if self.accept_kw("within"):
                first = self.parse_expression()
                if self.accept_op(","):
                    within = (first, self.parse_expression())
                else:
                    within = (first,)
            if self.accept_kw("per"):
                per = self.parse_expression()
            sel = self.parse_selector()
            action = self.parse_output_action()
            if isinstance(action, InsertIntoStream):
                return OnDemandQuery(OnDemandQueryType.INSERT, store, on, sel, action,
                                     within=within, per=per)
            if isinstance(action, DeleteStream):
                return OnDemandQuery(OnDemandQueryType.DELETE, store, on, sel, action,
                                     within=within, per=per)
            if isinstance(action, UpdateOrInsertStream):
                return OnDemandQuery(OnDemandQueryType.UPDATE_OR_INSERT, store, on, sel,
                                     action, within=within, per=per)
            if isinstance(action, UpdateStream):
                return OnDemandQuery(OnDemandQueryType.UPDATE, store, on, sel, action,
                                     within=within, per=per)
            return OnDemandQuery(OnDemandQueryType.FIND, store, on, sel, None,
                                 within=within, per=per)
        # `select ... insert into T` / `update T ...` / `delete T on ...` forms
        sel = self.parse_selector()
        action = self.parse_output_action()
        type_map = {
            InsertIntoStream: OnDemandQueryType.INSERT,
            DeleteStream: OnDemandQueryType.DELETE,
            UpdateStream: OnDemandQueryType.UPDATE,
            UpdateOrInsertStream: OnDemandQueryType.UPDATE_OR_INSERT,
        }
        qt = type_map.get(type(action))
        if qt is None:
            self.fail("on-demand query needs a table action or 'from'")
        target = getattr(action, "target_id", None)
        return OnDemandQuery(qt, target, getattr(action, "on_condition", None), sel, action)

    # ------------------------------------------------------------- expressions
    def parse_expression(self) -> Expression:
        return self.parse_or()

    def parse_expression_list(self) -> list[Expression]:
        if self.at_op(")"):
            return []
        out = [self.parse_expression()]
        while self.accept_op(","):
            out.append(self.parse_expression())
        return out

    def parse_or(self) -> Expression:
        left = self.parse_and()
        while self.at_kw("or"):
            self.next()
            left = Or(left, self.parse_and())
        return left

    def parse_and(self) -> Expression:
        left = self.parse_not()
        while self.at_kw("and"):
            self.next()
            left = And(left, self.parse_not())
        return left

    def parse_not(self) -> Expression:
        if self.accept_kw("not"):
            return Not(self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> Expression:
        left = self.parse_postfix()
        while self.at_op("<", "<=", ">", ">=", "==", "!="):
            op = CompareOp(self.next().value)
            right = self.parse_postfix()
            left = Compare(left, op, right)
        return left

    def parse_postfix(self) -> Expression:
        left = self.parse_additive()
        while True:
            if self.at_kw("is") and self.at_kw("null", offset=1):
                self.next(); self.next()
                if isinstance(left, Variable) and left.stream_id is None \
                        and left.stream_index is not None:
                    # `e1[1] is null` — unambiguous alias reference
                    left = IsNull(None, left.attribute, left.stream_index)
                else:
                    # bare name: executor context decides attribute vs alias
                    left = IsNull(left)
            elif self.accept_kw("in"):
                left = In(left, self.expect_ident())
            else:
                break
        return left

    def parse_additive(self) -> Expression:
        left = self.parse_multiplicative()
        while self.at_op("+", "-"):
            op = MathOp(self.next().value)
            left = MathExpr(left, op, self.parse_multiplicative())
        return left

    def parse_multiplicative(self) -> Expression:
        left = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = MathOp(self.next().value)
            left = MathExpr(left, op, self.parse_unary())
        return left

    def parse_unary(self) -> Expression:
        if self.accept_op("-"):
            return Minus(self.parse_unary())
        if self.accept_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> Expression:
        t = self.peek()
        if t.type == TokenType.OP and t.value == "(":
            self.next()
            e = self.parse_expression()
            self.expect_op(")")
            return e
        if t.type == TokenType.STRING:
            self.next()
            return Constant(t.value, DataType.STRING)
        if t.type in (TokenType.INT, TokenType.LONG):
            # time constant? `10 sec`
            if self.peek(1).type == TokenType.IDENT and self.peek(1).value.lower() in TIME_UNITS:
                return Constant(self.parse_time_value(), DataType.LONG, is_time=True)
            self.next()
            v = int(t.value)
            return Constant(v, DataType.LONG if t.type == TokenType.LONG else DataType.INT)
        if t.type == TokenType.FLOAT:
            self.next()
            return Constant(float(t.value), DataType.FLOAT)
        if t.type == TokenType.DOUBLE:
            self.next()
            return Constant(float(t.value), DataType.DOUBLE)
        if t.type == TokenType.IDENT:
            low = t.value.lower()
            if low == "true":
                self.next()
                return Constant(True, DataType.BOOL)
            if low == "false":
                self.next()
                return Constant(False, DataType.BOOL)
            return self.parse_name_expression()
        self.fail("expected expression")

    def parse_name_expression(self) -> Expression:
        """Variable (`a`, `s.a`, `e1[0].a`) or function call (`ns:f(..)`, `f(..)`)."""
        name = self.expect_ident()
        # function with namespace `ns:f(...)`
        if self.at_op(":") and self.peek(1).type == TokenType.IDENT and self.at_op("(", offset=2):
            self.next()
            fname = self.expect_ident()
            self.expect_op("(")
            args = self.parse_expression_list()
            self.expect_op(")")
            return AttributeFunction(name, fname, args)
        if self.at_op("("):
            self.next()
            args = self.parse_expression_list()
            self.expect_op(")")
            return AttributeFunction(None, name, args)
        # stream index `e1[0].a` / `e1[last].a`
        idx: Optional[int] = None
        if self.at_op("[") and (
            self.peek(1).type == TokenType.INT
            or (self.peek(1).type == TokenType.IDENT and self.peek(1).value.lower() == "last")
        ) and self.at_op("]", offset=2):
            self.next()
            it = self.next()
            idx = LAST_INDEX if it.type == TokenType.IDENT else int(it.value)
            self.expect_op("]")
        if self.accept_op("."):
            attr = self.expect_ident()
            return Variable(attribute=attr, stream_id=name, stream_index=idx)
        return Variable(attribute=name, stream_index=idx)

    def parse_variable_ref(self) -> Variable:
        e = self.parse_name_expression()
        if not isinstance(e, Variable):
            self.fail("expected attribute reference")
        return e

    # ------------------------------------------------------------- time values
    def parse_time_value(self) -> int:
        """`1 hour 20 min` → milliseconds (sums unit terms). A bare integer is
        accepted as milliseconds (superset of SiddhiQL)."""
        total = 0
        seen = False
        while self.peek().type in (TokenType.INT, TokenType.LONG) and (
            self.peek(1).type == TokenType.IDENT
            and self.peek(1).value.lower() in TIME_UNITS
        ):
            n = int(self.next().value)
            unit = self.next().value.lower()
            total += n * TIME_UNITS[unit]
            seen = True
        if not seen:
            if self.peek().type in (TokenType.INT, TokenType.LONG):
                return int(self.next().value)
            self.fail("expected time value")
        return total
