"""REST deployment service.

Reference: ``modules/siddhi-service`` —
``impl/SiddhiApiServiceImpl.java:45`` (deploy ``:51``, undeploy ``:100``): a
small HTTP wrapper that deploys SiddhiQL app text onto a shared
``SiddhiManager``, keeps runtimes + input handlers by app name, and undeploys
on request. Endpoints (stdlib http.server, threaded; no framework deps):

    POST   /siddhi-apps                      body = SiddhiQL text → deploy+start
    GET    /siddhi-apps                      list deployed app names
    GET    /siddhi-apps/{name}/status        {"state": "running"|"stopped"}
    GET    /siddhi-apps/{name}/flow          flow-control stats (WAL bytes,
                                             watermarks, queue depth/credits,
                                             shed counts, adaptive batch size)
    POST   /siddhi-apps/{name}/recover       checkpoint restore + WAL replay
                                             (flow/recovery.py); body may be
                                             JSON {"revision": "..."}
    GET    /siddhi-apps/{name}/error-store   stored failed events
                                             (?stream=S filters)
    POST   /siddhi-apps/{name}/error-store/replay
                                             re-inject stored entries; body
                                             may be JSON {"stream": "S",
                                             "ids": [lo, hi]}
    GET    /siddhi-apps/{name}/resilience    sink circuit/retry stats, device
                                             quarantine state, chaos counters
    GET    /siddhi-apps/{name}/dcn           multi-host shard state: peer
                                             health, retry/spill counters,
                                             lane-group ownership, failover
                                             counts (apps with an attached
                                             ``runtime.dcn_worker``)
    GET    /siddhi-apps/{name}/metrics       Prometheus 0.0.4 text exposition
                                             of the app's statistics (tail
                                             buckets carry trace exemplars
                                             when @app:trace sampled one)
    GET    /metrics                          same, across every deployed app
    GET    /siddhi-apps/{name}/trace         sampled pipeline span chains
                                             (@app:trace); ?limit=N caps it,
                                             ?stream=S filters by ingress
                                             stream
    GET    /siddhi-apps/{name}/latency       X-Ray detection-latency
                                             attribution: per-query phase
                                             histograms + end-to-end
                                             reconciliation
    GET    /siddhi-apps/{name}/flightrecorder
                                             control-plane transition ring
                                             (?category=, ?limit=,
                                             ?since_ns= incremental-tail
                                             cursor filters)
    GET    /mesh                             mesh-fabric state (placement
                                             plan, per-host evidence,
                                             migration/recovery counters,
                                             recent decisions) when a
                                             MeshFabric is attached via
                                             ``service.attach_mesh``
    GET    /siddhi-apps/{name}/slo           SLO-autopilot state: per-query
                                             class/budget vs windowed p99,
                                             controller decisions + ladder
                                             position (fleet tenants with
                                             @app:fleet slo.* keys)
    DELETE /siddhi-apps/{name}               undeploy (shutdown + forget)
    POST   /siddhi-apps/{name}/streams/{sid} body = JSON {"data": [...],
                                             "timestamp": ms?} → send event

Responses are JSON ``{"status": "OK"|"ERROR", "message": ...}`` like the
reference's ``ApiResponseMessage``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .core.manager import SiddhiManager


class SiddhiService:
    """Deploy/undeploy SiddhiQL apps over HTTP on a shared manager."""

    def __init__(self, manager: Optional[SiddhiManager] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 playback: bool = False):
        self.manager = manager or SiddhiManager()
        self.playback = playback
        self._lock = threading.Lock()
        self.runtimes: dict[str, object] = {}
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):        # quiet by default
                pass

            def _reply(self, code: int, payload: dict) -> None:
                self._reply_text(code, json.dumps(payload),
                                 "application/json")

            def _reply_text(self, code: int, text: str,
                            content_type: str) -> None:
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n) if n else b""

            def _wants_openmetrics(self) -> bool:
                # exemplars ride only the OpenMetrics exposition — strict
                # 0.0.4 parsers reject them, so the scraper must ask
                return "application/openmetrics-text" in \
                    (self.headers.get("Accept") or "")

            def _parse_limit(self, query: dict):
                """``?limit=`` → (ok, limit|None); replies 400 itself on a
                malformed value (shared by the ring-paging endpoints)."""
                return self._parse_nonneg(query, "limit")

            def _parse_nonneg(self, query: dict, key: str):
                value = query.get(key)
                try:
                    value = int(value) if value else None
                    if value is not None and value < 0:
                        raise ValueError(value)
                except ValueError:
                    self._reply(400, {
                        "status": "ERROR",
                        "message": f"{key} must be a non-negative integer"})
                    return False, None
                return True, value

            def do_POST(self):
                parts = [p for p in self.path.split("/") if p]
                if parts == ["siddhi-apps"]:
                    code, payload = service.deploy(self._body().decode())
                elif len(parts) == 4 and parts[0] == "siddhi-apps" \
                        and parts[2] == "streams":
                    code, payload = service.send_event(
                        parts[1], parts[3], self._body().decode())
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "recover":
                    code, payload = service.recover(
                        parts[1], self._body().decode())
                elif len(parts) == 4 and parts[0] == "siddhi-apps" \
                        and parts[2:] == ["error-store", "replay"]:
                    code, payload = service.replay_errors(
                        parts[1], self._body().decode())
                else:
                    code, payload = 404, {"status": "ERROR",
                                          "message": "unknown path"}
                self._reply(code, payload)

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse
                url = urlparse(self.path)
                query = {k: v[0] for k, v in parse_qs(url.query).items()}
                parts = [p for p in url.path.split("/") if p]
                if parts == ["siddhi-apps"]:
                    self._reply(200, {"status": "OK",
                                      "apps": sorted(service.runtimes)})
                elif parts == ["mesh"]:
                    code, payload = service.mesh_stats()
                    self._reply(code, payload)
                elif parts == ["mesh", "latency"]:
                    code, payload = service.mesh_latency()
                    self._reply(code, payload)
                elif parts == ["metrics"]:
                    code, text, ctype = service.metrics_text(
                        None, openmetrics=self._wants_openmetrics())
                    self._reply_text(code, text, ctype)
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "metrics":
                    code, text, ctype = service.metrics_text(
                        parts[1], openmetrics=self._wants_openmetrics())
                    if code == 200:
                        self._reply_text(code, text, ctype)
                    else:
                        self._reply(code, {"status": "ERROR",
                                           "message": text})
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "trace":
                    ok, limit = self._parse_limit(query)
                    if not ok:
                        return
                    code, payload = service.trace_export(
                        parts[1], limit, query.get("stream"))
                    self._reply(code, payload)
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "latency":
                    code, payload = service.latency_stats(parts[1])
                    self._reply(code, payload)
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "flightrecorder":
                    ok, limit = self._parse_limit(query)
                    if not ok:
                        return
                    ok, since_ns = self._parse_nonneg(query, "since_ns")
                    if not ok:
                        return
                    code, payload = service.flight_export(
                        parts[1], query.get("category"), limit, since_ns)
                    self._reply(code, payload)
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "slo":
                    code, payload = service.slo_stats(parts[1])
                    self._reply(code, payload)
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "status":
                    code, payload = service.status(parts[1])
                    self._reply(code, payload)
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "flow":
                    code, payload = service.flow_stats(parts[1])
                    self._reply(code, payload)
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "error-store":
                    code, payload = service.error_store_entries(
                        parts[1], query.get("stream"))
                    self._reply(code, payload)
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "resilience":
                    code, payload = service.resilience_stats(parts[1])
                    self._reply(code, payload)
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "dcn":
                    code, payload = service.dcn_stats(parts[1])
                    self._reply(code, payload)
                elif len(parts) == 3 and parts[0] == "siddhi-apps" \
                        and parts[2] == "fleet":
                    code, payload = service.fleet_stats(parts[1])
                    self._reply(code, payload)
                else:
                    self._reply(404, {"status": "ERROR",
                                      "message": "unknown path"})

            def do_DELETE(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) == 2 and parts[0] == "siddhi-apps":
                    code, payload = service.undeploy(parts[1])
                    self._reply(code, payload)
                else:
                    self._reply(404, {"status": "ERROR",
                                      "message": "unknown path"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None
        self.mesh = None                # MeshFabric via attach_mesh()

    # -- mesh fabric -----------------------------------------------------------
    def attach_mesh(self, fabric) -> None:
        """Attach a :class:`~siddhi_tpu.mesh.MeshFabric` so ``GET /mesh``
        serves its placement plan, per-host evidence and decision trail
        (the fabric is engine-level, not app-level — one per mesh)."""
        self.mesh = fabric

    def mesh_stats(self) -> tuple[int, dict]:
        if self.mesh is None:
            return 200, {"status": "OK", "enabled": False}
        return 200, {"status": "OK", "enabled": True, **self.mesh.report()}

    def mesh_latency(self) -> tuple[int, dict]:
        """Federated latency breakdown across the process mesh: one pull
        of every live worker's phase histograms, rendered per-worker plus
        the fabric-level merge (``GET /mesh/latency``)."""
        if self.mesh is None:
            return 200, {"status": "OK", "enabled": False}
        try:
            fed = self.mesh.federation()
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            return 500, {"status": "ERROR", "message": str(e)}
        return 200, {"status": "OK", "enabled": True, **fed}

    # -- operations (also usable programmatically) -----------------------------
    def deploy(self, app_text: str) -> tuple[int, dict]:
        with self._lock:
            try:
                from .compiler import parse, update_variables
                parsed = parse(update_variables(
                    app_text, None, self.manager.context.config_manager)
                    if "${" in app_text else app_text)
            except Exception as e:
                return 400, {"status": "ERROR", "message": str(e)}
            # duplicate check BEFORE registering — creating first would clobber
            # the running app's slot in manager.runtimes; an app created
            # programmatically on the shared manager counts as a duplicate too
            if parsed.name() in self.runtimes or \
                    parsed.name() in self.manager.runtimes:
                return 409, {"status": "ERROR",
                             "message": f"app '{parsed.name()}' already deployed"}
            try:
                rt = self.manager.create_siddhi_app_runtime(
                    parsed, playback=self.playback)
            except Exception as e:
                return 400, {"status": "ERROR", "message": str(e)}
            try:
                rt.start()
            except Exception as e:
                self.manager.runtimes.pop(rt.name, None)
                return 500, {"status": "ERROR",
                             "message": f"start failed: {e}"}
            self.runtimes[rt.name] = rt
            return 200, {"status": "OK", "name": rt.name,
                         "message": "Siddhi app deployed and runtime created"}

    def undeploy(self, name: str) -> tuple[int, dict]:
        with self._lock:
            rt = self.runtimes.pop(name, None)
            if rt is None:
                return 404, {"status": "ERROR",
                             "message": f"no app '{name}' deployed"}
            rt.shutdown()
            self.manager.runtimes.pop(name, None)
            return 200, {"status": "OK",
                         "message": "Siddhi app removed successfully"}

    def status(self, name: str) -> tuple[int, dict]:
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        running = getattr(rt, "_started", False)
        return 200, {"status": "OK",
                     "state": "running" if running else "stopped"}

    def send_event(self, name: str, stream_id: str,
                   body: str) -> tuple[int, dict]:
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        try:
            payload = json.loads(body)
            data = payload["data"]
            ts = payload.get("timestamp")
            rt.input_handler(stream_id).send(data, timestamp=ts)
        except Exception as e:
            return 400, {"status": "ERROR", "message": str(e)}
        return 200, {"status": "OK", "message": "event sent"}

    def flow_stats(self, name: str) -> tuple[int, dict]:
        """Flow-control observability: WAL/backpressure stats plus any
        adaptive device batch sizes."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        flow = getattr(rt, "flow", None)
        payload = {"status": "OK"}
        payload.update(flow.stats_report() if flow is not None
                       else {"enabled": False, "streams": {}})
        adaptive = {}
        for bridge in getattr(rt, "device_bridges", []):
            ctrl = getattr(bridge.runtime, "batch_controller", None)
            if ctrl is not None:
                adaptive[bridge.query_name] = ctrl.report()
        if adaptive:
            payload["adaptive"] = adaptive
        return 200, payload

    def error_store_entries(self, name: str,
                            stream: Optional[str] = None) -> tuple[int, dict]:
        """Stored failed events awaiting replay (GET .../error-store)."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        store = rt.ctx.siddhi_context.error_store
        if store is None:
            return 200, {"status": "OK", "entries": []}
        from dataclasses import asdict
        entries = [asdict(e) for e in store.load(name, stream)]
        # event data may hold non-JSON values (OBJECT attributes) — stringify
        for e in entries:
            e["event_data"] = [
                v if isinstance(v, (str, int, float, bool, type(None)))
                else repr(v) for v in e["event_data"]]
        return 200, {"status": "OK", "entries": entries}

    def replay_errors(self, name: str, body: str = "") -> tuple[int, dict]:
        """Re-inject stored entries (POST .../error-store/replay); body may
        narrow by {"stream": "...", "ids": [lo, hi]}."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        store = rt.ctx.siddhi_context.error_store
        if store is None:
            return 400, {"status": "ERROR",
                         "message": "no error store configured"}
        stream = min_id = max_id = None
        if body.strip():
            try:
                payload = json.loads(body)
                stream = payload.get("stream")
                ids = payload.get("ids")
                if ids is not None:
                    min_id, max_id = int(ids[0]), int(ids[1])
            except (ValueError, TypeError, IndexError, AttributeError):
                return 400, {"status": "ERROR",
                             "message": 'body must be JSON like {"stream": '
                                        '"S", "ids": [lo, hi]} or empty'}
        try:
            report = store.replay(rt, stream, min_id, max_id)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            return 500, {"status": "ERROR", "message": str(e)}
        return 200, {"status": "OK", **report}

    def metrics_text(self, name: Optional[str],
                     openmetrics: bool = False) -> tuple[int, str, str]:
        """Prometheus text exposition: one app, or every deployed app when
        ``name`` is None (the all-apps scrape endpoint). Returns
        ``(code, text, content_type)``; with ``openmetrics=True`` the
        exposition carries trace-id exemplars and the ``# EOF`` terminator
        under the OpenMetrics content type."""
        from .observability import CONTENT_TYPE, render
        from .observability.prometheus import OPENMETRICS_CONTENT_TYPE
        ctype = OPENMETRICS_CONTENT_TYPE if openmetrics else CONTENT_TYPE
        if name is None:
            managers = [rt.ctx.statistics_manager
                        for _, rt in sorted(self.runtimes.items())]
        else:
            rt = self.runtimes.get(name)
            if rt is None:
                return 404, f"no app '{name}' deployed", CONTENT_TYPE
            managers = [rt.ctx.statistics_manager]
        collectors = ()
        if name is None and self.mesh is not None \
                and self.mesh.supervisor is not None:
            # federate the process mesh on the all-apps scrape: pull every
            # live worker's tracker state, then render per-worker families
            # plus the fabric merge alongside the parent's own
            try:
                self.mesh.sync_children()
            except Exception:  # noqa: BLE001 — stale caches still render
                pass
            collectors = (self.mesh.collect_federated,)
        text = render(managers, with_exemplars=openmetrics,
                      collectors=collectors)
        if openmetrics:
            text += "# EOF\n"
        return 200, text, ctype

    def trace_export(self, name: str, limit: Optional[int] = None,
                     stream: Optional[str] = None) -> tuple[int, dict]:
        """Sampled span chains from the app's @app:trace ring; ``stream``
        filters by ingress stream so a 2048-deep ring is usable without
        client-side paging."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        return 200, {"status": "OK",
                     **rt.observability.trace_export(limit, stream)}

    def latency_stats(self, name: str) -> tuple[int, dict]:
        """X-Ray detection-latency attribution: per-query per-phase
        percentiles reconciled against the end-to-end histogram."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        return 200, {"status": "OK", **rt.observability.latency_report()}

    def flight_export(self, name: str, category: Optional[str] = None,
                      limit: Optional[int] = None,
                      since_ns: Optional[int] = None) -> tuple[int, dict]:
        """The app's flight-recorder ring: timestamped control-plane
        transitions (AIMD resizes, flush causes, breaker flips, ejections,
        SLO decisions, takeovers), trace-cross-referenced where provoked
        by a traced batch. ``since_ns`` tails the ring incrementally: pass
        the largest ``t_ns`` already seen, only newer entries return."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        return 200, {"status": "OK",
                     **rt.observability.flight_export(category, limit,
                                                      since_ns)}

    def slo_stats(self, name: str) -> tuple[int, dict]:
        """SLO-autopilot state for one tenant app: its queries' declared
        class/budget against the windowed measured p99, plus each attached
        group controller's ladder position and recent decision log."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        queries, controllers, seen = [], [], set()
        for b in getattr(rt, "fleet_bridges", []):
            member = b.member
            group = member.group if member.group is not None else b.group
            t = getattr(member, "slo", None)
            if t is not None:
                queries.append(t.report())
            ctrl = getattr(group, "slo", None)
            if ctrl is not None and id(ctrl) not in seen:
                seen.add(id(ctrl))
                controllers.append(ctrl.report())
        if not queries and not controllers:
            return 200, {"status": "OK", "enabled": False}
        return 200, {"status": "OK", "enabled": True, "queries": queries,
                     "controllers": controllers}

    def resilience_stats(self, name: str) -> tuple[int, dict]:
        """Sink circuits/retries, device quarantine, chaos counters."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        resilience = getattr(rt, "resilience", None)
        payload = {"status": "OK"}
        payload.update(resilience.report() if resilience is not None
                       else {"sinks": [], "device": []})
        return 200, payload

    def dcn_stats(self, name: str) -> tuple[int, dict]:
        """Multi-host shard state (peer health / spill / failover). A
        sharded deployment attaches its :class:`~siddhi_tpu.tpu.dcn.
        DCNWorker` as ``runtime.dcn_worker``; single-host apps report
        ``enabled: false``."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        worker = getattr(rt, "dcn_worker", None)
        if worker is None:
            return 200, {"status": "OK", "enabled": False}
        return 200, {"status": "OK", "enabled": True, **worker.report()}

    def fleet_stats(self, name: str) -> tuple[int, dict]:
        """Fleet-tier guard state for one tenant app: its enrolled lanes
        (with per-tenant ejection/circuit/shed evidence), the shape groups
        it belongs to (guard + fair-share reports), and the engine-wide
        solo-fallback log so quietly degraded fleets are visible."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        bridges = getattr(rt, "fleet_bridges", [])
        if not bridges:
            return 200, {"status": "OK", "enabled": False}
        mgr = self.manager.fleet
        stats = mgr.stats()
        keys = {b.group.shape_key for b in bridges}
        return 200, {
            "status": "OK", "enabled": True,
            "queries": [b.report() for b in bridges],
            "groups": {k: g for k, g in stats["groups"].items()
                       if k in keys},
            "solo_fallbacks": stats["fallbacks"],
            "fallback_reasons": stats["fallback_reasons"],
            "cache": stats["cache"],
        }

    def recover(self, name: str, body: str = "") -> tuple[int, dict]:
        """Restore the latest (or a named) persisted revision and replay the
        WAL suffix — the crash-recovery entry point for deployed apps."""
        rt = self.runtimes.get(name)
        if rt is None:
            return 404, {"status": "ERROR",
                         "message": f"no app '{name}' deployed"}
        revision = None
        if body.strip():
            try:
                revision = json.loads(body).get("revision")
            except (ValueError, AttributeError):
                return 400, {"status": "ERROR",
                             "message": "body must be JSON like "
                                        '{"revision": "..."} or empty'}
        try:
            from .flow.recovery import recover as _recover
            report = _recover(rt, revision)
        except Exception as e:
            return 400, {"status": "ERROR", "message": str(e)}
        return 200, {"status": "OK", **report}

    # -- lifecycle -------------------------------------------------------------
    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._lock:
            for name, rt in list(self.runtimes.items()):
                rt.shutdown()
                self.manager.runtimes.pop(name, None)
            self.runtimes.clear()
