"""Window processors.

Reference: ``core/query/processor/stream/window/`` (30 types, 6,866 LoC). Each
window emits CURRENT events for arrivals and EXPIRED events for evictions — the
retraction protocol downstream aggregators rely on (see
``LengthWindowProcessor.java:106-140``). Time-driven windows use the deterministic
Scheduler (watermark timers) instead of wall-clock callbacks.

All windows implement ``snapshot_state``/``restore_state`` (checkpointing) and
``find_events`` (join support, the reference's ``FindableProcessor.find``).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Optional

from ..query_api.definition import DataType
from .event import EventType, StreamEvent
from .executor import RowFrame, StreamFrame
from .processors import Processor


class WindowProcessor(Processor):
    requires_scheduler = False

    def __init__(self):
        super().__init__()
        self.app_context = None
        self.element_id = None

    def setup(self, app_context, element_id: str) -> None:
        self.app_context = app_context
        self.element_id = element_id
        app_context.register_state(element_id, self)

    # join support: current window contents
    def find_events(self) -> list[StreamEvent]:
        return []

    def snapshot_state(self) -> dict:
        return {}

    def restore_state(self, state: dict) -> None:
        pass

    @staticmethod
    def _expired(ev: StreamEvent, ts: Optional[int] = None) -> StreamEvent:
        e = ev.copy()
        e.type = EventType.EXPIRED
        if ts is not None:
            e.timestamp = ts
        return e


# ---------------------------------------------------------------------------
# length / lengthBatch / batch
# ---------------------------------------------------------------------------

class LengthWindow(WindowProcessor):
    """Sliding count window (reference ``LengthWindowProcessor.java:81``).
    Buffer is op-log snapshotable (``SnapshotableStreamEventQueue`` analog)."""

    def __init__(self, length: int):
        super().__init__()
        from .snapshot import SnapshotableEventBuffer
        self.length = length
        self.buffer = SnapshotableEventBuffer()

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                continue
            if len(self.buffer) >= self.length:
                oldest = self.buffer.popleft()
                out.append(self._expired(oldest, ev.timestamp))
            self.buffer.append(ev)
            out.append(ev)
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return list(self.buffer)

    def snapshot_state(self) -> dict:
        return {"buffer": self.buffer.capture()}

    def restore_state(self, state: dict) -> None:
        self.buffer.restore(state["buffer"])

    def reset_increment_baseline(self) -> None:
        self.buffer.begin_oplog()

    def incremental_snapshot_state(self) -> "Optional[dict]":
        ops = self.buffer.incremental_snapshot()
        return None if ops is None else {"ops": ops}

    def apply_increment(self, inc: dict) -> None:
        self.buffer.apply_ops(inc["ops"])


class LengthBatchWindow(WindowProcessor):
    """Tumbling count window: emits when N collected; previous batch expires."""

    def __init__(self, length: int):
        super().__init__()
        self.length = length
        self.pending: list[StreamEvent] = []
        self.last_batch: list[StreamEvent] = []

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                continue
            self.pending.append(ev)
            if len(self.pending) >= self.length:
                ts = ev.timestamp
                for old in self.last_batch:
                    out.append(self._expired(old, ts))
                out.append(StreamEvent(ts, [], EventType.RESET))
                out.extend(self.pending)
                self.last_batch = self.pending
                self.pending = []
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return list(self.last_batch) + list(self.pending)

    def snapshot_state(self) -> dict:
        return {
            "pending": [(e.timestamp, list(e.data)) for e in self.pending],
            "last": [(e.timestamp, list(e.data)) for e in self.last_batch],
        }

    def restore_state(self, state: dict) -> None:
        self.pending = [StreamEvent(t, d) for t, d in state["pending"]]
        self.last_batch = [StreamEvent(t, d) for t, d in state["last"]]


class BatchWindow(WindowProcessor):
    """Per-chunk batch window (reference ``BatchWindowProcessor``)."""

    def __init__(self):
        super().__init__()
        self.last_batch: list[StreamEvent] = []

    def process(self, events: list[StreamEvent]) -> None:
        currents = [e for e in events if e.type == EventType.CURRENT]
        if not currents:
            return
        out: list[StreamEvent] = []
        ts = currents[-1].timestamp
        for old in self.last_batch:
            out.append(self._expired(old, ts))
        out.append(StreamEvent(ts, [], EventType.RESET))
        out.extend(currents)
        self.last_batch = currents
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return list(self.last_batch)

    def snapshot_state(self) -> dict:
        return {"last": [(e.timestamp, list(e.data)) for e in self.last_batch]}

    def restore_state(self, state: dict) -> None:
        self.last_batch = [StreamEvent(t, d) for t, d in state["last"]]


# ---------------------------------------------------------------------------
# time / timeBatch / timeLength / delay
# ---------------------------------------------------------------------------

class TimeWindow(WindowProcessor):
    """Sliding time window (reference ``TimeWindowProcessor.java:86``)."""

    requires_scheduler = True

    def __init__(self, duration_ms: int):
        super().__init__()
        from .snapshot import SnapshotableEventBuffer
        self.duration = duration_ms
        self.buffer = SnapshotableEventBuffer()

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type == EventType.TIMER:
                out.extend(self._expire(ev.timestamp))
                continue
            if ev.type != EventType.CURRENT:
                continue
            out.extend(self._expire(ev.timestamp))
            self.buffer.append(ev)
            out.append(ev)
            self.app_context.scheduler.notify_at(
                ev.timestamp + self.duration, self._on_timer)
        self.forward(out)

    def _expire(self, now: int) -> list[StreamEvent]:
        out = []
        while self.buffer and self.buffer[0].timestamp + self.duration <= now:
            out.append(self._expired(self.buffer.popleft(), now))
        return out

    def _on_timer(self, ts: int) -> None:
        self.process([StreamEvent(ts, [], EventType.TIMER)])

    def find_events(self) -> list[StreamEvent]:
        return list(self.buffer)

    def snapshot_state(self) -> dict:
        return {"buffer": self.buffer.capture()}

    def restore_state(self, state: dict) -> None:
        self.buffer.restore(state["buffer"])
        # re-arm expiry timers (fresh scheduler after restore)
        for e in self.buffer:
            self.app_context.scheduler.notify_at(
                e.timestamp + self.duration, self._on_timer)

    def reset_increment_baseline(self) -> None:
        self.buffer.begin_oplog()

    def incremental_snapshot_state(self) -> "Optional[dict]":
        ops = self.buffer.incremental_snapshot()
        return None if ops is None else {"ops": ops}

    def apply_increment(self, inc: dict) -> None:
        self.buffer.apply_ops(inc["ops"])
        # arm timers only for the newly appended events; survivors from the
        # base restore already have theirs
        for op in inc["ops"]:
            if op[0] == "a":
                self.app_context.scheduler.notify_at(
                    op[1] + self.duration, self._on_timer)


class TimeBatchWindow(WindowProcessor):
    """Tumbling time window."""

    requires_scheduler = True

    def __init__(self, duration_ms: int, start_time: Optional[int] = None):
        super().__init__()
        self.duration = duration_ms
        self.start_time = start_time
        self.pending: list[StreamEvent] = []
        self.last_batch: list[StreamEvent] = []
        self.boundary: Optional[int] = None

    def process(self, events: list[StreamEvent]) -> None:
        # per-flush forwards, same rationale as HoppingWindow.process: the
        # selector collapses each aggregated batch chunk to one row, so two
        # boundary flushes merged into one forward would lose a row
        for ev in events:
            if ev.type == EventType.TIMER:
                if self.boundary is not None and ev.timestamp >= self.boundary:
                    self.forward(self._flush(self.boundary))
                continue
            if ev.type != EventType.CURRENT:
                continue
            if self.boundary is None:
                base = self.start_time if self.start_time is not None else ev.timestamp
                self.boundary = base + self.duration
                self.app_context.scheduler.notify_at(self.boundary, self._on_timer)
            while ev.timestamp >= self.boundary:
                self.forward(self._flush(self.boundary))
            self.pending.append(ev)

    def _flush(self, ts: int) -> list[StreamEvent]:
        out: list[StreamEvent] = []
        if self.pending or self.last_batch:
            for old in self.last_batch:
                out.append(self._expired(old, ts))
            out.append(StreamEvent(ts, [], EventType.RESET))
            out.extend(self.pending)
            self.last_batch = self.pending
            self.pending = []
        self.boundary += self.duration
        self.app_context.scheduler.notify_at(self.boundary, self._on_timer)
        return out

    def _on_timer(self, ts: int) -> None:
        self.process([StreamEvent(ts, [], EventType.TIMER)])

    def find_events(self) -> list[StreamEvent]:
        return list(self.last_batch) + list(self.pending)

    def snapshot_state(self) -> dict:
        return {
            "pending": [(e.timestamp, list(e.data)) for e in self.pending],
            "last": [(e.timestamp, list(e.data)) for e in self.last_batch],
            "boundary": self.boundary,
        }

    def restore_state(self, state: dict) -> None:
        self.pending = [StreamEvent(t, d) for t, d in state["pending"]]
        self.last_batch = [StreamEvent(t, d) for t, d in state["last"]]
        self.boundary = state["boundary"]
        if self.boundary is not None:
            self.app_context.scheduler.notify_at(self.boundary, self._on_timer)


class TimeLengthWindow(WindowProcessor):
    """Sliding window bounded by both time and count."""

    requires_scheduler = True

    def __init__(self, duration_ms: int, length: int):
        super().__init__()
        self.duration = duration_ms
        self.length = length
        self.buffer: list[StreamEvent] = []

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type == EventType.TIMER:
                out.extend(self._expire(ev.timestamp))
                continue
            if ev.type != EventType.CURRENT:
                continue
            out.extend(self._expire(ev.timestamp))
            if len(self.buffer) >= self.length:
                out.append(self._expired(self.buffer.pop(0), ev.timestamp))
            self.buffer.append(ev)
            out.append(ev)
            self.app_context.scheduler.notify_at(
                ev.timestamp + self.duration, self._on_timer)
        self.forward(out)

    def _expire(self, now: int) -> list[StreamEvent]:
        out = []
        while self.buffer and self.buffer[0].timestamp + self.duration <= now:
            out.append(self._expired(self.buffer.pop(0), now))
        return out

    def _on_timer(self, ts: int) -> None:
        self.process([StreamEvent(ts, [], EventType.TIMER)])

    def find_events(self) -> list[StreamEvent]:
        return list(self.buffer)

    def snapshot_state(self) -> dict:
        return {"buffer": [(e.timestamp, list(e.data)) for e in self.buffer]}

    def restore_state(self, state: dict) -> None:
        self.buffer = [StreamEvent(t, d) for t, d in state["buffer"]]
        for e in self.buffer:
            self.app_context.scheduler.notify_at(
                e.timestamp + self.duration, self._on_timer)


class DelayWindow(WindowProcessor):
    """Events pass through after a fixed delay (reference ``DelayWindowProcessor``)."""

    requires_scheduler = True

    def __init__(self, delay_ms: int):
        super().__init__()
        self.delay = delay_ms
        self.held: list[StreamEvent] = []

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type == EventType.TIMER:
                while self.held and self.held[0].timestamp + self.delay <= ev.timestamp:
                    e = self.held.pop(0)
                    out.append(StreamEvent(ev.timestamp, e.data, EventType.CURRENT))
                continue
            if ev.type != EventType.CURRENT:
                continue
            self.held.append(ev)
            self.app_context.scheduler.notify_at(ev.timestamp + self.delay, self._on_timer)
        self.forward(out)

    def _on_timer(self, ts: int) -> None:
        self.process([StreamEvent(ts, [], EventType.TIMER)])

    def find_events(self) -> list[StreamEvent]:
        return list(self.held)

    def snapshot_state(self) -> dict:
        return {"held": [(e.timestamp, list(e.data)) for e in self.held]}

    def restore_state(self, state: dict) -> None:
        self.held = [StreamEvent(t, d) for t, d in state["held"]]
        for e in self.held:
            self.app_context.scheduler.notify_at(
                e.timestamp + self.delay, self._on_timer)


# ---------------------------------------------------------------------------
# externalTime / externalTimeBatch — event-time attribute driven
# ---------------------------------------------------------------------------

class ExternalTimeWindow(WindowProcessor):
    """Sliding window over an event-time attribute."""

    def __init__(self, ts_executor: Callable, duration_ms: int):
        super().__init__()
        self.ts_executor = ts_executor
        self.duration = duration_ms
        self.buffer: list[tuple[int, StreamEvent]] = []

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                continue
            ets = int(self.ts_executor(StreamFrame(ev)))
            while self.buffer and self.buffer[0][0] + self.duration <= ets:
                out.append(self._expired(self.buffer.pop(0)[1], ev.timestamp))
            self.buffer.append((ets, ev))
            out.append(ev)
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return [e for _, e in self.buffer]

    def snapshot_state(self) -> dict:
        return {"buffer": [(ets, e.timestamp, list(e.data))
                           for ets, e in self.buffer]}

    def restore_state(self, state: dict) -> None:
        self.buffer = [(ets, StreamEvent(t, d)) for ets, t, d in state["buffer"]]


class ExternalTimeBatchWindow(WindowProcessor):
    """Tumbling window over an event-time attribute."""

    def __init__(self, ts_executor: Callable, duration_ms: int,
                 start_time: Optional[int] = None):
        super().__init__()
        self.ts_executor = ts_executor
        self.duration = duration_ms
        self.start_time = start_time
        self.boundary: Optional[int] = None
        self.pending: list[StreamEvent] = []
        self.last_batch: list[StreamEvent] = []

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                continue
            ets = int(self.ts_executor(StreamFrame(ev)))
            if self.boundary is None:
                base = self.start_time if self.start_time is not None else ets
                self.boundary = base + self.duration
            while ets >= self.boundary:
                if self.pending or self.last_batch:
                    for old in self.last_batch:
                        out.append(self._expired(old, ev.timestamp))
                    out.append(StreamEvent(ev.timestamp, [], EventType.RESET))
                    out.extend(self.pending)
                    self.last_batch = self.pending
                    self.pending = []
                self.boundary += self.duration
            self.pending.append(ev)
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return list(self.last_batch) + list(self.pending)

    def snapshot_state(self) -> dict:
        return {"pending": [(e.timestamp, list(e.data)) for e in self.pending],
                "last": [(e.timestamp, list(e.data)) for e in self.last_batch],
                "boundary": self.boundary}

    def restore_state(self, state: dict) -> None:
        self.pending = [StreamEvent(t, d) for t, d in state["pending"]]
        self.last_batch = [StreamEvent(t, d) for t, d in state["last"]]
        self.boundary = state["boundary"]


# ---------------------------------------------------------------------------
# session
# ---------------------------------------------------------------------------

class SessionWindow(WindowProcessor):
    """Session window with gap; optional session key (reference
    ``SessionWindowProcessor``). Currents pass through; a session's events expire
    together when the gap elapses with no new arrival."""

    requires_scheduler = True

    def __init__(self, gap_ms: int, key_executor: Optional[Callable] = None,
                 allowed_latency_ms: int = 0):
        super().__init__()
        self.gap = gap_ms
        self.key_executor = key_executor
        self.allowed_latency = allowed_latency_ms
        self.sessions: dict = {}            # key -> {"events": [...], "last_ts": int}

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type == EventType.TIMER:
                out.extend(self._close_due(ev.timestamp))
                continue
            if ev.type != EventType.CURRENT:
                continue
            out.extend(self._close_due(ev.timestamp))
            key = self.key_executor(StreamFrame(ev)) if self.key_executor else None
            sess = self.sessions.setdefault(key, {"events": [], "last_ts": ev.timestamp})
            sess["events"].append(ev)
            sess["last_ts"] = ev.timestamp
            out.append(ev)
            self.app_context.scheduler.notify_at(
                ev.timestamp + self.gap + self.allowed_latency, self._on_timer)
        self.forward(out)

    def _close_due(self, now: int) -> list[StreamEvent]:
        out = []
        for key in list(self.sessions):
            sess = self.sessions[key]
            if sess["last_ts"] + self.gap + self.allowed_latency <= now:
                for e in sess["events"]:
                    out.append(self._expired(e, now))
                del self.sessions[key]
        return out

    def _on_timer(self, ts: int) -> None:
        self.process([StreamEvent(ts, [], EventType.TIMER)])

    def find_events(self) -> list[StreamEvent]:
        return [e for s in self.sessions.values() for e in s["events"]]

    def snapshot_state(self) -> dict:
        return {"sessions": {
            key: {"events": [(e.timestamp, list(e.data)) for e in s["events"]],
                  "last_ts": s["last_ts"]}
            for key, s in self.sessions.items()}}

    def restore_state(self, state: dict) -> None:
        self.sessions = {
            key: {"events": [StreamEvent(t, d) for t, d in s["events"]],
                  "last_ts": s["last_ts"]}
            for key, s in state["sessions"].items()}
        for s in self.sessions.values():
            self.app_context.scheduler.notify_at(
                s["last_ts"] + self.gap + self.allowed_latency, self._on_timer)


# ---------------------------------------------------------------------------
# sort / frequent / lossyFrequent
# ---------------------------------------------------------------------------

class SortWindow(WindowProcessor):
    """Keeps the top-N events by sort key; evicts the extreme (reference
    ``SortWindowProcessor``)."""

    def __init__(self, length: int, key_executors: list[Callable],
                 orders: list[str]):
        super().__init__()
        self.length = length
        self.key_executors = key_executors
        self.orders = orders  # 'asc' | 'desc' per key
        self.buffer: list[StreamEvent] = []

    def _sort_key(self, ev: StreamEvent):
        keys = []
        for fn, order in zip(self.key_executors, self.orders):
            v = fn(StreamFrame(ev))
            keys.append(_Reversed(v) if order == "desc" else v)
        return tuple(keys)

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                continue
            self.buffer.append(ev)
            self.buffer.sort(key=self._sort_key)
            out.append(ev)
            if len(self.buffer) > self.length:
                evicted = self.buffer.pop()   # worst per sort order
                out.append(self._expired(evicted, ev.timestamp))
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return list(self.buffer)

    def snapshot_state(self) -> dict:
        return {"buffer": [(e.timestamp, list(e.data)) for e in self.buffer]}

    def restore_state(self, state: dict) -> None:
        self.buffer = [StreamEvent(t, d) for t, d in state["buffer"]]


class _Reversed:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v


class FrequentWindow(WindowProcessor):
    """Misra-Gries frequent-items window (reference ``FrequentWindowProcessor``)."""

    def __init__(self, count: int, key_executors: Optional[list[Callable]] = None):
        super().__init__()
        self.count = count
        self.key_executors = key_executors
        self.counts: "OrderedDict" = OrderedDict()   # key -> [count, StreamEvent]

    def _key(self, ev: StreamEvent):
        if self.key_executors:
            return tuple(fn(StreamFrame(ev)) for fn in self.key_executors)
        return tuple(ev.data)

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                continue
            key = self._key(ev)
            if key in self.counts:
                self.counts[key][0] += 1
                self.counts[key][1] = ev
                out.append(ev)
            elif len(self.counts) < self.count:
                self.counts[key] = [1, ev]
                out.append(ev)
            else:
                # decrement all; evict zeros — and if the pass freed a
                # slot, the NEW event takes it and emits (reference
                # FrequentWindowProcessor tentatively inserts, decrements
                # the old keys, and only drops the arrival when nothing
                # evicted)
                for k in list(self.counts):
                    self.counts[k][0] -= 1
                    if self.counts[k][0] <= 0:
                        out.append(self._expired(self.counts[k][1], ev.timestamp))
                        del self.counts[k]
                if len(self.counts) < self.count:
                    self.counts[key] = [1, ev]
                    out.append(ev)
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return [v[1] for v in self.counts.values()]

    def snapshot_state(self) -> dict:
        return {"counts": [
            (key, c, e.timestamp, list(e.data))
            for key, (c, e) in self.counts.items()]}

    def restore_state(self, state: dict) -> None:
        self.counts = OrderedDict(
            (tuple(key), [c, StreamEvent(t, d)])
            for key, c, t, d in state["counts"])


class LossyFrequentWindow(WindowProcessor):
    """Lossy-counting frequent-items window."""

    def __init__(self, support: float, error: Optional[float] = None,
                 key_executors: Optional[list[Callable]] = None):
        super().__init__()
        self.support = support
        self.error = error if error is not None else support / 10.0
        self.key_executors = key_executors
        self.total = 0
        self.counts: dict = {}   # key -> [freq, delta, StreamEvent]

    def _key(self, ev: StreamEvent):
        if self.key_executors:
            return tuple(fn(StreamFrame(ev)) for fn in self.key_executors)
        return tuple(ev.data)

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                continue
            self.total += 1
            bucket = int(self.total * self.error) + 1
            key = self._key(ev)
            if key in self.counts:
                self.counts[key][0] += 1
                self.counts[key][2] = ev
            else:
                self.counts[key] = [1, bucket - 1, ev]
            entry = self.counts[key]
            if entry[0] + entry[1] >= self.total * self.support:
                out.append(ev)
            # periodic pruning
            for k in list(self.counts):
                f, d, e = self.counts[k]
                if f + d <= bucket - 1:
                    out.append(self._expired(e, ev.timestamp))
                    del self.counts[k]
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return [v[2] for v in self.counts.values()]

    def snapshot_state(self) -> dict:
        return {"total": self.total,
                "counts": [(key, f, dlt, e.timestamp, list(e.data))
                           for key, (f, dlt, e) in self.counts.items()]}

    def restore_state(self, state: dict) -> None:
        self.total = state["total"]
        self.counts = {tuple(key): [f, dlt, StreamEvent(t, d)]
                       for key, f, dlt, t, d in state["counts"]}


# ---------------------------------------------------------------------------
# hopping — time window emitted every hop
# ---------------------------------------------------------------------------

class HoppingWindow(WindowProcessor):
    """Fixed-length window emitted every hop interval (reference
    ``HopingWindowProcessor``)."""

    requires_scheduler = True

    def __init__(self, duration_ms: int, hop_ms: int):
        super().__init__()
        self.duration = duration_ms
        self.hop = hop_ms
        self.buffer: list[StreamEvent] = []
        self.last_batch: list[StreamEvent] = []
        self.boundary: Optional[int] = None

    def process(self, events: list[StreamEvent]) -> None:
        # each flush forwards as its OWN chunk: the selector collapses
        # aggregated batch chunks to one row per chunk (reference: every
        # scheduler fire delivers its own chunk), so merging two boundary
        # flushes into one forward would silently drop the first row
        for ev in events:
            if ev.type == EventType.TIMER:
                if self.boundary is not None and ev.timestamp >= self.boundary:
                    self.forward(self._hop_flush(self.boundary))
                continue
            if ev.type != EventType.CURRENT:
                continue
            if self.boundary is None:
                self.boundary = ev.timestamp + self.hop
                self.app_context.scheduler.notify_at(self.boundary, self._on_timer)
            while ev.timestamp >= self.boundary:
                self.forward(self._hop_flush(self.boundary))
            self.buffer.append(ev)

    def _hop_flush(self, ts: int) -> list[StreamEvent]:
        out: list[StreamEvent] = []
        # retain only events within the window length
        self.buffer = [e for e in self.buffer if e.timestamp + self.duration > ts]
        for old in self.last_batch:
            out.append(self._expired(old, ts))
        out.append(StreamEvent(ts, [], EventType.RESET))
        out.extend(StreamEvent(ts, e.data, EventType.CURRENT) for e in self.buffer)
        self.last_batch = list(self.buffer)
        self.boundary += self.hop
        self.app_context.scheduler.notify_at(self.boundary, self._on_timer)
        return out

    def _on_timer(self, ts: int) -> None:
        self.process([StreamEvent(ts, [], EventType.TIMER)])

    def find_events(self) -> list[StreamEvent]:
        return list(self.buffer)

    def snapshot_state(self) -> dict:
        return {"buffer": [(e.timestamp, list(e.data)) for e in self.buffer],
                "last": [(e.timestamp, list(e.data)) for e in self.last_batch],
                "boundary": self.boundary}

    def restore_state(self, state: dict) -> None:
        self.buffer = [StreamEvent(t, d) for t, d in state["buffer"]]
        self.last_batch = [StreamEvent(t, d) for t, d in state["last"]]
        self.boundary = state["boundary"]
        if self.boundary is not None:
            self.app_context.scheduler.notify_at(self.boundary, self._on_timer)


# ---------------------------------------------------------------------------
# expression windows — retain while expression holds
# ---------------------------------------------------------------------------

class ExpressionWindow(WindowProcessor):
    """Sliding window retaining events while a condition over the buffer holds
    (reference ``ExpressionWindowProcessor``). The expression sees per-event
    attributes plus ``count()``/``sum(x)``-style built-ins via the retain check
    callback supplied by the runtime builder."""

    def __init__(self, retain_check: Callable[[list[StreamEvent], StreamEvent], int]):
        super().__init__()
        # retain_check(buffer, newest) -> number of oldest events to evict
        self.retain_check = retain_check
        self.buffer: list[StreamEvent] = []

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                continue
            self.buffer.append(ev)
            n_evict = self.retain_check(self.buffer, ev)
            for _ in range(n_evict):
                out.append(self._expired(self.buffer.pop(0), ev.timestamp))
            out.append(ev)
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return list(self.buffer)


class EmptyWindow(WindowProcessor):
    """Pass-through window (reference ``EmptyWindowProcessor``) — `#window()`."""

    def process(self, events: list[StreamEvent]) -> None:
        out = [e for e in events if e.type == EventType.CURRENT]
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return []


# ---------------------------------------------------------------------------
# cron window
# ---------------------------------------------------------------------------

class CronWindow(WindowProcessor):
    """Batch window flushed on cron schedule (reference ``CronWindowProcessor``).

    Uses the minimal cron evaluator in ``siddhi_tpu.core.cron`` (quartz-style
    6/7-field expressions, second resolution).
    """

    requires_scheduler = True

    def __init__(self, cron_expr: str):
        super().__init__()
        from .cron import CronSchedule
        self.schedule = CronSchedule(cron_expr)
        self.pending: list[StreamEvent] = []
        self.last_batch: list[StreamEvent] = []
        self._armed = False

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type == EventType.TIMER:
                if self.pending or self.last_batch:
                    for old in self.last_batch:
                        out.append(self._expired(old, ev.timestamp))
                    out.append(StreamEvent(ev.timestamp, [], EventType.RESET))
                    out.extend(self.pending)
                    self.last_batch = self.pending
                    self.pending = []
                self._arm(ev.timestamp)
                continue
            if ev.type != EventType.CURRENT:
                continue
            if not self._armed:
                self._arm(ev.timestamp)
            self.pending.append(ev)
        self.forward(out)

    def _arm(self, now: int) -> None:
        nxt = self.schedule.next_fire_after(now)
        if nxt is not None:
            self.app_context.scheduler.notify_at(nxt, self._on_timer)
            self._armed = True

    def _on_timer(self, ts: int) -> None:
        self.process([StreamEvent(ts, [], EventType.TIMER)])

    def find_events(self) -> list[StreamEvent]:
        return list(self.last_batch) + list(self.pending)

    def snapshot_state(self) -> dict:
        return {"pending": [(e.timestamp, list(e.data)) for e in self.pending],
                "last": [(e.timestamp, list(e.data)) for e in self.last_batch],
                "armed": self._armed}

    def restore_state(self, state: dict) -> None:
        self.pending = [StreamEvent(t, d) for t, d in state["pending"]]
        self.last_batch = [StreamEvent(t, d) for t, d in state["last"]]
        self._armed = False
        if state.get("armed"):
            self._arm(self.app_context.current_time())
