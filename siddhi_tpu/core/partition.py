"""Partitions: per-key cloned query state.

Reference: ``core/partition/`` — ``PartitionRuntimeImpl``, ``PartitionStreamReceiver``
(key eval & dispatch :82-117), value & range partition executors. Each distinct key
lazily instantiates the inner queries (their windows/aggregators/patterns are
per-key state); inner ``#streams`` are partition-local. This per-key-instance
layout is exactly what the TPU path shards across a mesh axis
(``siddhi_tpu/tpu/partition.py``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..query_api import Partition, PartitionType, SingleInputStream, StateInputStream, JoinInputStream
from .event import StreamEvent
from .executor import ExecutorBuilder, StreamFrame, StreamResolver
from .query_runtime import QueryRuntime, build_query_runtime
from .stream import StreamJunction


class PartitionKeyExecutor:
    """value partition: expr; range partition: first matching label."""

    def __init__(self, value_fn: Optional[Callable] = None,
                 ranges: Optional[list[tuple[str, Callable]]] = None):
        self.value_fn = value_fn
        self.ranges = ranges or []

    def key_of(self, ev: StreamEvent) -> Optional[Any]:
        if self.value_fn is not None:
            return self.value_fn(StreamFrame(ev))
        for label, cond in self.ranges:
            if bool(cond(StreamFrame(ev))):
                return label
        return None    # no range matched → event dropped (reference behavior)


class PartitionInstance:
    """All inner query runtimes for one partition key."""

    def __init__(self, key: Any, partition: "PartitionRuntime"):
        self.key = key
        self.p = partition
        app_context = partition.app_context
        self.inner_junctions: dict[str, StreamJunction] = {}
        self.inner_defs: dict = {}
        self.query_runtimes: list[QueryRuntime] = []
        # receivers per outer stream id
        self.receivers: dict[str, list] = {}

        # two passes: infer inner stream defs from inner-inserting queries
        for i, q in enumerate(partition.partition_ast.queries):
            name = q.name() or f"{partition.name}-query-{i}"
            rt = build_query_runtime(
                q, app_context, partition.stream_defs,
                self._get_junction, f"{name}-k{key}", inner_defs=self.inner_defs,
                metric_name=name)   # one histogram per LOGICAL query: a
            # tracker per partition key would grow without bound
            self.query_runtimes.append(rt)
            for sid, receiver in rt.subscriptions:
                ist = q.input_stream
                inner = getattr(ist, "is_inner_stream", False) if \
                    isinstance(ist, SingleInputStream) else False
                if inner:
                    self._get_junction(sid, True).subscribe(receiver)
                else:
                    self.receivers.setdefault(sid, []).append(receiver)
            rt.start()
            # register query callbacks attached at partition level
            for cb in partition.query_callbacks.get(q.name(), []):
                rt.add_callback(cb)
            # fill implicit schema of inner target streams
            from ..query_api import InsertIntoStream
            os_ = q.output_stream
            if isinstance(os_, InsertIntoStream) and os_.is_inner_stream:
                d = self.inner_defs.get(os_.target_id)
                j = self.inner_junctions.get(os_.target_id)
                target_def = d if d is not None else (j.definition if j else None)
                if target_def is not None and not target_def.attributes:
                    names, dtypes = rt.output_schema
                    for n, t in zip(names, dtypes):
                        target_def.attribute(n, t)
                    self.inner_defs[os_.target_id] = target_def
            # fill implicit schema of global target streams
            if isinstance(os_, InsertIntoStream) and not os_.is_inner_stream:
                j = partition.get_outer_junction(os_.target_id)
                if not j.definition.attributes:
                    from ..query_api.definition import StreamDefinition
                    names, dtypes = rt.output_schema
                    d = StreamDefinition(os_.target_id)
                    for n, t in zip(names, dtypes):
                        d.attribute(n, t)
                    j.definition = d

    def _get_junction(self, stream_id: str, inner: bool) -> StreamJunction:
        if not inner:
            return self.p.get_outer_junction(stream_id)
        j = self.inner_junctions.get(stream_id)
        if j is None:
            d = self.inner_defs.get(stream_id)
            if d is None:
                from ..query_api.definition import StreamDefinition
                d = StreamDefinition(stream_id)
                self.inner_defs[stream_id] = d
            j = StreamJunction(d, self.p.app_context)
            self.inner_junctions[stream_id] = j
        return j

    def send(self, stream_id: str, event: StreamEvent) -> None:
        for r in self.receivers.get(stream_id, []):
            r.receive(event)


class PartitionStreamReceiver:
    def __init__(self, partition: "PartitionRuntime", stream_id: str,
                 key_executor: Optional[PartitionKeyExecutor]):
        self.partition = partition
        self.stream_id = stream_id
        self.key_executor = key_executor

    def receive(self, event: StreamEvent) -> None:
        if self.key_executor is None:
            # non-partitioned stream inside partition: broadcast to all instances
            for inst in self.partition.instances.values():
                inst.send(self.stream_id, event)
            return
        key = self.key_executor.key_of(event)
        if key is None:
            return
        inst = self.partition.get_instance(key)
        inst.send(self.stream_id, event)


class PartitionRuntime:
    def __init__(self, partition_ast: Partition, app_context, stream_defs: dict,
                 get_junction: Callable, name: str):
        self.partition_ast = partition_ast
        self.app_context = app_context
        self.stream_defs = stream_defs
        self.get_outer_junction = lambda sid, inner=False: get_junction(sid, False)
        self.name = name
        self.instances: dict[Any, PartitionInstance] = {}
        self.key_executors: dict[str, PartitionKeyExecutor] = {}
        self.query_callbacks: dict[str, list] = {}
        app_context.register_state(f"partition-{name}", self)

        for pt in partition_ast.partition_types:
            d = stream_defs[pt.stream_id]
            builder = ExecutorBuilder(StreamResolver(d), app_context)
            if pt.value_expr is not None:
                fn, _ = builder.build(pt.value_expr)
                self.key_executors[pt.stream_id] = PartitionKeyExecutor(value_fn=fn)
            else:
                ranges = [(r.partition_key, builder.build(r.condition)[0])
                          for r in pt.ranges]
                self.key_executors[pt.stream_id] = PartitionKeyExecutor(ranges=ranges)

        # pre-create global junctions for non-inner insert targets so callbacks
        # can attach before the first key instance materializes
        from ..query_api import InsertIntoStream
        for q in partition_ast.queries:
            os_ = q.output_stream
            if isinstance(os_, InsertIntoStream) and not os_.is_inner_stream:
                self.get_outer_junction(os_.target_id)

        # subscribe to every outer stream the inner queries consume
        self.consumed: set[str] = set()
        for q in partition_ast.queries:
            ist = q.input_stream
            if isinstance(ist, SingleInputStream):
                if not ist.is_inner_stream:
                    self.consumed.add(ist.stream_id)
            elif isinstance(ist, StateInputStream):
                self.consumed.update(ist.stream_ids())
            elif isinstance(ist, JoinInputStream):
                for s in (ist.left, ist.right):
                    if not s.is_inner_stream:
                        self.consumed.add(s.stream_id)

    def subscribe_all(self, get_junction: Callable) -> None:
        for sid in self.consumed:
            if sid in self.app_context.tables or sid in self.app_context.named_windows:
                continue
            ke = self.key_executors.get(sid)
            get_junction(sid, False).subscribe(
                PartitionStreamReceiver(self, sid, ke))

    def get_instance(self, key: Any) -> PartitionInstance:
        inst = self.instances.get(key)
        if inst is None:
            inst = PartitionInstance(key, self)
            self.instances[key] = inst
        return inst

    def add_query_callback(self, query_name: str, cb) -> None:
        self.query_callbacks.setdefault(query_name, []).append(cb)
        for inst in self.instances.values():
            for i, q in enumerate(self.partition_ast.queries):
                if q.name() == query_name:
                    inst.query_runtimes[i].add_callback(cb)

    # purge support (reference: @purge annotation) — drop idle keys
    def purge(self, keys: list[Any]) -> None:
        for k in keys:
            self.instances.pop(k, None)

    def snapshot_state(self) -> dict:
        return {"keys": list(self.instances.keys())}

    def restore_state(self, state: dict) -> None:
        for k in state["keys"]:
            self.get_instance(k)
