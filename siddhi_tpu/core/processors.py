"""Processor chain primitives: filter, stream functions.

Reference: ``core/query/processor/Processor.java`` (chain interface),
``filter/FilterProcessor.java``, ``stream/function/StreamFunctionProcessor.java``.
Chunks are plain ``list[StreamEvent]`` — the mutable linked-list cursor of the
reference (``ComplexEventChunk``) is unnecessary with immutable list passing.
"""

from __future__ import annotations

from typing import Callable, Optional

from .event import EventType, StreamEvent
from .executor import StreamFrame


class Processor:
    def __init__(self):
        self.next: Optional[Processor] = None

    def process(self, events: list[StreamEvent]) -> None:
        raise NotImplementedError

    def forward(self, events: list[StreamEvent]) -> None:
        if self.next is not None and events:
            self.next.process(events)

    def set_next(self, p: "Processor") -> "Processor":
        self.next = p
        return p


class FilterProcessor(Processor):
    """Drops events failing the condition (TIMER events always pass through)."""

    def __init__(self, condition: Callable):
        super().__init__()
        self.condition = condition

    def process(self, events: list[StreamEvent]) -> None:
        out = []
        for ev in events:
            if ev.type == EventType.TIMER or ev.type == EventType.RESET:
                out.append(ev)
                continue
            if bool(self.condition(StreamFrame(ev))):
                out.append(ev)
        if out:
            self.forward(out)


class StreamFunctionProcessor(Processor):
    """1→N event transform (extension point; reference ``StreamFunctionProcessor``).

    ``fn(event) -> list[list] | list | None`` — returns appended-attribute payloads.
    """

    def __init__(self, fn: Callable[[StreamEvent], object]):
        super().__init__()
        self.fn = fn

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                out.append(ev)
                continue
            res = self.fn(ev)
            if res is None:
                continue
            if res and isinstance(res[0], (list, tuple)):
                for row in res:
                    out.append(StreamEvent(ev.timestamp, list(row), ev.type))
            else:
                out.append(StreamEvent(ev.timestamp, list(res), ev.type))
        if out:
            self.forward(out)


class SinkProcessor(Processor):
    """Chain terminator calling a function with the chunk."""

    def __init__(self, fn: Callable[[list[StreamEvent]], None]):
        super().__init__()
        self.fn = fn

    def process(self, events: list[StreamEvent]) -> None:
        self.fn(events)
