"""Attribute aggregators: sum/avg/min/max/count/distinctCount/stdDev/and/or/
minForever/maxForever/unionSet.

Reference: ``core/query/selector/attribute/aggregator/`` (12 executors, 3,790 LoC).
Each supports retraction (``remove``) so EXPIRED events from windows roll the
aggregate back — the protocol the whole windowed-aggregation design rests on.
"""

from __future__ import annotations

import bisect
import math
from collections import Counter
from typing import Any, Optional

from ..query_api.definition import DataType


class Aggregator:
    """Stateful aggregate with add/remove/reset (one instance per group key)."""

    def add(self, v: Any) -> None:
        raise NotImplementedError

    def remove(self, v: Any) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def value(self) -> Any:
        raise NotImplementedError

    def snapshot(self) -> Any:
        return self.__dict__.copy()

    def restore(self, state: Any) -> None:
        self.__dict__.update(state)


class SumAggregator(Aggregator):
    def __init__(self, is_int: bool):
        self.is_int = is_int
        self.total = 0
        self.count = 0

    def add(self, v):
        if v is None:
            return
        self.total += v
        self.count += 1

    def remove(self, v):
        if v is None:
            return
        self.total -= v
        self.count -= 1

    def reset(self):
        self.total = 0
        self.count = 0

    def value(self):
        if self.count == 0:
            return None
        return int(self.total) if self.is_int else float(self.total)


class CountAggregator(Aggregator):
    def __init__(self):
        self.count = 0

    def add(self, v):
        self.count += 1

    def remove(self, v):
        self.count -= 1

    def reset(self):
        self.count = 0

    def value(self):
        return self.count


class AvgAggregator(Aggregator):
    def __init__(self):
        self.total = 0.0
        self.count = 0

    def add(self, v):
        if v is None:
            return
        self.total += v
        self.count += 1

    def remove(self, v):
        if v is None:
            return
        self.total -= v
        self.count -= 1

    def reset(self):
        self.total = 0.0
        self.count = 0

    def value(self):
        return None if self.count == 0 else self.total / self.count


class MinMaxAggregator(Aggregator):
    """Sorted multiset so EXPIRED removals restore the previous extreme."""

    def __init__(self, is_min: bool):
        self.is_min = is_min
        self.values: list = []

    def add(self, v):
        if v is None:
            return
        bisect.insort(self.values, v)

    def remove(self, v):
        if v is None:
            return
        i = bisect.bisect_left(self.values, v)
        if i < len(self.values) and self.values[i] == v:
            self.values.pop(i)

    def reset(self):
        self.values = []

    def value(self):
        if not self.values:
            return None
        return self.values[0] if self.is_min else self.values[-1]


class ForeverAggregator(Aggregator):
    """minForever/maxForever — never retracts."""

    def __init__(self, is_min: bool):
        self.is_min = is_min
        self.current = None

    def add(self, v):
        if v is None:
            return
        if self.current is None:
            self.current = v
        else:
            self.current = min(self.current, v) if self.is_min else max(self.current, v)

    def remove(self, v):
        pass

    def reset(self):
        # forever aggregators survive resets by design
        pass

    def value(self):
        return self.current


class DistinctCountAggregator(Aggregator):
    def __init__(self):
        self.counter: Counter = Counter()

    def add(self, v):
        self.counter[v] += 1

    def remove(self, v):
        self.counter[v] -= 1
        if self.counter[v] <= 0:
            del self.counter[v]

    def reset(self):
        self.counter = Counter()

    def value(self):
        return len(self.counter)


class StdDevAggregator(Aggregator):
    """Population standard deviation (matches the reference's semantics)."""

    def __init__(self):
        self.n = 0
        self.sum = 0.0
        self.sumsq = 0.0

    def add(self, v):
        if v is None:
            return
        self.n += 1
        self.sum += v
        self.sumsq += v * v

    def remove(self, v):
        if v is None:
            return
        self.n -= 1
        self.sum -= v
        self.sumsq -= v * v

    def reset(self):
        self.n = 0
        self.sum = 0.0
        self.sumsq = 0.0

    def value(self):
        if self.n == 0:
            return None
        mean = self.sum / self.n
        var = max(self.sumsq / self.n - mean * mean, 0.0)
        return math.sqrt(var)


class BoolAggregator(Aggregator):
    """``and`` / ``or`` over booleans."""

    def __init__(self, is_and: bool):
        self.is_and = is_and
        self.true_count = 0
        self.false_count = 0

    def add(self, v):
        if v:
            self.true_count += 1
        else:
            self.false_count += 1

    def remove(self, v):
        if v:
            self.true_count -= 1
        else:
            self.false_count -= 1

    def reset(self):
        self.true_count = 0
        self.false_count = 0

    def value(self):
        if self.is_and:
            return self.false_count == 0
        return self.true_count > 0


class UnionSetAggregator(Aggregator):
    def __init__(self):
        self.counter: Counter = Counter()

    def add(self, v):
        if v is None:
            return
        if isinstance(v, (set, frozenset)):
            for x in v:
                self.counter[x] += 1
        else:
            self.counter[v] += 1

    def remove(self, v):
        if v is None:
            return
        items = v if isinstance(v, (set, frozenset)) else [v]
        for x in items:
            self.counter[x] -= 1
            if self.counter[x] <= 0:
                del self.counter[x]

    def reset(self):
        self.counter = Counter()

    def value(self):
        return set(self.counter)


AGGREGATOR_NAMES = {
    "sum", "avg", "count", "min", "max", "distinctCount", "stdDev",
    "and", "or", "minForever", "maxForever", "unionSet",
}


def make_aggregator(name: str, arg_type: Optional[DataType]) -> Aggregator:
    if name == "sum":
        return SumAggregator(arg_type in (DataType.INT, DataType.LONG, None))
    if name == "count":
        return CountAggregator()
    if name == "avg":
        return AvgAggregator()
    if name == "min":
        return MinMaxAggregator(True)
    if name == "max":
        return MinMaxAggregator(False)
    if name == "minForever":
        return ForeverAggregator(True)
    if name == "maxForever":
        return ForeverAggregator(False)
    if name == "distinctCount":
        return DistinctCountAggregator()
    if name == "stdDev":
        return StdDevAggregator()
    if name == "and":
        return BoolAggregator(True)
    if name == "or":
        return BoolAggregator(False)
    if name == "unionSet":
        return UnionSetAggregator()
    raise KeyError(name)


def aggregator_return_type(name: str, arg_type: Optional[DataType]) -> DataType:
    if name in ("count", "distinctCount"):
        return DataType.LONG
    if name in ("avg", "stdDev"):
        return DataType.DOUBLE
    if name in ("and", "or"):
        return DataType.BOOL
    if name == "unionSet":
        return DataType.OBJECT
    if name == "sum":
        if arg_type in (DataType.FLOAT, DataType.DOUBLE):
            return DataType.DOUBLE
        return DataType.LONG
    return arg_type or DataType.OBJECT
