"""Stream junctions, input handlers, callbacks — the event bus.

Reference: ``core/stream/StreamJunction.java`` (pub/sub per stream, fault routing),
``stream/input/InputHandler.java``, ``stream/output/StreamCallback.java``,
``query/output/callback/QueryCallback.java``. The reference's optional LMAX
Disruptor async mode is replaced by the TPU path's micro-batching ingress; the
interpreter junction is synchronous and deterministic.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

import numpy as np

from ..query_api.definition import AbstractDefinition
from .event import Event, EventType, StreamEvent

log = logging.getLogger("siddhi_tpu.stream")


class OnErrorAction:
    LOG = "log"
    STREAM = "stream"
    STORE = "store"


class StreamJunction:
    """Per-stream event bus: receivers subscribe; publishers send.

    ``@async(buffer.size, workers, batch.size.max)`` on the stream definition
    switches the junction to asynchronous dispatch (the reference's Disruptor
    mode, ``StreamJunction.java:279-316``): ``send_event`` enqueues into an
    ``AsyncDispatcher`` and worker threads deliver under the app lock.
    """

    def __init__(self, definition: AbstractDefinition, app_context,
                 on_error_action: str = OnErrorAction.LOG):
        self.definition = definition
        self.app_context = app_context
        self.receivers: list = []          # objects with .receive(StreamEvent)
        self.on_error_action = on_error_action
        self.fault_junction: Optional["StreamJunction"] = None
        self.throughput = 0
        self.receiver_errors = 0           # every receiver failure counts —
        # multi-query fan-out faults must not collapse into one
        self.last_event_ts: Optional[int] = None   # newest delivered event
        # time — the watermark-lag gauge reads app clock minus this
        self.dispatcher = None             # AsyncDispatcher when @async
        self.flow = None                   # StreamFlow when @app:wal/@app:backpressure

    def subscribe(self, receiver) -> None:
        if receiver not in self.receivers:
            self.receivers.append(receiver)

    def unsubscribe(self, receiver) -> None:
        if receiver in self.receivers:
            self.receivers.remove(receiver)

    def enable_async(self, buffer_size: int = 1024, workers: int = 1,
                     batch_size_max: int = 64) -> None:
        from .async_junction import AsyncDispatcher
        self.dispatcher = AsyncDispatcher(
            self, self.app_context, buffer_size=buffer_size, workers=workers,
            batch_size_max=batch_size_max)

    def send_event(self, event: StreamEvent) -> None:
        if self.dispatcher is not None:
            # throughput counts at DELIVERY (worker, under the engine lock):
            # a bare += here would race between producer threads
            tracer = self.app_context.tracer
            if tracer is not None and event.trace is None:
                # the delivery worker is a different thread: the sampled
                # trace must ride the event across the queue (the handoff
                # mark becomes an ingress-queue span at delivery)
                event.trace = tracer.active
                if event.trace is not None:
                    event.trace.mark_handoff()
            self.dispatcher.enqueue(("event", event))
            return
        self.deliver_event(event)

    def send_events(self, events: list[StreamEvent]) -> None:
        """Deliver a chunk, preserving batch identity for chunk-aware receivers
        (``#window.batch()`` semantics depend on it)."""
        if not events:
            return
        if self.dispatcher is not None:
            tracer = self.app_context.tracer
            if tracer is not None and events[0].trace is None:
                events[0].trace = tracer.active
                if events[0].trace is not None:
                    events[0].trace.mark_handoff()
            self.dispatcher.enqueue(("chunk", events))
            return
        self.deliver_events(events)

    def _activate_trace(self, trace):
        """Re-activate a queue-carried trace on the delivery thread; returns
        True when a matching pop() is owed. The enqueue-to-delivery wait
        closes as an ``ingress-queue`` span (the handoff mark)."""
        tracer = self.app_context.tracer
        if tracer is None or trace is None or tracer.active is trace:
            return False
        trace.close_handoff(self.definition.id)
        tracer.push(trace)
        return True

    def deliver_event(self, event: StreamEvent) -> None:
        """Synchronous delivery into the receiver chain (worker entry point in
        async mode; delivery is serialized under the engine lock)."""
        self.throughput += 1
        self.last_event_ts = event.timestamp if self.last_event_ts is None \
            else max(self.last_event_ts, event.timestamp)
        pushed = self._activate_trace(event.trace)
        first_error = None
        try:
            for r in self.receivers:
                try:
                    r.receive(event)
                except Exception as e:  # noqa: BLE001 — per-receiver isolation:
                    # one faulty query must not starve the other subscribers
                    self._record_receiver_error(r, e)
                    if first_error is None:
                        first_error = e
        finally:
            if pushed:
                self.app_context.tracer.pop()
        if self.flow is not None and event.flow_seq is not None:
            # applied watermark advances under the engine lock: a quiesced
            # snapshot records a cut at a WAL record boundary
            self.flow.on_applied(event.flow_seq)
        if first_error is not None:
            # every failure was logged/counted above; the event routes to
            # fault handling ONCE — per-receiver routing would store/emit
            # the same event twice and duplicate it on replay
            self.handle_error(event, first_error)

    def rows_capable(self) -> bool:
        """True when every subscriber accepts raw row chunks — the columnar
        fast path can then skip per-event ``StreamEvent`` materialization
        entirely (measured ~35% of chunked-ingress wall time)."""
        return self.dispatcher is None and self.flow is None and \
            self.receivers and \
            all(hasattr(r, "receive_rows") for r in self.receivers)

    def deliver_rows(self, rows: list, timestamps) -> None:
        """Zero-wrap chunk delivery to rows-capable receivers (see
        ``rows_capable``). Caller transfers ownership of ``rows``."""
        self.throughput += len(rows)
        newest = max(timestamps)
        self.last_event_ts = newest if self.last_event_ts is None \
            else max(self.last_event_ts, newest)
        for r in self.receivers:
            try:
                r.receive_rows(rows, timestamps)
            except Exception as e:  # noqa: BLE001 — per-receiver isolation,
                # same contract as deliver_events; fault routing sees the
                # chunk as StreamEvents (rare path, built on demand)
                self._record_receiver_error(r, e)
                self.handle_error(
                    [StreamEvent(ts, list(row), EventType.CURRENT)
                     for row, ts in zip(rows, timestamps)], e)

    def columns_capable(self) -> bool:
        """True when every subscriber accepts whole columnar chunks — the
        zero-object edge then hands numpy columns end to end (source →
        junction → sink) with no per-event Python objects at all. Unlike
        ``rows_capable`` an empty receiver list IS capable: the chunk is
        counted and dropped, same as ``send_events`` to a bare junction."""
        return self.dispatcher is None and self.flow is None and \
            all(hasattr(r, "receive_columns") for r in self.receivers)

    def deliver_columns(self, cols: dict, ts: np.ndarray, n: int) -> None:
        """Zero-object chunk delivery to columns-capable receivers (see
        ``columns_capable``). ``cols`` maps attribute name → numpy column;
        receivers must not mutate them."""
        self.throughput += n
        newest = int(ts.max()) if n else 0
        self.last_event_ts = newest if self.last_event_ts is None \
            else max(self.last_event_ts, newest)
        for r in self.receivers:
            try:
                r.receive_columns(cols, ts, n)
            except Exception as e:  # noqa: BLE001 — per-receiver isolation,
                # same contract as deliver_rows; fault routing sees the
                # chunk as StreamEvents (failure path, built on demand)
                self._record_receiver_error(r, e)
                self.handle_error(self._columns_fault_events(cols, ts, n), e)

    def _columns_fault_events(self, cols: dict, ts, n: int) -> list:
        from .columns import columns_to_rows
        rows = columns_to_rows(cols, self.definition.attribute_names, n)
        return [StreamEvent(int(t), row, EventType.CURRENT)
                for row, t in zip(rows, np.asarray(ts).tolist())]

    def deliver_events(self, events: list[StreamEvent]) -> None:
        self.throughput += len(events)
        newest = max(e.timestamp for e in events)
        self.last_event_ts = newest if self.last_event_ts is None \
            else max(self.last_event_ts, newest)
        pushed = self._activate_trace(events[0].trace)
        failures = {}           # id(event|chunk) -> (target, first exception)
        try:
            for r in self.receivers:
                if hasattr(r, "receive_chunk"):
                    try:
                        r.receive_chunk(events)
                    except Exception as e:  # noqa: BLE001 — chunk receivers
                        # process the batch as one unit: the failure is
                        # attributed to the chunk, not an arbitrary member
                        self._record_receiver_error(r, e)
                        failures.setdefault(id(events), (events, e))
                else:
                    for ev in events:
                        try:
                            r.receive(ev)
                        except Exception as e:  # noqa: BLE001 — attribute the
                            # failure to the event that actually raised
                            self._record_receiver_error(r, e)
                            failures.setdefault(id(ev), (ev, e))
        finally:
            if pushed:
                self.app_context.tracer.pop()
        if self.flow is not None:
            seqs = [e.flow_seq for e in events if e.flow_seq is not None]
            if seqs:
                self.flow.on_applied(max(seqs))
        # one fault route per failed event (all failures counted above). A
        # chunk-level failure covers every member, so it supersedes any
        # per-event failures — routing both would store an event twice and
        # duplicate it on replay.
        if id(events) in failures:
            self.handle_error(events, failures[id(events)][1])
        else:
            for target, e in failures.values():
                self.handle_error(target, e)

    def _record_receiver_error(self, receiver, e: Exception) -> None:
        self.receiver_errors += 1
        log.error("receiver %s failed on stream '%s': %s",
                  type(receiver).__name__, self.definition.id, e)

    def handle_error(self, event, e: Exception) -> None:
        """Fault routing for one failed event — or a whole chunk when a
        chunk-aware receiver failed mid-batch (each member is routed)."""
        events = event if isinstance(event, list) else [event]
        if self.on_error_action == OnErrorAction.STREAM and self.fault_junction:
            for ev in events:
                # the fault definition declares _error OBJECT: carry the
                # exception itself (reference fault streams), not str(e)
                self.fault_junction.send_event(StreamEvent(
                    ev.timestamp, list(ev.data) + [e], ev.type))
            return
        if self.on_error_action == OnErrorAction.STORE:
            store = getattr(self.app_context.siddhi_context, "error_store", None)
            if store is not None:
                for ev in events:
                    store.save(self.app_context.name, self.definition.id,
                               ev, e, occurrence="before")
                return
        listener = self.app_context.exception_listener
        if listener is not None:
            listener(e)
        else:
            # LOG action (the default): record and continue — the event is
            # dropped, the app keeps running (reference OnErrorAction.LOG)
            log.error("error on stream '%s': %s", self.definition.id, e,
                      exc_info=True)


class InputHandler:
    """User-facing ingress for one stream (reference ``InputHandler.java``)."""

    def __init__(self, stream_id: str, junction: StreamJunction, app_context):
        self.stream_id = stream_id
        self.junction = junction
        self.app_context = app_context
        self.flow = None                # StreamFlow: WAL + admission gate

    def send(self, data, timestamp: Optional[int] = None) -> None:
        """Accepts ``[a, b, c]``, ``Event``, or ``list[Event]``."""
        tracer = self.app_context.tracer
        if tracer is None:
            self._send(data, timestamp)
            return
        tr = tracer.maybe_trace(self.stream_id)
        if tr is None:
            self._send(data, timestamp)
            return
        # sampled: the ingress span covers admission/WAL/dispatch; the
        # trace stays stack-active so synchronous downstream stages (query,
        # window, selector, sink) attach their spans without any plumbing
        n = len(data) if data and not isinstance(data, Event) \
            and isinstance(data[0], Event) else 1
        t0 = time.perf_counter_ns()
        tracer.push(tr)
        outcome = "error"
        try:
            outcome = self._send(data, timestamp) or "ok"
        finally:
            tracer.pop()
            tr.add_span("ingress", self.stream_id,
                        time.perf_counter_ns() - t0, n, outcome)

    def _send(self, data, timestamp: Optional[int] = None):
        if self.flow is not None and not self.flow.replaying:
            return self._send_flow(data, timestamp)
        if self.junction.dispatcher is not None:
            # async junction: producers only touch the queue mutex — the
            # watermark advances at DELIVERY time on the worker (under the
            # engine lock), so timers fire in processing order
            if isinstance(data, Event):
                self._check_arity(data.data)
                self.junction.send_event(
                    StreamEvent(data.timestamp, list(data.data),
                                EventType.CURRENT))
            elif data and isinstance(data[0], Event):
                for ev in data:
                    self._check_arity(ev.data)
                self.junction.send_events([
                    StreamEvent(ev.timestamp, list(ev.data), EventType.CURRENT)
                    for ev in data
                ])
            else:
                ts = timestamp if timestamp is not None \
                    else self.app_context.current_time()
                self._check_arity(data)
                self.junction.send_event(
                    StreamEvent(ts, list(data), EventType.CURRENT))
            return
        with self.app_context.root_lock:
            if isinstance(data, Event):
                self._send_one(data.timestamp, data.data)
            elif data and isinstance(data[0], Event):
                # watermark: only advance to the chunk's FIRST timestamp before
                # delivery — firing later timers first would reorder events
                # around window boundaries; the rest advances after the chunk
                self.app_context.advance_time(min(ev.timestamp for ev in data))
                for ev in data:
                    self._check_arity(ev.data)
                self.junction.send_events([
                    StreamEvent(ev.timestamp, list(ev.data), EventType.CURRENT)
                    for ev in data
                ])
                self.app_context.advance_time(max(ev.timestamp for ev in data))
            else:
                ts = timestamp if timestamp is not None else self.app_context.current_time()
                self._send_one(ts, list(data))

    def _send_flow(self, data, timestamp: Optional[int]) -> None:
        """Flow-controlled ingress: admission (overload policy) + WAL append
        ahead of delivery, then the vanilla dispatch semantics.

        The stream's flow lock is held from seq assignment through
        enqueue/delivery so WAL sequence order equals delivery order — a
        checkpoint watermark can then never cover a logged-but-undelivered
        lower seq (which recovery would skip, losing the event). Admission
        runs before the lock: BLOCK may sleep, and under the sync junction
        the lock order is root_lock → flow.lock everywhere."""
        chunk = False
        if isinstance(data, Event):
            rows, tss = [list(data.data)], [data.timestamp]
        elif data and isinstance(data[0], Event):
            rows = [list(ev.data) for ev in data]
            tss = [ev.timestamp for ev in data]
            chunk = True
        else:
            ts = timestamp if timestamp is not None \
                else self.app_context.current_time()
            rows, tss = [list(data)], [ts]
        for row in rows:
            self._check_arity(row)       # malformed rows must not hit the WAL
        if not self.flow.admit(len(rows)):
            # whole call shed by the gate; the ingress span records it
            return "shed"

        def build():
            events = [StreamEvent(ts, row, EventType.CURRENT)
                      for row, ts in zip(rows, tss)]
            seqs = self.flow.log(rows, tss)
            if seqs is not None:
                for ev, seq in zip(events, seqs):
                    ev.flow_seq = seq
            return events

        try:
            if self.junction.dispatcher is not None:
                with self.flow.lock:
                    events = build()
                    if chunk:
                        self.junction.send_events(events)
                    else:
                        self.junction.send_event(events[0])
                return
            with self.app_context.root_lock:
                with self.flow.lock:
                    events = build()
                    if chunk:
                        self.app_context.advance_time(
                            min(ev.timestamp for ev in events))
                        self.junction.send_events(events)
                        self.app_context.advance_time(
                            max(ev.timestamp for ev in events))
                    else:
                        self.app_context.advance_time(events[0].timestamp)
                        self.junction.send_event(events[0])
        finally:
            # the events are queued (depth_fn counts them) or delivery
            # failed: either way the admission reservation is done
            self.flow.release(len(rows))

    def send_rows(self, rows: list, timestamps) -> None:
        """Bulk ingress: one chunk of raw rows + per-row timestamps.

        The columnar fast path's preferred entry: the chunk reaches
        chunk-aware receivers (host/device bridges) as ONE micro-batch with
        no per-row ``Event`` wrapping. Semantics match a ``send`` of the
        equivalent ``Event`` list (watermark advances to the chunk minimum
        before delivery, to the maximum after)."""
        if not rows:
            return
        if len(rows) != len(timestamps):
            # zip would silently truncate on one path and desynchronize the
            # SoA stagers on the other — fail loudly instead
            raise ValueError(
                f"send_rows: {len(rows)} rows but {len(timestamps)} "
                f"timestamps")
        tracer = self.app_context.tracer
        if tracer is not None:
            # bulk ingress samples per CHUNK (one maybe_trace per call):
            # the columnar fast path must not pay per-row sampling checks
            tr = tracer.maybe_trace(self.stream_id)
            if tr is not None:
                t0 = time.perf_counter_ns()
                tracer.push(tr)
                try:
                    self._send_rows(rows, timestamps)
                finally:
                    tracer.pop()
                    tr.add_span("ingress", self.stream_id,
                                time.perf_counter_ns() - t0, len(rows))
                return
        self._send_rows(rows, timestamps)

    def _send_rows(self, rows: list, timestamps) -> None:
        if self.flow is not None and not self.flow.replaying:
            self._send([Event(ts, row) for row, ts in zip(rows, timestamps)])
            return
        arity = len(self.junction.definition.attributes)
        if any(len(r) != arity for r in rows):
            for row in rows:
                self._check_arity(row)         # raise with the full message
        if self.junction.rows_capable():
            # every subscriber is chunk-columnar: raw rows go straight into
            # the SoA stagers, no per-event StreamEvent materialization
            with self.app_context.root_lock:
                self.app_context.advance_time(min(timestamps))
                self.junction.deliver_rows(rows, timestamps)
                self.app_context.advance_time(max(timestamps))
            return
        events = [StreamEvent(ts, row, EventType.CURRENT)
                  for row, ts in zip(rows, timestamps)]
        if self.junction.dispatcher is not None:
            self.junction.send_events(events)
            return
        with self.app_context.root_lock:
            self.app_context.advance_time(
                min(ev.timestamp for ev in events))
            self.junction.send_events(events)
            self.app_context.advance_time(
                max(ev.timestamp for ev in events))

    def send_columns(self, cols: dict, timestamps=None,
                     count: Optional[int] = None) -> None:
        """Zero-object bulk ingress: one columnar chunk ({attribute name:
        numpy array | DictColumn}, optional int64 per-row timestamps).

        The preferred edge entry (columnar sources, the in-memory broker's
        rows chunks): when every subscriber is columns-capable the chunk
        reaches the SoA stagers with NO per-event Python objects at all;
        otherwise it degrades to the ``send_rows`` semantics. ``timestamps``
        None stamps the app's current time on every row."""
        from .columns import column_length
        n = count
        if n is None:
            n = int(len(timestamps)) if timestamps is not None else (
                column_length(next(iter(cols.values()))) if cols else 0)
        if n == 0:
            return
        names = self.junction.definition.attribute_names
        missing = [a for a in names if a not in cols]
        if missing:
            from .errors import SiddhiAppRuntimeError
            raise SiddhiAppRuntimeError(
                f"stream '{self.stream_id}': send_columns missing "
                f"column(s) {missing}")
        for name in names:
            if column_length(cols[name]) != n:
                raise ValueError(
                    f"send_columns: column '{name}' has "
                    f"{column_length(cols[name])} values but the chunk has "
                    f"{n} rows")
        if timestamps is None:
            ts = np.full(n, self.app_context.current_time(), dtype=np.int64)
        else:
            ts = np.asarray(timestamps, dtype=np.int64)
            if ts.shape[0] != n:
                raise ValueError(
                    f"send_columns: {n} rows but {ts.shape[0]} timestamps")
        tracer = self.app_context.tracer
        if tracer is not None:
            # chunk-level sampling, same policy as send_rows
            tr = tracer.maybe_trace(self.stream_id)
            if tr is not None:
                t0 = time.perf_counter_ns()
                tracer.push(tr)
                try:
                    self._send_columns(cols, ts, n)
                finally:
                    tracer.pop()
                    tr.add_span("ingress", self.stream_id,
                                time.perf_counter_ns() - t0, n)
                return
        self._send_columns(cols, ts, n)

    def _send_columns(self, cols: dict, ts: np.ndarray, n: int) -> None:
        j = self.junction
        if self.flow is None and j.dispatcher is None and \
                j.columns_capable():
            with self.app_context.root_lock:
                self.app_context.advance_time(int(ts.min()))
                j.deliver_columns(cols, ts, n)
                self.app_context.advance_time(int(ts.max()))
            return
        self._send_columns_fallback(cols, ts, n)

    def _send_columns_fallback(self, cols: dict, ts: np.ndarray,
                               n: int) -> None:
        """Non-columnar subscribers (or WAL/@async ingress): materialize
        rows once and take the ``send_rows`` path."""
        from .columns import columns_to_rows
        rows = columns_to_rows(cols, self.junction.definition.attribute_names,
                               n)
        self._send_rows(rows, ts.tolist())

    def _check_arity(self, data) -> None:
        defn = self.junction.definition
        if len(data) != len(defn.attributes):
            from .errors import SiddhiAppRuntimeError
            sig = ", ".join(f"{a.name} {a.type.value}" for a in defn.attributes)
            raise SiddhiAppRuntimeError(
                f"stream '{self.stream_id}' expects {len(defn.attributes)} "
                f"attributes ({sig}) but got {len(data)}: {data!r}")

    def _send_one(self, ts: int, data: list) -> None:
        self._check_arity(data)
        # watermark: advance clock & fire due timers before the event itself
        self.app_context.advance_time(ts)
        self.junction.send_event(StreamEvent(ts, data, EventType.CURRENT))


class StreamCallback:
    """Subscribe to a stream's output events (subclass or wrap a function)."""

    def __init__(self, fn: Optional[Callable[[list[Event]], None]] = None):
        self._fn = fn

    def receive(self, events: list[Event]) -> None:
        if self._fn:
            self._fn(events)

    # junction receiver adapter
    def receive_stream_event(self, event: StreamEvent) -> None:
        self.receive([Event(event.timestamp, event.data,
                            event.type == EventType.EXPIRED)])


class _StreamCallbackReceiver:
    """Adapts a StreamCallback to the junction receiver interface."""

    def __init__(self, callback: StreamCallback):
        self.callback = callback

    def receive(self, event: StreamEvent) -> None:
        if event.type in (EventType.CURRENT, EventType.EXPIRED):
            self.callback.receive_stream_event(event)


class RowsCallback:
    """Columns-capable stream subscription: ``fn(cols, ts, n)`` receives
    whole columnar chunks (zero per-event objects); per-event deliveries
    degrade to one synthesized chunk call. Subscribe via
    ``SiddhiAppRuntime.add_rows_callback``."""

    def __init__(self, fn: Callable):
        self._fn = fn

    def receive_columns(self, cols: dict, ts, n: int) -> None:
        self._fn(cols, ts, n)

    def receive(self, event: StreamEvent) -> None:
        if event.type is not EventType.CURRENT:
            return
        names = getattr(self, "names", None) or [
            f"c{i}" for i in range(len(event.data))]
        cols = {nm: np.asarray([v], dtype=object)
                for nm, v in zip(names, event.data)}
        self._fn(cols, np.asarray([event.timestamp], np.int64), 1)


class QueryCallback:
    """Per-query callback: receive(timestamp, current_events, expired_events)."""

    def __init__(self, fn: Optional[Callable] = None):
        self._fn = fn

    def receive(self, timestamp: int, in_events: Optional[list[Event]],
                out_events: Optional[list[Event]]) -> None:
        if self._fn:
            self._fn(timestamp, in_events, out_events)
