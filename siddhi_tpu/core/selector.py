"""QuerySelector: projection, aggregation, group-by, having, order-by/limit.

Reference: ``core/query/selector/QuerySelector.java`` (processGroupBy:207,
processInBatchGroupBy:315), ``GroupByKeyGenerator``, ``OrderByEventComparator``.
The reference's ThreadLocal group-by flow keys become explicit per-key aggregator
maps here (batch-synchronous, no thread-locals).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..query_api import (
    AttributeFunction,
    DataType,
    OrderByOrder,
    Selector,
)
from .aggregators import (
    AGGREGATOR_NAMES,
    Aggregator,
    aggregator_return_type,
    make_aggregator,
)
from .event import EventType, JoinedEvent, PatternEvent, StateEvent, StreamEvent
from .executor import ExecutorBuilder, JoinFrame, RowFrame, StateFrame, StreamFrame


class AttributeSpec:
    """One output column: stateless expression or stateful aggregation."""

    def __init__(self, name: str, dtype: DataType,
                 value_fn: Optional[Callable] = None,
                 agg_name: Optional[str] = None,
                 agg_arg_fn: Optional[Callable] = None,
                 agg_arg_type: Optional[DataType] = None,
                 agg_filter_fn: Optional[Callable] = None):
        self.name = name
        self.dtype = dtype
        self.value_fn = value_fn          # stateless path
        self.agg_name = agg_name          # stateful path
        self.agg_arg_fn = agg_arg_fn
        self.agg_arg_type = agg_arg_type
        self.agg_filter_fn = agg_filter_fn

    @property
    def is_aggregate(self) -> bool:
        return self.agg_name is not None


def make_frame(ev: StreamEvent):
    if isinstance(ev, PatternEvent):
        return StateFrame(ev.state_event)
    if isinstance(ev, JoinedEvent):
        return JoinFrame(ev.left, ev.right, ev.timestamp)
    return StreamFrame(ev)


class QuerySelector:
    def __init__(self, attributes: list[AttributeSpec],
                 group_by_fns: list[Callable],
                 having_fn: Optional[Callable],
                 order_by: list[tuple[int, OrderByOrder]],
                 limit: Optional[int], offset: Optional[int],
                 element_id: str = "selector"):
        self.attributes = attributes
        self.group_by_fns = group_by_fns
        self.having_fn = having_fn
        self.order_by = order_by            # (output position, order)
        self.limit = limit
        self.offset = offset
        self.element_id = element_id
        self.has_aggregates = any(a.is_aggregate for a in attributes)
        # batching-window upstream (lengthBatch/timeBatch/...): aggregated
        # chunks collapse to the LAST surviving row — last per key under
        # group-by (reference QuerySelector.processInBatchNoGroupBy:271 /
        # processInBatchGroupBy:315). Set by the query builder.
        self.batching = False
        # which event kinds the query OUTPUTS (``insert [all|expired]
        # events``) — the collapse's "last SURVIVING event" honors this
        # (reference currentOn/expiredOn gating inside the selector); the
        # per-event paths keep gating downstream in the output callback
        self.current_on = True
        self.expired_on = True
        # group key -> {attr index -> Aggregator}
        self.agg_states: dict[Any, dict[int, Aggregator]] = {}
        self.next = None                    # rate limiter / output callback

    @property
    def output_names(self) -> list[str]:
        return [a.name for a in self.attributes]

    @property
    def output_types(self) -> list[DataType]:
        return [a.dtype for a in self.attributes]

    def _group_key(self, frame) -> Any:
        if not self.group_by_fns:
            return None
        return tuple(fn(frame) for fn in self.group_by_fns)

    def _aggs_for(self, key: Any) -> dict[int, Aggregator]:
        aggs = self.agg_states.get(key)
        if aggs is None:
            aggs = {
                i: make_aggregator(a.agg_name, a.agg_arg_type)
                for i, a in enumerate(self.attributes)
                if a.is_aggregate
            }
            self.agg_states[key] = aggs
        return aggs

    def process(self, events: list[StreamEvent]) -> None:
        collapse = self.batching and (self.has_aggregates or
                                      bool(self.group_by_fns))
        out: list[StreamEvent] = []
        out_keys: list = []
        for ev in events:
            if ev.type == EventType.RESET:
                for aggs in self.agg_states.values():
                    for a in aggs.values():
                        a.reset()
                continue
            if ev.type == EventType.TIMER:
                continue
            frame = make_frame(ev)
            key = self._group_key(frame) if self.group_by_fns or \
                (self.has_aggregates or collapse) else None
            data: list = []
            aggs = self._aggs_for(key) if self.has_aggregates else {}
            for i, spec in enumerate(self.attributes):
                if spec.is_aggregate:
                    agg = aggs[i]
                    if spec.agg_filter_fn is None or bool(spec.agg_filter_fn(frame)):
                        v = spec.agg_arg_fn(frame) if spec.agg_arg_fn else None
                        if ev.type == EventType.CURRENT:
                            agg.add(v)
                        elif ev.type == EventType.EXPIRED:
                            agg.remove(v)
                    data.append(agg.value())
                else:
                    data.append(spec.value_fn(frame))
            if self.having_fn is not None:
                if not bool(self.having_fn(
                        HavingFrame(data, ev.timestamp, frame))):
                    continue
            oev = StreamEvent(ev.timestamp, data, ev.type)
            if self.group_by_fns:
                # reference GroupedComplexEvent: grouped first/last rate
                # limiters downstream batch per key
                oev.group_key = key
            out.append(oev)
            out_keys.append(key)
        if not out:
            return
        if collapse:
            # one row per batch chunk: the last surviving event (last per
            # key under group-by, first-seen key order — the reference's
            # LinkedHashMap). Surviving = passing the query's output-kind
            # gate, so `insert into` never collapses onto an expired row.
            pairs = [(ev, key) for ev, key in zip(out, out_keys)
                     if (ev.type == EventType.CURRENT and self.current_on)
                     or (ev.type == EventType.EXPIRED and self.expired_on)]
            if self.group_by_fns:
                last_by_key: dict = {}
                for ev, key in pairs:
                    last_by_key[key] = ev
                out = list(last_by_key.values())
            else:
                out = [pairs[-1][0]] if pairs else []
            if not out:
                return
        if self.order_by or self.limit is not None \
                or self.offset is not None:
            # the reference removes non-output event kinds INSIDE the
            # selector before order/limit (processNoGroupBy's gate) — a
            # mixed [expired..., current...] flush chunk must not have its
            # limit slots consumed by rows the query never outputs
            out = [ev for ev in out
                   if (ev.type == EventType.CURRENT and self.current_on)
                   or (ev.type == EventType.EXPIRED and self.expired_on)]
        out = self._order_limit(out)
        if self.next is not None and out:
            self.next.process(out)

    def _order_limit(self, events: list[StreamEvent]) -> list[StreamEvent]:
        if self.order_by:
            def keyf(ev):
                ks = []
                for pos, order in self.order_by:
                    v = ev.data[pos]
                    ks.append(_Rev(v) if order == OrderByOrder.DESC else v)
                return tuple(ks)
            events = sorted(events, key=keyf)
        if self.offset is not None:
            events = events[self.offset:]
        if self.limit is not None:
            events = events[: self.limit]
        return events

    # -- state ----------------------------------------------------------------
    def snapshot_state(self) -> dict:
        return {
            "aggs": {
                repr(key): {i: a.snapshot() for i, a in aggs.items()}
                for key, aggs in self.agg_states.items()
            },
            "keys": list(self.agg_states.keys()),
        }

    def restore_state(self, state: dict) -> None:
        self.agg_states = {}
        for key in state["keys"]:
            aggs = self._aggs_for(key)
            saved = state["aggs"][repr(key)]
            for i, a in aggs.items():
                a.restore(saved[i])


class HavingFrame:
    """Evaluation frame for having conditions: the projected output row
    (``.data`` — RowFrame protocol) plus the pre-projection input frame
    (``.src``) for input-attribute references."""
    __slots__ = ("data", "ts", "src")

    def __init__(self, data: list, ts: int, src):
        self.data = data
        self.ts = ts
        self.src = src

    def timestamp(self) -> int:
        return self.ts


class _HavingResolver:
    """Output aliases first (unprefixed), then the query's input resolver
    over ``frame.src`` (reference: having sees the whole meta event)."""

    def __init__(self, out_names, out_types, input_resolver):
        self.out_names = out_names
        self.out_types = out_types
        self.input_resolver = input_resolver

    def resolve(self, var):
        if var.stream_id is None and var.attribute in self.out_names:
            pos = self.out_names.index(var.attribute)
            return (lambda f: f.data[pos]), self.out_types[pos]
        fn, t = self.input_resolver.resolve(var)
        return (lambda f: fn(f.src)), t

    def encode_string(self, key, value):       # pragma: no cover - delegate
        return self.input_resolver.encode_string(key, value)


class _Rev:
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        if self.v is None or other.v is None:
            return other.v is None
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v


def build_selector(selector: Selector, builder: ExecutorBuilder,
                   input_names: list[str], input_types: list[DataType],
                   element_id: str = "selector") -> QuerySelector:
    """Compile a Selector AST into a QuerySelector using the given executor
    builder (whose resolver matches the query's input kind)."""
    from ..query_api import OutputAttribute, Variable

    attrs_ast = list(selector.attributes)
    if selector.select_all:
        attrs_ast = [
            OutputAttribute(None, Variable(attribute=n)) for n in input_names
        ]

    specs: list[AttributeSpec] = []
    for oa in attrs_ast:
        expr = oa.expr
        if isinstance(expr, AttributeFunction) and expr.namespace is None \
                and expr.name in AGGREGATOR_NAMES:
            if expr.args:
                arg_fn, arg_t = builder.build(expr.args[0])
            else:
                arg_fn, arg_t = (lambda f: None), None
            specs.append(AttributeSpec(
                oa.name, aggregator_return_type(expr.name, arg_t),
                agg_name=expr.name, agg_arg_fn=arg_fn, agg_arg_type=arg_t,
            ))
        else:
            fn, t = builder.build(expr)
            specs.append(AttributeSpec(oa.name, t, value_fn=fn))

    group_fns = [builder.build(v)[0] for v in selector.group_by]

    having_fn = None
    if selector.having is not None:
        # the reference's having executor sees BOTH the projected output
        # attributes and the query's input attributes (its output meta event
        # still wraps the input state — JoinTestCase.joinTest14 pins
        # `having orders.items == "item1"` over a join). Output aliases win
        # for unprefixed names; prefixed or unknown names resolve through
        # the query's own input resolver against the pre-projection frame.
        out_names = [s.name for s in specs]
        out_types = [s.dtype for s in specs]
        hb = ExecutorBuilder(
            _HavingResolver(out_names, out_types, builder.resolver),
            builder.context)
        having_fn, _ = hb.build(selector.having)

    order_by = []
    out_names = [s.name for s in specs]
    for ob in selector.order_by:
        if ob.variable.attribute not in out_names:
            raise ValueError(f"order by unknown output attribute '{ob.variable.attribute}'")
        order_by.append((out_names.index(ob.variable.attribute), ob.order))

    return QuerySelector(specs, group_fns, having_fn, order_by,
                         selector.limit, selector.offset, element_id)
