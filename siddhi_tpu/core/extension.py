"""Extension registry: the ``@extension`` decorator ≈ the reference's ``@Extension``
annotation + ``SiddhiExtensionLoader`` (annotation-scanned classpath loading,
``util/SiddhiExtensionLoader.java:99``). Python entry points replace classpath
scanning; kinds mirror the reference's extension types. Parameter metadata +
validation mirror ``siddhi-annotations`` (``@Parameter``/``@ParameterOverload``/
``@ReturnAttribute``/``@Example`` and
``util/extension/validator/InputParameterValidator.java``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..query_api.definition import DataType, StreamDefinition

GLOBAL_EXTENSIONS: dict[str, type] = {}

EXTENSION_KINDS = {
    "function",          # scalar function (FunctionExecutor)
    "aggregator",        # attribute aggregator
    "window",            # window processor
    "stream_function",   # stream processor / stream function
    "source", "sink", "source_mapper", "sink_mapper", "store",
}


@dataclass
class Parameter:
    """Reference ``@Parameter`` — one declared argument of an extension."""

    name: str
    types: list[DataType]
    description: str = ""
    optional: bool = False
    default: Optional[str] = None
    dynamic: bool = False


@dataclass
class ReturnAttribute:
    """Reference ``@ReturnAttribute``."""

    name: str
    types: list[DataType]
    description: str = ""


@dataclass
class Example:
    """Reference ``@Example``."""

    syntax: str
    description: str = ""


@dataclass
class ExtensionMeta:
    """Reference ``@Extension`` metadata block, attached as
    ``cls.extension_meta`` and consumed by the doc generator + validator."""

    name: str
    kind: str
    description: str = ""
    parameters: list[Parameter] = field(default_factory=list)
    return_attributes: list[ReturnAttribute] = field(default_factory=list)
    examples: list[Example] = field(default_factory=list)


def extension(name: str, kind: str = "function", description: str = "",
              parameters: Optional[list[Parameter]] = None,
              return_attributes: Optional[list[ReturnAttribute]] = None,
              examples: Optional[list[Example]] = None):
    """Class decorator: ``@extension("str:concat", kind="function",
    parameters=[Parameter("s1", [DataType.STRING]), ...])``.

    Parameter metadata, when given, is validated against call-site argument
    types at build time (reference ``InputParameterValidator``).
    """
    if kind not in EXTENSION_KINDS:
        raise ValueError(f"unknown extension kind '{kind}'")

    def deco(cls):
        cls.extension_kind = kind
        cls.extension_name = name
        plist = list(parameters or [])
        # positional validation matches arg i against params[i], which is only
        # sound when every optional parameter trails the required ones —
        # reject bad metadata at declaration, not with misleading call errors
        seen_optional = False
        for p in plist:
            if p.optional:
                seen_optional = True
            elif seen_optional:
                raise ValueError(
                    f"extension '{name}': required parameter '{p.name}' "
                    f"follows an optional one; optional parameters must be "
                    f"trailing")
        cls.extension_meta = ExtensionMeta(
            name=name, kind=kind, description=description,
            parameters=plist,
            return_attributes=list(return_attributes or []),
            examples=list(examples or []))
        GLOBAL_EXTENSIONS[name] = cls
        return cls

    return deco


def validate_extension_args(cls, arg_types: list[Optional[DataType]]) -> None:
    """Check call-site argument types against declared ``Parameter`` metadata
    (reference ``InputParameterValidator.java``). No-op without metadata."""
    meta: Optional[ExtensionMeta] = getattr(cls, "extension_meta", None)
    if meta is None or not meta.parameters:
        return
    params = meta.parameters
    required = sum(1 for p in params if not p.optional)
    if not (required <= len(arg_types) <= len(params)):
        expected = str(required) if required == len(params) else \
            f"{required}..{len(params)}"
        raise TypeError(
            f"extension '{meta.name}' expects {expected} argument(s), "
            f"got {len(arg_types)}")
    for i, at in enumerate(arg_types):
        p = params[i]
        if at is None or DataType.OBJECT in p.types:
            continue        # unknown/any — accept
        if at not in p.types:
            raise TypeError(
                f"extension '{meta.name}' parameter '{p.name}' accepts "
                f"{[t.value for t in p.types]}, got {at.value}")


class ScalarFunctionExtension:
    """Base for scalar function extensions.

    Subclasses implement ``execute(args) -> value`` and set ``return_type``.
    """

    extension_kind = "function"
    return_type: DataType = DataType.OBJECT

    def execute(self, args: list) -> Any:
        raise NotImplementedError

    def bind(self, arg_fns: list[Callable], arg_types: list[DataType]):
        def run(frame):
            return self.execute([fn(frame) for fn in arg_fns])
        return run, self.return_type


class StreamFunctionExtension:
    """Base for stream functions: N input attrs → appended output attrs.

    ``init`` returns the output StreamDefinition; ``process`` returns payload
    rows (input data + appended values).
    """

    extension_kind = "stream_function"

    def init(self, input_def: StreamDefinition, params, param_fns) -> StreamDefinition:
        raise NotImplementedError

    def process(self, event, param_values: list):
        raise NotImplementedError


class ScriptFunction:
    """``define function f[lang] return type { body }`` — script-language UDF.

    Supported languages: ``python`` (body is an expression or function body using
    ``data`` — the argument list). JavaScript bodies are not executable without a
    JS engine; defining them raises at build time (reference parity would need
    Nashorn/GraalJS).
    """

    def __init__(self, fid: str, language: str, return_type: DataType, body: str):
        self.id = fid
        self.language = language.lower()
        self.return_type = return_type
        self.body = body
        if self.language not in ("python", "py"):
            raise ValueError(
                f"script language '{language}' not supported (use python)")
        src = body.strip()
        ns: dict[str, Any] = {}
        try:
            code = compile(src, f"<function {fid}>", "eval")
            self._fn = lambda data: eval(code, {"__builtins__": {}}, {"data": data})  # noqa: S307
        except SyntaxError:
            indented = "\n".join("    " + line for line in src.splitlines())
            exec(compile(f"def __udf__(data):\n{indented}\n",  # noqa: S102
                         f"<function {fid}>", "exec"), ns)
            self._fn = ns["__udf__"]

    def bind(self, arg_fns: list[Callable], arg_types: list[DataType]):
        def run(frame):
            return self._fn([fn(frame) for fn in arg_fns])
        return run, self.return_type
