"""On-demand (store) queries: pull queries against tables / named windows.

Reference: ``core/query/OnDemandQueryRuntime`` + ``util/parser/OnDemandQueryParser``.
"""

from __future__ import annotations

from typing import Optional

from ..query_api import (
    AttributeFunction,
    OnDemandQuery,
    OnDemandQueryType,
    OutputAttribute,
    Variable,
)
from .aggregators import AGGREGATOR_NAMES, aggregator_return_type, make_aggregator
from .event import Event
from .executor import ExecutorBuilder, RowFrame, RowResolver
from .table import compile_table_condition, TableMatchFrame


class OnDemandQueryRuntime:
    def __init__(self, odq: OnDemandQuery, app_context):
        self.odq = odq
        self.app_context = app_context

    def execute(self) -> list[Event]:
        odq = self.odq
        ctx = self.app_context
        now = ctx.current_time()
        store_id = odq.input_store_id

        if odq.type == OnDemandQueryType.INSERT:
            target = odq.output_stream.target_id
            table = ctx.get_table(target)
            builder = ExecutorBuilder(RowResolver([], []), ctx)
            row = [builder.build(a.expr)[0](RowFrame([], now))
                   for a in odq.selector.attributes]
            table.add([row], now)
            return []

        # resolve rows from table or named window
        if store_id in ctx.tables:
            table = ctx.get_table(store_id)
            names = table.definition.attribute_names
            types = [a.type for a in table.definition.attributes]
            # the `on` may sit on the query or on its table action; no "matching
            # event" side exists in on-demand queries: all refs bind to rows
            on = odq.on_condition or getattr(odq.output_stream, "on_condition", None)
            cond = compile_table_condition(table, on, [], [], ctx)
            if odq.type == OnDemandQueryType.DELETE:
                if cond is not None:
                    table.delete(cond, [], now)
                else:
                    table.restore_state({"rows": []})
                return []
            if odq.type in (OnDemandQueryType.UPDATE, OnDemandQueryType.UPDATE_OR_INSERT):
                setters = []
                for sa in odq.output_stream.set_attributes:
                    pos = table.definition.attribute_position(sa.table_variable.attribute)
                    b = ExecutorBuilder(
                        RowResolver(names, types, table.definition.id), ctx)
                    fn, _ = b.build(sa.value_expr)
                    setters.append((pos, lambda f, fn=fn: fn(RowFrame(f.row or []))))
                if odq.type == OnDemandQueryType.UPDATE:
                    table.update(cond, [], setters, now)
                else:
                    table.update_or_add(cond, [], setters, now)
                return []
            # hand the compiled condition to the table: record stores push it
            # down (StoreExpression), in-memory tables use the PK fast path
            rows = [list(r) for r in table.find(cond, None, now)]
        elif store_id in ctx.named_windows:
            nw = ctx.named_windows[store_id]
            names = nw.definition.attribute_names
            types = [a.type for a in nw.definition.attributes]
            rows = [list(e.data) for e in nw.find_events()]
            if odq.on_condition is not None:
                b = ExecutorBuilder(RowResolver(names, types), ctx)
                fn, _ = b.build(odq.on_condition)
                rows = [r for r in rows if bool(fn(RowFrame(r, now)))]
        elif store_id in ctx.aggregations:
            return ctx.aggregations[store_id].on_demand_find(odq, now)
        else:
            raise KeyError(f"no table/window/aggregation '{store_id}'")

        return self._select(rows, names, types, now)

    # -- FIND projection with optional fold-style aggregation ----------------
    def _select(self, rows: list[list], names: list[str], types, now: int) -> list[Event]:
        sel = self.odq.selector
        builder = ExecutorBuilder(RowResolver(names, types), self.app_context)

        attrs = list(sel.attributes)
        if sel.select_all or not attrs:
            attrs = [OutputAttribute(None, Variable(attribute=n)) for n in names]

        has_agg = any(
            isinstance(a.expr, AttributeFunction) and a.expr.namespace is None
            and a.expr.name in AGGREGATOR_NAMES for a in attrs
        )
        group_fns = [builder.build(v)[0] for v in sel.group_by]

        if not has_agg:
            fns = [builder.build(a.expr)[0] for a in attrs]
            out = []
            for r in rows:
                frame = RowFrame(r, now)
                out.append(Event(now, [fn(frame) for fn in fns]))
            return self._post(out, attrs, now)

        # fold aggregation per group
        groups: dict = {}
        order: list = []
        for r in rows:
            frame = RowFrame(r, now)
            key = tuple(fn(frame) for fn in group_fns) if group_fns else None
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(r)

        # compile each attribute once; fold per group
        compiled = []
        for a in attrs:
            e = a.expr
            if isinstance(e, AttributeFunction) and e.namespace is None \
                    and e.name in AGGREGATOR_NAMES:
                arg_fn, arg_t = builder.build(e.args[0]) if e.args \
                    else ((lambda f: None), None)
                compiled.append(("agg", e.name, arg_fn, arg_t))
            else:
                compiled.append(("value", None, builder.build(e)[0], None))
        out = []
        for key in order:
            grows = groups[key]
            data = []
            for kind, agg_name, fn, arg_t in compiled:
                if kind == "agg":
                    agg = make_aggregator(agg_name, arg_t)
                    for r in grows:
                        agg.add(fn(RowFrame(r, now)))
                    data.append(agg.value())
                else:
                    data.append(fn(RowFrame(grows[-1], now)))
            out.append(Event(now, data))
        return self._post(out, attrs, now)

    def _post(self, events: list[Event], attrs, now: int) -> list[Event]:
        sel = self.odq.selector
        out_names = []
        for a in attrs:
            try:
                out_names.append(a.name)
            except ValueError:
                out_names.append(f"_c{len(out_names)}")
        if sel.having is not None:
            types = [None] * len(out_names)
            from ..query_api.definition import DataType
            b = ExecutorBuilder(
                RowResolver(out_names, [DataType.OBJECT] * len(out_names)),
                self.app_context)
            fn, _ = b.build(sel.having)
            events = [e for e in events if bool(fn(RowFrame(e.data, now)))]
        if sel.order_by:
            for ob in reversed(sel.order_by):
                pos = out_names.index(ob.variable.attribute)
                events.sort(key=lambda e: (e.data[pos] is None, e.data[pos]),
                            reverse=(ob.order.value == "desc"))
        if sel.offset:
            events = events[sel.offset:]
        if sel.limit is not None:
            events = events[: sel.limit]
        return events
