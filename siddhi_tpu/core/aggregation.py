"""Incremental aggregations: multi-duration rollup cascade.

Reference: ``core/aggregation/`` — ``AggregationRuntime.java``,
``IncrementalExecutor.java`` (bucket rollover), per-duration stores, on-demand
``within ... per ...`` retrieval. Redesigned: buckets are keyed dicts of running
aggregator states per duration; rollups happen by bucketing the event timestamp
directly into every requested duration (equivalent results, no cascade chain —
the cascade is an optimization the TPU path reintroduces as segmented scans).
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Optional

from ..query_api import (
    AttributeFunction,
    OnDemandQuery,
    OutputAttribute,
    Variable,
)
from ..query_api.definition import AggregationDefinition, TimePeriodDuration
from .aggregators import AGGREGATOR_NAMES, aggregator_return_type, make_aggregator
from .errors import SiddhiAppRuntimeError
from .event import Event, EventType, StreamEvent
from .executor import ExecutorBuilder, StreamFrame, StreamResolver

_MS = {
    TimePeriodDuration.SECONDS: 1000,
    TimePeriodDuration.MINUTES: 60_000,
    TimePeriodDuration.HOURS: 3_600_000,
    TimePeriodDuration.DAYS: 86_400_000,
}


def parse_within_value(v) -> int:
    """One bound of a two-arg ``within start, end``: epoch-ms int or a fully
    specified date string 'YYYY-MM-DD HH:MM:SS[ +HH:MM]' (no wildcards)."""
    if isinstance(v, (int, float)):
        return int(v)
    if isinstance(v, str):
        if "*" in v:
            raise SiddhiAppRuntimeError(
                f"wildcards are only valid in single-value within: {v!r}")
        return _date_ms(v)
    raise SiddhiAppRuntimeError("within bound must be a constant timestamp or date string")


def parse_within_single(v) -> tuple[Optional[int], Optional[int]]:
    """Single-arg ``within``: a wildcard pattern covers its whole period
    (reference: ``aggregation/AggregationRuntime.java`` within handling —
    '2017-06-** **:**:**' means all of June 2017). Returns [start, end)."""
    if isinstance(v, (int, float)):
        return int(v), None
    if not isinstance(v, str):
        raise SiddhiAppRuntimeError("within bound must be a constant timestamp or date string")
    text, tz = _split_tz(v.strip())
    try:
        date_part, time_part = text.split()
        y_s, mo_s, d_s = date_part.split("-")
        h_s, mi_s, s_s = time_part.split(":")
    except ValueError:
        raise SiddhiAppRuntimeError(f"cannot parse within bound {v!r}") from None
    if "*" in y_s:
        return None, None  # every year: unbounded
    fields = [mo_s, d_s, h_s, mi_s, s_s]
    mins = [1, 1, 0, 0, 0]
    wild = ["*" in f for f in fields]
    first = wild.index(True) if any(wild) else 5
    if not all(wild[first:]):
        raise SiddhiAppRuntimeError(
            f"within wildcards must be a contiguous suffix: {v!r}")
    vals = [int(f) if not w else m for f, w, m in zip(fields, wild, mins)]
    y = int(y_s)
    start_dt = _dt.datetime(y, vals[0], vals[1], vals[2], vals[3], vals[4], tzinfo=tz)
    if first == 0:
        end_dt = _dt.datetime(y + 1, 1, 1, tzinfo=tz)
    elif first == 1:
        end_dt = (_dt.datetime(y + 1, 1, 1, tzinfo=tz) if vals[0] == 12
                  else _dt.datetime(y, vals[0] + 1, 1, tzinfo=tz))
    else:
        unit = {2: _dt.timedelta(days=1), 3: _dt.timedelta(hours=1),
                4: _dt.timedelta(minutes=1), 5: _dt.timedelta(seconds=1)}[first]
        end_dt = start_dt + unit
    return int(start_dt.timestamp() * 1000), int(end_dt.timestamp() * 1000)


def _split_tz(text: str):
    # trailing ' +HH:MM' / ' -HH:MM' timezone offset; default UTC
    if len(text) > 6 and text[-6] in "+-" and text[-3] == ":" and text[-7] == " ":
        sign = -1 if text[-6] == "-" else 1
        h, m = int(text[-5:-3]), int(text[-2:])
        return text[:-7], _dt.timezone(sign * _dt.timedelta(hours=h, minutes=m))
    return text, _dt.timezone.utc


def _date_ms(text: str) -> int:
    text, tz = _split_tz(text.strip())
    dt = _dt.datetime.strptime(text, "%Y-%m-%d %H:%M:%S").replace(tzinfo=tz)
    return int(dt.timestamp() * 1000)


_PURGE_DEFAULT_RETENTION: dict[TimePeriodDuration, Optional[int]] = {
    # reference IncrementalDataPurger defaults: sec 120s, min 24h, hours 30d,
    # days 1 year, months/years never purged
    TimePeriodDuration.SECONDS: 120_000,
    TimePeriodDuration.MINUTES: 86_400_000,
    TimePeriodDuration.HOURS: 30 * 86_400_000,
    TimePeriodDuration.DAYS: 365 * 86_400_000,
    TimePeriodDuration.MONTHS: None,
    TimePeriodDuration.YEARS: None,
}

def parse_retention(text: str) -> Optional[int]:
    """'120 sec' / '24 hours' / '1 year' → ms; 'all' → None (keep forever).
    Units shared with the SiddhiQL time-literal table."""
    from ..compiler.tokenizer import TIME_UNITS
    text = text.strip().lower()
    if text == "all":
        return None
    parts = text.split()
    try:
        if len(parts) == 2:
            return int(float(parts[0]) * TIME_UNITS[parts[1]])
        return int(text)   # bare ms
    except (ValueError, KeyError):
        raise SiddhiAppRuntimeError(
            f"cannot parse retention/interval {text!r}") from None


def bucket_start(ts: int, duration: TimePeriodDuration) -> int:
    if duration in _MS:
        return ts - ts % _MS[duration]
    dt = _dt.datetime.fromtimestamp(ts / 1000.0, tz=_dt.timezone.utc)
    if duration == TimePeriodDuration.MONTHS:
        dt = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    else:  # YEARS
        dt = dt.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
    return int(dt.timestamp() * 1000)


def _to_jsonable(v):
    """Tagged-JSON encode for aggregator state (Counter/set/tuple carry
    type tags; everything else must already be a JSON scalar/list/dict)."""
    from collections import Counter
    if isinstance(v, Counter):
        return {"__counter__": [[_to_jsonable(k), n] for k, n in v.items()]}
    if isinstance(v, (set, frozenset)):
        return {"__set__": [_to_jsonable(x) for x in sorted(v, key=repr)]}
    if isinstance(v, tuple):
        return {"__tuple__": [_to_jsonable(x) for x in v]}
    if isinstance(v, list):
        return [_to_jsonable(x) for x in v]
    if isinstance(v, dict):
        if all(isinstance(k, str) for k in v):
            return {k: _to_jsonable(x) for k, x in v.items()}
        return {"__map__": [[_to_jsonable(k), _to_jsonable(x)]
                            for k, x in v.items()]}
    return v


def _from_jsonable(v):
    from collections import Counter
    if isinstance(v, dict):
        if "__counter__" in v and len(v) == 1:
            c = Counter()
            for k, n in v["__counter__"]:
                c[_hashable(_from_jsonable(k))] = n
            return c
        if "__set__" in v and len(v) == 1:
            return {_hashable(_from_jsonable(x)) for x in v["__set__"]}
        if "__tuple__" in v and len(v) == 1:
            return tuple(_from_jsonable(x) for x in v["__tuple__"])
        if "__map__" in v and len(v) == 1:
            return {_hashable(_from_jsonable(k)): _from_jsonable(x)
                    for k, x in v["__map__"]}
        return {k: _from_jsonable(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_from_jsonable(x) for x in v]
    return v


def _hashable(v):
    return tuple(v) if isinstance(v, list) else v


class AggregationRuntime:
    def __init__(self, definition: AggregationDefinition, app_context,
                 stream_defs: dict):
        self.definition = definition
        self.app_context = app_context
        stream = definition.basic_single_input_stream
        sid = stream.stream_id
        if sid not in stream_defs:
            raise KeyError(f"aggregation '{definition.id}': undefined stream '{sid}'")
        self.input_def = stream_defs[sid]
        builder = ExecutorBuilder(StreamResolver(self.input_def), app_context)

        # timestamp executor
        if definition.aggregate_attribute is not None:
            self.ts_fn, _ = builder.build(
                Variable(attribute=definition.aggregate_attribute))
        else:
            self.ts_fn = None

        # selector decomposition
        self.group_fns = [builder.build(v)[0] for v in definition.selector.group_by]
        self.attr_specs = []     # (name, kind, fn, agg_name, dtype)
        for oa in definition.selector.attributes:
            e = oa.expr
            if isinstance(e, AttributeFunction) and e.namespace is None \
                    and e.name in AGGREGATOR_NAMES:
                arg_fn, arg_t = builder.build(e.args[0]) if e.args else ((lambda f: None), None)
                self.attr_specs.append(
                    (oa.name, "agg", arg_fn, e.name,
                     aggregator_return_type(e.name, arg_t), arg_t))
            else:
                fn, t = builder.build(e)
                self.attr_specs.append((oa.name, "value", fn, None, t, t))

        # duration -> {bucket_start -> {group_key -> state}}
        # state = {"aggs": {name: Aggregator}, "values": {name: last value}}
        self.stores: dict[TimePeriodDuration, dict[int, dict[Any, dict]]] = {
            d: {} for d in definition.durations
        }
        app_context.register_state(f"aggregation-{definition.id}", self)

        # @store(type='X'): persisted incremental aggregation (reference
        # ``aggregation/persistedaggregation/`` + CudStreamProcessorQueueManager
        # .java:29 — completed buckets are written behind to one store table
        # per duration; reads merge store rows with live in-memory buckets,
        # so rollups survive restart). Store rows are an append-log of
        # [bucket_ts, key_repr, pickled-state]; readers take the newest
        # version of each (bucket, key) — out-of-order reopenings simply
        # append a fresher version.
        from ..query_api.annotation import find_annotation as _find_ann
        store_ann = _find_ann(definition.annotations, "store")
        self.persist_stores: dict[TimePeriodDuration, Any] = {}
        self._dirty: dict[TimePeriodDuration, set[int]] = {
            d: set() for d in definition.durations}
        self._max_bucket: dict[TimePeriodDuration, Optional[int]] = {
            d: None for d in definition.durations}
        if store_ann is not None:
            stype = store_ann.get("type")
            cls = app_context.siddhi_context.extensions.get(f"store:{stype}")
            if cls is None:
                raise SiddhiAppRuntimeError(
                    f"aggregation '{definition.id}': no store extension "
                    f"'{stype}'")
            from ..query_api.definition import DataType, TableDefinition
            opts = {e.key: e.value for e in store_ann.elements if e.key}
            for d in definition.durations:
                td = TableDefinition(f"{definition.id}_{d.value.upper()}")
                td.attribute("AGG_TIMESTAMP", DataType.LONG)
                td.attribute("KEY", DataType.STRING)
                td.attribute("STATE", DataType.STRING)
                t = cls(td, app_context)
                # same contract as the @store table path: a ConfigReader is
                # handed to every store extension before init
                t.config_reader = app_context.config_reader("store", stype)
                t.init(td, opts)
                self.persist_stores[d] = t

        # subscribe via a junction receiver
        junction = app_context.stream_junctions.get(sid)
        if junction is not None:
            junction.subscribe(self)

        # honor filters on the input stream
        from ..query_api import Filter as _F
        self.filter_fn = None
        for h in stream.handlers:
            if isinstance(h, _F):
                self.filter_fn, _ = builder.build(h.expr)

        # @purge(enable='true', interval='15 min',
        #        @retentionPeriod(sec='120 sec', min='24 hours', ...))
        # (reference: aggregation/IncrementalDataPurger.java)
        from ..query_api.annotation import find_annotation
        purge_ann = find_annotation(definition.annotations, "purge")
        self.purge_enabled = purge_ann is not None and \
            (purge_ann.get("enable") or "true").lower() == "true"
        self.purge_interval = parse_retention(
            (purge_ann.get("interval") if purge_ann else None) or "15 min")
        if self.purge_enabled and self.purge_interval is None:
            raise SiddhiAppRuntimeError(
                "@purge interval must be a time value ('all' is only valid "
                "inside @retentionPeriod)")
        self.retention: dict[TimePeriodDuration, Optional[int]] = \
            dict(_PURGE_DEFAULT_RETENTION)
        rp = purge_ann.nested("retentionPeriod") if purge_ann else None
        if rp is not None:
            keymap = {
                "sec": TimePeriodDuration.SECONDS,
                "min": TimePeriodDuration.MINUTES,
                "hours": TimePeriodDuration.HOURS,
                "days": TimePeriodDuration.DAYS,
                "months": TimePeriodDuration.MONTHS,
                "years": TimePeriodDuration.YEARS,
            }
            for e in rp.elements:
                if e.key is None:
                    continue
                d = keymap.get(e.key.lower())
                if d is None:
                    raise SiddhiAppRuntimeError(
                        f"unknown retentionPeriod key '{e.key}'")
                self.retention[d] = parse_retention(e.value)
        if self.purge_enabled:
            self._arm_purge()

        # -- @device: compile the sec…year rollup to batched segmented
        # reductions (tpu/aggregation_compile.py; reference cascade:
        # aggregation/IncrementalExecutor.java:113-164). Events stage into a
        # columnar micro-batch; the device reduces per-(bucket, key) partials
        # which merge here at bucket granularity. Host fallback on any
        # unsupported shape unless @device(strict='true').
        dev_ann = _find_ann(definition.annotations, "device")
        self._dev = None
        self._dev_builder = None
        if dev_ann is not None:
            from ..tpu.aggregation_compile import CompiledAggregation
            from ..tpu.batch import BatchBuilder
            from ..tpu.expr_compile import DeviceCompileError
            try:
                cap = int(dev_ann.get("batch") or 1024)
                self._dev = CompiledAggregation(definition, self.input_def,
                                                cap)
                self._dev_builder = BatchBuilder(self._dev.schema, cap)
                self._dev_ts_pos = (
                    self.input_def.attribute_position(
                        definition.aggregate_attribute)
                    if definition.aggregate_attribute is not None else None)
            except DeviceCompileError as e:
                if (dev_ann.get("strict") or "").lower() == "true":
                    raise
                import logging
                logging.getLogger("siddhi_tpu.device").info(
                    "aggregation '%s' stays on the host path: %s",
                    definition.id, e)

    # -- junction receiver ----------------------------------------------------
    def receive(self, event: StreamEvent) -> None:
        if event.type != EventType.CURRENT:
            return
        if self._dev is not None:
            # device mode: stage the raw row; the kernel applies the filter
            # and the bucketing clock column is read positionally
            ts = int(event.data[self._dev_ts_pos]) \
                if self._dev_ts_pos is not None else event.timestamp
            self._dev_builder.append(event.data, ts)
            if self._dev_builder.full:
                self._flush_device()
            return
        frame = StreamFrame(event)
        if self.filter_fn is not None and not bool(self.filter_fn(frame)):
            return
        ts = int(self.ts_fn(frame)) if self.ts_fn is not None else event.timestamp
        key = tuple(fn(frame) for fn in self.group_fns) if self.group_fns else None
        for duration, buckets in self.stores.items():
            bs = bucket_start(ts, duration)
            if self.persist_stores:
                prev_max = self._max_bucket[duration]
                if prev_max is None or bs > prev_max:
                    self._max_bucket[duration] = bs
                    # write-behind: buckets older than the new one completed
                    self._flush_duration(duration, up_to_exclusive=bs)
                self._dirty[duration].add(bs)
            bucket = buckets.setdefault(bs, {})
            state = bucket.get(key)
            if state is None and self.persist_stores:
                # read-through: a bucket reopening after restart/purge must
                # resume from its persisted state — a fresh zero state would
                # clobber the history on the next flush (last-wins append-log)
                state = self._load_persisted_state(duration, bs, key)
                if state is not None:
                    bucket[key] = state
            if state is None:
                state = {
                    "aggs": {
                        name: make_aggregator(agg_name, arg_t)
                        for name, kind, fn, agg_name, rt, arg_t in self.attr_specs
                        if kind == "agg"
                    },
                    "values": {},
                }
                bucket[key] = state
            for name, kind, fn, agg_name, rt, arg_t in self.attr_specs:
                if kind == "agg":
                    state["aggs"][name].add(fn(frame))
                else:
                    state["values"][name] = fn(frame)

    # -- device flush ---------------------------------------------------------
    def _flush_device(self) -> None:
        """Runs the staged micro-batch through the device reducer and merges
        the per-(bucket, key) partials into the bucket stores (including the
        persisted-store write-behind bookkeeping receive() does per event)."""
        if self._dev_builder is None or len(self._dev_builder) == 0:
            return
        from ..tpu.aggregation_compile import merge_partial_into_state
        batch = self._dev_builder.emit()
        slab = self._dev.bucket_slab(batch["ts"])
        fetched = self._dev.step(batch["cols"], batch["ts"], slab,
                                 batch["valid"])
        durations = self.definition.durations
        for di, bs, key, row in self._dev.iter_partials(fetched):
            duration = durations[di]
            buckets = self.stores[duration]
            if self.persist_stores:
                prev_max = self._max_bucket[duration]
                if prev_max is None or bs > prev_max:
                    self._max_bucket[duration] = bs
                    self._flush_duration(duration, up_to_exclusive=bs)
                self._dirty[duration].add(bs)
            bucket = buckets.setdefault(bs, {})
            state = bucket.get(key)
            if state is None and self.persist_stores:
                state = self._load_persisted_state(duration, bs, key)
                if state is not None:
                    bucket[key] = state
            if state is None:
                state = {
                    "aggs": {
                        name: make_aggregator(agg_name, arg_t)
                        for name, kind, fn, agg_name, rt, arg_t
                        in self.attr_specs if kind == "agg"
                    },
                    "values": {},
                }
                bucket[key] = state
            merge_partial_into_state(state, self._dev.specs, row)

    # -- purging --------------------------------------------------------------
    def _arm_purge(self) -> None:
        self.app_context.scheduler.notify_at(
            self.app_context.current_time() + self.purge_interval,
            self._on_purge)

    def _on_purge(self, fire_ts: int) -> None:
        self.purge(fire_ts)
        self.app_context.scheduler.notify_at(
            fire_ts + self.purge_interval, self._on_purge)

    def purge(self, now: Optional[int] = None) -> int:
        """Drop buckets older than the per-duration retention; returns the
        number of buckets removed. The bucket covering `now` is never purged."""
        if now is None:
            now = self.app_context.current_time()
        self._flush_device()          # staged events may reopen old buckets
        removed = 0
        for duration, buckets in self.stores.items():
            ret = self.retention.get(duration)
            if ret is None:
                continue
            if self.persist_stores:
                # a dirty bucket deleted here would be lost from BOTH memory
                # and the store — flush write-behinds before purging
                self._flush_duration(duration)
            cutoff = now - ret
            keep = bucket_start(now, duration)
            for bs in [b for b in buckets if b < cutoff and b != keep]:
                del buckets[bs]
                removed += 1
            store = self.persist_stores.get(duration)
            if store is not None:
                # delete persisted rows past retention when the store can;
                # reads are bounded by the retention cutoff either way
                # (_persisted_rows), so retention semantics match the
                # non-persisted path (advisor r3)
                store.record_purge("AGG_TIMESTAMP", min(cutoff, keep))
        return removed

    # -- persisted store I/O ---------------------------------------------------
    @staticmethod
    def _encode_state(key, state: dict) -> str:
        """Typed JSON, NOT pickle: an external store holds data, not code —
        restore must never execute store contents, and the rows stay
        readable by external tools (advisor r3)."""
        import json
        payload = {
            "key": _to_jsonable(key),
            "aggs": {n: _to_jsonable(a.snapshot())
                     for n, a in state["aggs"].items()},
            "values": {k: _to_jsonable(v)
                       for k, v in state["values"].items()},
        }
        return json.dumps(payload, separators=(",", ":"))

    def _decode_state(self, blob: str) -> tuple:
        import json
        payload = json.loads(blob)
        state = {
            "aggs": {
                name: make_aggregator(agg_name, arg_t)
                for name, kind, fn, agg_name, rt, arg_t in self.attr_specs
                if kind == "agg"
            },
            "values": {k: _from_jsonable(v)
                       for k, v in payload["values"].items()},
        }
        for n, a in state["aggs"].items():
            a.restore(_from_jsonable(payload["aggs"][n]))
        return _from_jsonable(payload["key"]), state

    def _flush_duration(self, duration, up_to_exclusive=None) -> None:
        store = self.persist_stores.get(duration)
        if store is None:
            return
        dirty = self._dirty[duration]
        buckets = self.stores[duration]
        rows = []
        for bs in sorted(dirty):
            if up_to_exclusive is not None and bs >= up_to_exclusive:
                continue
            for key, state in buckets.get(bs, {}).items():
                rows.append([bs, repr(key), self._encode_state(key, state)])
            dirty.discard(bs)
        if rows:
            # upsert when the store supports it; else append (readers apply
            # last-wins, and the log keeps superseded versions — advisor r3)
            if not store.record_replace(["AGG_TIMESTAMP", "KEY"], rows):
                store.record_add(rows)

    def flush_persisted(self) -> None:
        """Flush every dirty bucket — shutdown/persist barrier (the reference
        drains its CUD queue)."""
        self._flush_device()
        for duration in self.persist_stores:
            self._flush_duration(duration)

    def _load_persisted_state(self, duration, bs: int, key):
        """Newest persisted state for one (bucket, key), or None."""
        store = self.persist_stores.get(duration)
        if store is None:
            return None
        key_repr = repr(key)
        blob = None
        for row_bs, row_key, row_blob in store.record_find({}):
            if int(row_bs) == bs and row_key == key_repr:
                blob = row_blob                 # append order: last wins
        if blob is None:
            return None
        _, state = self._decode_state(blob)
        return state

    def _persisted_rows(self, duration, start=None, end=None) -> dict:
        """{(bucket_ts, key_repr): (key, state)} — newest version wins.
        Bounds filter and last-wins dedup happen BEFORE unpickling, so a
        bounded query doesn't pay for the whole append-log history."""
        store = self.persist_stores.get(duration)
        if store is None:
            return {}
        if self.purge_enabled:
            # retention bounds the merge even when the store can't delete:
            # out-of-retention rows must not resurface through the store
            # (advisor r3 — parity with the non-persisted path)
            ret = self.retention.get(duration)
            if ret is not None:
                cut = self.app_context.current_time() - ret
                start = cut if start is None else max(start, cut)
        latest: dict = {}
        for bs, key_repr, blob in store.record_find({}):
            bs = int(bs)
            if start is not None and bs < start:
                continue
            if end is not None and bs >= end:
                continue
            latest[(bs, key_repr)] = blob       # append order: last wins
        out: dict = {}
        for k, blob in latest.items():
            key, state = self._decode_state(blob)
            out[k] = (key, state)
        return out

    # -- retrieval ------------------------------------------------------------
    @property
    def output_names(self) -> list[str]:
        return ["AGG_TIMESTAMP"] + [s[0] for s in self.attr_specs]

    @property
    def output_definition(self):
        from ..query_api.definition import DataType, StreamDefinition
        d = StreamDefinition(self.definition.id)
        d.attribute("AGG_TIMESTAMP", DataType.LONG)
        for name, kind, fn, agg_name, rt, arg_t in self.attr_specs:
            d.attribute(name, rt if rt is not None else DataType.OBJECT)
        return d

    def duration_for(self, per_value: str):
        per = str(per_value).lower().rstrip("s")
        dur_map = {
            "second": TimePeriodDuration.SECONDS, "sec": TimePeriodDuration.SECONDS,
            "minute": TimePeriodDuration.MINUTES, "min": TimePeriodDuration.MINUTES,
            "hour": TimePeriodDuration.HOURS, "day": TimePeriodDuration.DAYS,
            "month": TimePeriodDuration.MONTHS, "year": TimePeriodDuration.YEARS,
        }
        from .errors import SiddhiAppRuntimeError
        if per not in dur_map:
            raise SiddhiAppRuntimeError(
                f"unknown aggregation granularity '{per_value}'")
        d = dur_map[per]
        if d not in self.stores:
            raise SiddhiAppRuntimeError(
                f"aggregation '{self.definition.id}' lacks duration '{d.value}' "
                f"(defined: {[x.value for x in self.stores]})")
        return d

    def rows_for(self, duration: TimePeriodDuration,
                 start: Optional[int] = None, end: Optional[int] = None) -> list[list]:
        self._flush_device()          # reads see every staged event
        buckets = self.stores.get(duration)
        if buckets is None:
            from .errors import SiddhiAppRuntimeError
            raise SiddhiAppRuntimeError(
                f"aggregation '{self.definition.id}' has no duration {duration}")
        # persisted mode: older rollups live in the store; live in-memory
        # buckets overlay them (they're strictly newer)
        merged: dict[int, dict[Any, dict]] = {}
        if self.persist_stores:
            for (bs, _krepr), (key, state) in \
                    self._persisted_rows(duration, start, end).items():
                merged.setdefault(bs, {})[key] = state
        for bs, bucket in buckets.items():
            for key, state in bucket.items():
                merged.setdefault(bs, {})[key] = state
        rows = []
        for bs in sorted(merged):
            if start is not None and bs < start:
                continue
            if end is not None and bs >= end:
                continue
            for key, state in merged[bs].items():
                row = [bs]
                for name, kind, fn, agg_name, rt, arg_t in self.attr_specs:
                    if kind == "agg":
                        row.append(state["aggs"][name].value())
                    else:
                        row.append(state["values"].get(name))
                rows.append(row)
        return rows

    def on_demand_find(self, odq: OnDemandQuery, now: int) -> list[Event]:
        # `within t1 [, t2] per 'duration'`
        duration = self.definition.durations[0]
        if odq.per is not None:
            duration = self.duration_for(odq.per.value)
        start = end = None
        if odq.within:
            vals = [v.value for v in odq.within]
            if len(vals) > 1:
                start, end = parse_within_value(vals[0]), parse_within_value(vals[1])
            else:
                start, end = parse_within_single(vals[0])
        rows = self.rows_for(duration, start, end)

        names = self.output_names
        from .executor import RowFrame, RowResolver
        from ..query_api.definition import DataType
        types = [DataType.LONG] + [s[4] for s in self.attr_specs]
        builder = ExecutorBuilder(RowResolver(names, types), self.app_context)
        if odq.on_condition is not None:
            cond, _ = builder.build(odq.on_condition)
            rows = [r for r in rows if bool(cond(RowFrame(r, now)))]
        attrs = list(odq.selector.attributes)
        if odq.selector.select_all or not attrs:
            return [Event(now, list(r)) for r in rows]
        out = []
        for r in rows:
            frame = RowFrame(r, now)
            out.append(Event(now, [builder.build(a.expr)[0](frame) for a in attrs]))
        return out

    # -- state ----------------------------------------------------------------
    def snapshot_state(self) -> dict:
        self._flush_device()          # checkpoint covers staged events
        enc = {}
        for duration, buckets in self.stores.items():
            enc[duration.value] = {
                bs: {
                    repr(key): {
                        "aggs": {n: a.snapshot() for n, a in st["aggs"].items()},
                        "values": dict(st["values"]),
                        "_key": key,
                    }
                    for key, st in bucket.items()
                }
                for bs, bucket in buckets.items()
            }
        return enc

    def restore_state(self, state: dict) -> None:
        if self._dev_builder is not None and len(self._dev_builder):
            self._dev_builder.emit()          # restore replaces staged rows
        for duration in self.stores:
            self.stores[duration] = {}
            for bs, bucket in state.get(duration.value, {}).items():
                dst = self.stores[duration].setdefault(int(bs), {})
                for _, st in bucket.items():
                    key = st["_key"]
                    new_state = {
                        "aggs": {
                            name: make_aggregator(agg_name, arg_t)
                            for name, kind, fn, agg_name, rt, arg_t in self.attr_specs
                            if kind == "agg"
                        },
                        "values": dict(st["values"]),
                    }
                    for n, a in new_state["aggs"].items():
                        a.restore(st["aggs"][n])
                    dst[key] = new_state
