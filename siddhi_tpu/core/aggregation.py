"""Incremental aggregations: multi-duration rollup cascade.

Reference: ``core/aggregation/`` — ``AggregationRuntime.java``,
``IncrementalExecutor.java`` (bucket rollover), per-duration stores, on-demand
``within ... per ...`` retrieval. Redesigned: buckets are keyed dicts of running
aggregator states per duration; rollups happen by bucketing the event timestamp
directly into every requested duration (equivalent results, no cascade chain —
the cascade is an optimization the TPU path reintroduces as segmented scans).
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Optional

from ..query_api import (
    AttributeFunction,
    OnDemandQuery,
    OutputAttribute,
    Variable,
)
from ..query_api.definition import AggregationDefinition, TimePeriodDuration
from .aggregators import AGGREGATOR_NAMES, aggregator_return_type, make_aggregator
from .event import Event, EventType, StreamEvent
from .executor import ExecutorBuilder, StreamFrame, StreamResolver

_MS = {
    TimePeriodDuration.SECONDS: 1000,
    TimePeriodDuration.MINUTES: 60_000,
    TimePeriodDuration.HOURS: 3_600_000,
    TimePeriodDuration.DAYS: 86_400_000,
}


def bucket_start(ts: int, duration: TimePeriodDuration) -> int:
    if duration in _MS:
        return ts - ts % _MS[duration]
    dt = _dt.datetime.fromtimestamp(ts / 1000.0, tz=_dt.timezone.utc)
    if duration == TimePeriodDuration.MONTHS:
        dt = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    else:  # YEARS
        dt = dt.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
    return int(dt.timestamp() * 1000)


class AggregationRuntime:
    def __init__(self, definition: AggregationDefinition, app_context,
                 stream_defs: dict):
        self.definition = definition
        self.app_context = app_context
        stream = definition.basic_single_input_stream
        sid = stream.stream_id
        if sid not in stream_defs:
            raise KeyError(f"aggregation '{definition.id}': undefined stream '{sid}'")
        self.input_def = stream_defs[sid]
        builder = ExecutorBuilder(StreamResolver(self.input_def), app_context)

        # timestamp executor
        if definition.aggregate_attribute is not None:
            self.ts_fn, _ = builder.build(
                Variable(attribute=definition.aggregate_attribute))
        else:
            self.ts_fn = None

        # selector decomposition
        self.group_fns = [builder.build(v)[0] for v in definition.selector.group_by]
        self.attr_specs = []     # (name, kind, fn, agg_name, dtype)
        for oa in definition.selector.attributes:
            e = oa.expr
            if isinstance(e, AttributeFunction) and e.namespace is None \
                    and e.name in AGGREGATOR_NAMES:
                arg_fn, arg_t = builder.build(e.args[0]) if e.args else ((lambda f: None), None)
                self.attr_specs.append(
                    (oa.name, "agg", arg_fn, e.name,
                     aggregator_return_type(e.name, arg_t), arg_t))
            else:
                fn, t = builder.build(e)
                self.attr_specs.append((oa.name, "value", fn, None, t, t))

        # duration -> {bucket_start -> {group_key -> state}}
        # state = {"aggs": {name: Aggregator}, "values": {name: last value}}
        self.stores: dict[TimePeriodDuration, dict[int, dict[Any, dict]]] = {
            d: {} for d in definition.durations
        }
        app_context.register_state(f"aggregation-{definition.id}", self)

        # subscribe via a junction receiver
        junction = app_context.stream_junctions.get(sid)
        if junction is not None:
            junction.subscribe(self)

        # honor filters on the input stream
        from ..query_api import Filter as _F
        self.filter_fn = None
        for h in stream.handlers:
            if isinstance(h, _F):
                self.filter_fn, _ = builder.build(h.expr)

    # -- junction receiver ----------------------------------------------------
    def receive(self, event: StreamEvent) -> None:
        if event.type != EventType.CURRENT:
            return
        frame = StreamFrame(event)
        if self.filter_fn is not None and not bool(self.filter_fn(frame)):
            return
        ts = int(self.ts_fn(frame)) if self.ts_fn is not None else event.timestamp
        key = tuple(fn(frame) for fn in self.group_fns) if self.group_fns else None
        for duration, buckets in self.stores.items():
            bs = bucket_start(ts, duration)
            bucket = buckets.setdefault(bs, {})
            state = bucket.get(key)
            if state is None:
                state = {
                    "aggs": {
                        name: make_aggregator(agg_name, arg_t)
                        for name, kind, fn, agg_name, rt, arg_t in self.attr_specs
                        if kind == "agg"
                    },
                    "values": {},
                }
                bucket[key] = state
            for name, kind, fn, agg_name, rt, arg_t in self.attr_specs:
                if kind == "agg":
                    state["aggs"][name].add(fn(frame))
                else:
                    state["values"][name] = fn(frame)

    # -- retrieval ------------------------------------------------------------
    @property
    def output_names(self) -> list[str]:
        return ["AGG_TIMESTAMP"] + [s[0] for s in self.attr_specs]

    @property
    def output_definition(self):
        from ..query_api.definition import DataType, StreamDefinition
        d = StreamDefinition(self.definition.id)
        d.attribute("AGG_TIMESTAMP", DataType.LONG)
        for name, kind, fn, agg_name, rt, arg_t in self.attr_specs:
            d.attribute(name, rt if rt is not None else DataType.OBJECT)
        return d

    def duration_for(self, per_value: str):
        per = str(per_value).lower().rstrip("s")
        dur_map = {
            "second": TimePeriodDuration.SECONDS, "sec": TimePeriodDuration.SECONDS,
            "minute": TimePeriodDuration.MINUTES, "min": TimePeriodDuration.MINUTES,
            "hour": TimePeriodDuration.HOURS, "day": TimePeriodDuration.DAYS,
            "month": TimePeriodDuration.MONTHS, "year": TimePeriodDuration.YEARS,
        }
        if per not in dur_map:
            raise KeyError(f"unknown aggregation granularity '{per_value}'")
        d = dur_map[per]
        if d not in self.stores:
            raise KeyError(
                f"aggregation '{self.definition.id}' lacks duration {d.value}")
        return d

    def rows_for(self, duration: TimePeriodDuration,
                 start: Optional[int] = None, end: Optional[int] = None) -> list[list]:
        buckets = self.stores.get(duration)
        if buckets is None:
            raise KeyError(
                f"aggregation '{self.definition.id}' has no duration {duration}")
        rows = []
        for bs in sorted(buckets):
            if start is not None and bs < start:
                continue
            if end is not None and bs >= end:
                continue
            for key, state in buckets[bs].items():
                row = [bs]
                for name, kind, fn, agg_name, rt, arg_t in self.attr_specs:
                    if kind == "agg":
                        row.append(state["aggs"][name].value())
                    else:
                        row.append(state["values"].get(name))
                rows.append(row)
        return rows

    def on_demand_find(self, odq: OnDemandQuery, now: int) -> list[Event]:
        # `within t1 [, t2] per 'duration'`
        duration = self.definition.durations[0]
        if odq.per is not None:
            duration = self.duration_for(odq.per.value)
        start = end = None
        if odq.within:
            vals = [v.value for v in odq.within]
            start = vals[0]
            end = vals[1] if len(vals) > 1 else None
        rows = self.rows_for(duration, start, end)

        names = self.output_names
        from .executor import RowFrame, RowResolver
        from ..query_api.definition import DataType
        types = [DataType.LONG] + [s[4] for s in self.attr_specs]
        builder = ExecutorBuilder(RowResolver(names, types), self.app_context)
        if odq.on_condition is not None:
            cond, _ = builder.build(odq.on_condition)
            rows = [r for r in rows if bool(cond(RowFrame(r, now)))]
        attrs = list(odq.selector.attributes)
        if odq.selector.select_all or not attrs:
            return [Event(now, list(r)) for r in rows]
        out = []
        for r in rows:
            frame = RowFrame(r, now)
            out.append(Event(now, [builder.build(a.expr)[0](frame) for a in attrs]))
        return out

    # -- state ----------------------------------------------------------------
    def snapshot_state(self) -> dict:
        enc = {}
        for duration, buckets in self.stores.items():
            enc[duration.value] = {
                bs: {
                    repr(key): {
                        "aggs": {n: a.snapshot() for n, a in st["aggs"].items()},
                        "values": dict(st["values"]),
                        "_key": key,
                    }
                    for key, st in bucket.items()
                }
                for bs, bucket in buckets.items()
            }
        return enc

    def restore_state(self, state: dict) -> None:
        for duration in self.stores:
            self.stores[duration] = {}
            for bs, bucket in state.get(duration.value, {}).items():
                dst = self.stores[duration].setdefault(int(bs), {})
                for _, st in bucket.items():
                    key = st["_key"]
                    new_state = {
                        "aggs": {
                            name: make_aggregator(agg_name, arg_t)
                            for name, kind, fn, agg_name, rt, arg_t in self.attr_specs
                            if kind == "agg"
                        },
                        "values": dict(st["values"]),
                    }
                    for n, a in new_state["aggs"].items():
                        a.restore(st["aggs"][n])
                    dst[key] = new_state
