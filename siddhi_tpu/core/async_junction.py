"""Async event dispatch: the Disruptor-mode analog for stream junctions.

Reference: ``StreamJunction.startProcessing`` (``stream/StreamJunction.java:279-316``)
spins up an LMAX Disruptor ring buffer when a stream is annotated
``@async(buffer.size='..', workers='..', batch.size.max='..')``; producers
publish into the ring and worker threads drain it into the receiver chain.

TPU-native redesign: the engine is batch-synchronous — processors are not
locked individually; instead ONE app-level lock (``SiddhiAppContext.root_lock``)
guards all host engine state, and the async dispatcher decouples *producers*
from *delivery*:

- ``send()`` enqueues into a bounded buffer and returns (multi-threaded
  producers are safe — enqueue is under a queue mutex, not the engine lock);
- worker threads drain events in ``batch.size.max`` chunks and deliver them
  under ``root_lock`` (single-writer engine semantics preserved);
- backpressure: a full buffer blocks the producer briefly; if the buffer stays
  full (e.g. the producer itself holds ``root_lock``, so draining can't
  progress) the put *grows the queue* instead of deadlocking and counts the
  overflow — the gauge surfaces sizing problems, the engine never wedges;
- ``quiesce()`` waits for empty-queue + idle-workers: the ``ThreadBarrier``
  analog used by snapshot/persist and shutdown.

Delivery holds the engine lock, so with ``workers > 1`` host-side processing
is still serialized (the win is producer decoupling); device-offloaded queries
additionally overlap packing with device compute via ``AsyncDeviceDriver``
(``device_bridge.py``), where the expensive step runs *outside* the lock.
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Optional

from ..flow.backpressure import rlock_owned

log = logging.getLogger("siddhi_tpu.async")

# how long a producer waits on a full buffer before growing it instead
# (deadlock-proof backpressure: the producer may hold root_lock, which the
# drain path needs)
_FULL_WAIT_S = 0.2


class AsyncDispatcher:
    """Bounded multi-producer buffer + worker threads for one junction."""

    def __init__(self, junction, app_context, buffer_size: int = 1024,
                 workers: int = 1, batch_size_max: int = 64):
        self.junction = junction
        self.app_context = app_context
        self.buffer_size = max(1, buffer_size)
        self.workers = max(1, workers)
        self.batch_size_max = max(1, batch_size_max)

        self._q: collections.deque = collections.deque()
        self._n_events = 0                  # EVENTS queued (items may be chunks)
        self._cv = threading.Condition()
        self._busy = 0                      # workers currently delivering
        self._stopped = False
        self._started = False
        self._threads: list[threading.Thread] = []

        # observability (BufferedEventsTracker analog,
        # ``StreamJunction.getBufferedEvents:359``)
        self.total_enqueued = 0
        self.high_water = 0
        self.soft_overflows = 0             # puts that grew past buffer_size

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        with self._cv:          # idempotent under concurrent first sends
            if self._started:
                return
            self._started = True
        for i in range(self.workers):
            t = threading.Thread(
                target=self._run, name=f"async-{self.junction.definition.id}-{i}",
                daemon=True)
            self._threads.append(t)
            t.start()

    def stop(self) -> None:
        """Drain, then stop workers (reference shuts the disruptor down after
        a final drain)."""
        if not self._started:
            return
        self.quiesce()
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads.clear()
        self._started = False
        self._stopped = False

    # -- producer side -------------------------------------------------------
    @property
    def buffered_events(self) -> int:
        return len(self._q)

    @property
    def buffered_event_count(self) -> int:
        """Queued EVENTS (a ('chunk', [...]) item holds many) — the credit
        gate's depth unit (``flow/backpressure.py`` counts credits in events,
        so item-count depth would overrun the bound by the chunk size)."""
        return self._n_events

    @staticmethod
    def _item_size(item) -> int:
        return len(item[1]) if item[0] == "chunk" else 1

    def enqueue(self, item) -> None:
        """item: ('event', StreamEvent) | ('chunk', list[StreamEvent]).

        Backpressure: a producer that does NOT hold the app root lock blocks
        until space frees — ``@async(buffer.size)`` is a HARD bound for
        external producers (advisor r3). A producer holding root_lock (a
        query inserting into an async stream mid-delivery) must not block —
        the drain path needs that lock — so it grows the buffer and counts
        the overrun in ``soft_overflows`` instead (the reference's blocking
        ring buffer simply deadlocks in this shape)."""
        if not self._started:
            self.start()
        root = getattr(self.app_context, "root_lock", None)
        may_block = root is None or not rlock_owned(root)
        with self._cv:
            while len(self._q) >= self.buffer_size:
                if may_block and not self._stopped:
                    self._cv.wait(timeout=_FULL_WAIT_S)
                    continue
                self.soft_overflows += 1
                break
            self._q.append(item)
            self._n_events += self._item_size(item)
            self.total_enqueued += 1
            if len(self._q) > self.high_water:
                self.high_water = len(self._q)
            self._cv.notify()

    def drop_oldest(self):
        """Evict and return the oldest queued item (``('event', ev)`` /
        ``('chunk', [evs])``), or None when the queue is empty — the
        DROP_OLDEST overload policy's hook (``flow/backpressure.py``)."""
        with self._cv:
            if not self._q:
                return None
            item = self._q.popleft()
            self._n_events -= self._item_size(item)
            self._cv.notify_all()       # wake producers blocked on full
            return item

    # -- worker side ---------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stopped:
                    self._cv.wait(timeout=0.5)
                if self._stopped and not self._q:
                    return
                batch = []
                while self._q and len(batch) < self.batch_size_max:
                    batch.append(self._q.popleft())
                self._busy += 1
                self._cv.notify_all()       # wake producers blocked on full
            try:
                self._deliver(batch)
            except Exception:  # noqa: BLE001 — junction isolates per-receiver;
                # anything escaping here is a bug, but a worker must survive
                log.exception("async delivery failed on stream '%s'",
                              self.junction.definition.id)
            finally:
                with self._cv:
                    # credits free only when delivery COMPLETES: an in-flight
                    # batch still counts against the gate's bound, or the
                    # gate would over-admit by workers * batch_size_max
                    self._n_events -= sum(self._item_size(i) for i in batch)
                    self._busy -= 1
                    self._cv.notify_all()   # wake quiesce() waiters

    def _deliver(self, batch: list) -> None:
        with self.app_context.root_lock:
            for kind, payload in batch:
                if kind == "chunk":
                    # watermark to the chunk's first timestamp before delivery,
                    # the rest after (InputHandler chunk-send semantics)
                    self.app_context.advance_time(
                        min(ev.timestamp for ev in payload))
                    self.junction.deliver_events(payload)
                    self.app_context.advance_time(
                        max(ev.timestamp for ev in payload))
                else:
                    self.app_context.advance_time(payload.timestamp)
                    self.junction.deliver_event(payload)

    # -- barrier (ThreadBarrier analog) --------------------------------------
    def quiesce(self, timeout: float = 30.0) -> bool:
        """Block until the buffer is empty and all workers are idle. Called by
        snapshot/persist (the reference quiesces ingress with ThreadBarrier
        before walking state) and by shutdown."""
        import time
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._q or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=min(remaining, 0.5))
        return True
