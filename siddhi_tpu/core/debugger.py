"""Debugger: breakpoints at query IN/OUT terminals with step/play control.

Reference: ``core/debugger/SiddhiDebugger.java:36`` (acquireBreakPoint:95,
checkBreakPoint:133, next:182, play:190) + ``SiddhiDebuggerCallback``. The
reference blocks the sender thread on a lock; this engine is batch-synchronous
and single-threaded per send, so the callback runs inline and the returned
command (``NEXT`` — break again at the next terminal, ``PLAY`` — run until the
next explicitly acquired breakpoint) drives stepping deterministically.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

from .event import Event, EventType, StreamEvent


class QueryTerminal(enum.Enum):
    IN = "in"
    OUT = "out"


class SiddhiDebugger:
    NEXT = "next"
    PLAY = "play"

    def __init__(self, app_context):
        self.app_context = app_context
        self._breakpoints: set[tuple[str, QueryTerminal]] = set()
        self._callback: Optional[Callable] = None
        self._step_mode = False

    # -- reference API ---------------------------------------------------------
    def acquire_break_point(self, query_name: str, terminal: QueryTerminal) -> None:
        self._breakpoints.add((query_name, terminal))

    def release_break_point(self, query_name: str, terminal: QueryTerminal) -> None:
        self._breakpoints.discard((query_name, terminal))

    def release_all_break_points(self) -> None:
        self._breakpoints.clear()
        self._step_mode = False

    def set_debugger_callback(self, callback: Callable) -> None:
        """callback(event: Event, query_name: str, terminal: QueryTerminal,
        debugger) -> 'next' | 'play' | None."""
        self._callback = callback

    def next(self) -> None:
        self._step_mode = True

    def play(self) -> None:
        self._step_mode = False

    # -- engine hook -----------------------------------------------------------
    def check_break_point(self, query_name: str, terminal: QueryTerminal,
                          event: StreamEvent) -> None:
        if self._callback is None:
            return
        if self._step_mode or (query_name, terminal) in self._breakpoints:
            cmd = self._callback(
                Event(event.timestamp, list(event.data),
                      event.type == EventType.EXPIRED),
                query_name, terminal, self)
            if cmd == self.NEXT:
                self._step_mode = True
            elif cmd == self.PLAY:
                self._step_mode = False

    def get_query_state(self, query_name: str) -> dict:
        """Inspect the registered state of a query's elements (windows,
        selectors, pattern tables) by element-id prefix."""
        out = {}
        for element_id, holder in self.app_context.state_registry.items():
            # element ids are '{query}-{kind}[-{seq}]' — prefix match, so
            # 'q1' doesn't also pick up 'q10-...'
            if element_id == query_name or \
                    element_id.startswith(query_name + "-") or \
                    element_id == "device-" + query_name:
                try:
                    out[element_id] = holder.snapshot_state()
                except Exception:  # noqa: BLE001 — best-effort inspection
                    pass
        return out


class DebuggedReceiver:
    """Wraps a query's junction receiver with the IN-terminal check."""

    def __init__(self, inner, query_name: str, app_context):
        self.inner = inner
        self.query_name = query_name
        self.app_context = app_context

    def receive(self, event: StreamEvent) -> None:
        dbg = getattr(self.app_context, "debugger", None)
        if dbg is not None and event.type == EventType.CURRENT:
            dbg.check_break_point(self.query_name, QueryTerminal.IN, event)
        self.inner.receive(event)

    def receive_chunk(self, events: list[StreamEvent]) -> None:
        dbg = getattr(self.app_context, "debugger", None)
        if dbg is not None:
            for ev in events:
                if ev.type == EventType.CURRENT:
                    dbg.check_break_point(self.query_name, QueryTerminal.IN, ev)
        if hasattr(self.inner, "receive_chunk"):
            self.inner.receive_chunk(events)
        else:
            for ev in events:
                self.inner.receive(ev)


class DebuggedOutput:
    """Sits before the query's output fanout for the OUT-terminal check."""

    def __init__(self, inner, query_name: str, app_context):
        self.inner = inner
        self.query_name = query_name
        self.app_context = app_context

    def process(self, events: list[StreamEvent]) -> None:
        dbg = getattr(self.app_context, "debugger", None)
        if dbg is not None:
            for ev in events:
                # RESET markers (and window-internal TIMER rows) are engine
                # protocol, not output events — a stepping user sees only
                # CURRENT/EXPIRED, like the reference OUT terminal
                if ev.type in (EventType.CURRENT, EventType.EXPIRED):
                    dbg.check_break_point(self.query_name, QueryTerminal.OUT, ev)
        self.inner.process(events)
