"""Join engine.

Reference: ``core/query/input/stream/join/JoinProcessor.java`` — each side's
arrivals probe the opposite side's window buffer (``FindableProcessor.find``);
outer joins emit unmatched probes with a null side. EXPIRED events probe too,
producing EXPIRED joined events so downstream aggregations retract.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..query_api import EventTrigger, JoinType
from .event import EventType, JoinedEvent, StreamEvent
from .executor import JoinFrame
from .processors import Processor


class JoinSide(Processor):
    """Terminal processor of one side's chain; probes the other side."""

    def __init__(self, runtime: "JoinRuntime", is_left: bool):
        super().__init__()
        self.runtime = runtime
        self.is_left = is_left

    def process(self, events: list[StreamEvent]) -> None:
        self.runtime.on_side_events(self.is_left, events)


class JoinRuntime:
    def __init__(self, join_type: JoinType, trigger: EventTrigger,
                 condition_fn: Optional[Callable],
                 left_find: Callable[..., list[StreamEvent]],
                 right_find: Callable[..., list[StreamEvent]],
                 within_ms: Optional[int] = None):
        self.join_type = join_type
        self.trigger = trigger
        self.condition_fn = condition_fn
        self.left_find = left_find
        self.right_find = right_find
        self.within_ms = within_ms
        self.next = None    # selector

    def on_side_events(self, is_left: bool, events: list[StreamEvent]) -> None:
        out: list[JoinedEvent] = []
        for ev in events:
            if ev.type not in (EventType.CURRENT, EventType.EXPIRED):
                continue
            if is_left and self.trigger == EventTrigger.RIGHT:
                continue
            if (not is_left) and self.trigger == EventTrigger.LEFT:
                continue
            # the probe event is handed to the opposite side so table sides
            # can push an indexed lookup down instead of scanning
            # (reference: JoinProcessor + OperatorParser's IndexOperator)
            opposite = self.right_find(ev) if is_left else self.left_find(ev)
            matched = False
            for other in opposite:
                left_ev = ev if is_left else other
                right_ev = other if is_left else ev
                if self.within_ms is not None and \
                        abs(left_ev.timestamp - right_ev.timestamp) > self.within_ms:
                    continue
                frame = JoinFrame(left_ev, right_ev, ev.timestamp)
                if self.condition_fn is None or bool(self.condition_fn(frame)):
                    matched = True
                    out.append(JoinedEvent(ev.timestamp, left_ev, right_ev, ev.type))
            if not matched and self._emit_unmatched(is_left):
                left_ev = ev if is_left else None
                right_ev = None if is_left else ev
                out.append(JoinedEvent(ev.timestamp, left_ev, right_ev, ev.type))
        if out and self.next is not None:
            self.next.process(out)

    def _emit_unmatched(self, probe_is_left: bool) -> bool:
        if self.join_type == JoinType.FULL_OUTER_JOIN:
            return True
        if self.join_type == JoinType.LEFT_OUTER_JOIN and probe_is_left:
            return True
        if self.join_type == JoinType.RIGHT_OUTER_JOIN and not probe_is_left:
            return True
        return False
