"""Sources, sinks, mappers, and the in-memory broker.

Reference: ``core/stream/input/source/`` (``Source.java`` with connect/retry,
``SourceMapper``), ``core/stream/output/sink/`` (``Sink.java``, ``SinkMapper``,
``LogSink``, ``InMemorySink``), ``core/util/transport/InMemoryBroker.java``.
Transports are host-side by design — on TPU they feed the batching ingress.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import struct
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from ..query_api.annotation import Annotation
from ..query_api.definition import DataType, StreamDefinition
from .columns import (
    CsvColumnParser,
    RowsChunk,
    columns_to_rows,
    unpack_columns,
)
from .event import Event, EventType

log = logging.getLogger("siddhi_tpu.io")


# ---------------------------------------------------------------------------
# In-memory broker (static topic pub/sub, test transport)
# ---------------------------------------------------------------------------

class InMemoryBroker:
    _topics: dict[str, list[Callable[[Any], None]]] = {}
    _lock = threading.RLock()

    @classmethod
    def subscribe(cls, topic: str, receiver: Callable[[Any], None]) -> Callable[[], None]:
        with cls._lock:
            cls._topics.setdefault(topic, []).append(receiver)

        def unsubscribe():
            with cls._lock:
                if receiver in cls._topics.get(topic, []):
                    cls._topics[topic].remove(receiver)

        return unsubscribe

    @classmethod
    def publish(cls, topic: str, payload: Any) -> None:
        with cls._lock:
            receivers = list(cls._topics.get(topic, []))
        for r in receivers:
            r(payload)

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._topics.clear()


# ---------------------------------------------------------------------------
# Mappers
# ---------------------------------------------------------------------------

class SourceMapper:
    """payload → list of event payload lists.

    Rows-capable mappers additionally implement ``map_rows(payload_bytes)
    -> list[RowsChunk]`` (columns, not rows): the edge then delivers whole
    columnar chunks through ``InputHandler.send_columns`` with zero
    per-event Python objects. Legacy mappers (``map_rows`` left None) keep
    the per-event path unchanged."""

    map_rows = None             # rows-capable mappers override with a method

    def init(self, definition: StreamDefinition, options: dict) -> None:
        self.definition = definition
        self.options = options

    def map(self, payload: Any) -> list[list]:
        raise NotImplementedError


class PassThroughSourceMapper(SourceMapper):
    def map(self, payload: Any) -> list[list]:
        if isinstance(payload, Event):
            return [list(payload.data)]
        if isinstance(payload, (list, tuple)):
            if payload and isinstance(payload[0], (list, tuple, Event)):
                return [list(p.data) if isinstance(p, Event) else list(p)
                        for p in payload]
            return [list(payload)]
        raise ValueError(f"passThrough cannot map {type(payload).__name__}")


class JsonSourceMapper(SourceMapper):
    def map(self, payload: Any) -> list[list]:
        obj = json.loads(payload) if isinstance(payload, (str, bytes)) else payload
        events = obj if isinstance(obj, list) else [obj]
        out = []
        for e in events:
            if isinstance(e, dict):
                body = e.get("event", e)
                out.append([body.get(a.name) for a in self.definition.attributes])
            else:
                out.append(list(e))
        return out


class CsvSourceMapper(SourceMapper):
    """CSV line payloads, both paths:

    - ``map_rows`` (bytes of whole lines) parses straight into columns via
      :class:`~siddhi_tpu.core.columns.CsvColumnParser` — native C++ parse
      + dictionary encode + SoA staging when a toolchain exists, pure
      Python otherwise; ZERO per-event objects either way;
    - ``map`` is the per-event reference path (parity oracle for the rows
      path; also what non-line transports get).

    Options: ``ts.last='true'`` reads a trailing int64 event-time field
    per line; ``parse.capacity`` bounds one staged chunk (default 65536).
    """

    def init(self, definition: StreamDefinition, options: dict) -> None:
        super().init(definition, options)
        self.ts_last = (options.get("ts.last") or "").lower() == "true"
        self._parser: Optional[CsvColumnParser] = None

    @property
    def parser(self) -> CsvColumnParser:
        if self._parser is None:
            self._parser = CsvColumnParser(
                self.definition, ts_last=self.ts_last,
                capacity=int(self.options.get("parse.capacity") or 65536))
        return self._parser

    # -- rows path (zero-object) -----------------------------------------
    def map_rows(self, payload: bytes) -> list[RowsChunk]:
        return self.parser.parse(bytes(payload))

    # -- per-event reference path ----------------------------------------
    def map(self, payload: Any) -> list:
        if isinstance(payload, (bytes, bytearray, memoryview)):
            payload = bytes(payload).decode()
        out = []
        attrs = self.definition.attributes
        expected = len(attrs) + (1 if self.ts_last else 0)
        for line in str(payload).splitlines():
            line = line.strip("\r")
            if not line:
                continue
            fields = line.split(",")
            if len(fields) != expected:
                raise ValueError(
                    f"csv line has {len(fields)} fields, expected "
                    f"{expected}: {line!r}")
            row = []
            for f, a in zip(fields, attrs):
                if a.type == DataType.STRING:
                    row.append(f if f else None)
                elif not f:
                    row.append(None)
                elif a.type in (DataType.INT, DataType.LONG):
                    row.append(int(f))
                elif a.type == DataType.BOOL:
                    row.append(f.lower() == "true" or f == "1")
                else:
                    row.append(float(f))
            if self.ts_last:
                out.append(Event(int(fields[-1]), row))
            else:
                out.append(row)
        return out

    # mapper-level edge stats (wired as source.{sid}.* gauges)
    @property
    def rows_out(self) -> int:
        return self.parser.rows_out if self._parser else 0

    @property
    def rows_per_s(self) -> float:
        return self.parser.rows_per_s if self._parser else 0.0

    @property
    def parse_errors(self) -> int:
        return self.parser.parse_errors if self._parser else 0

    @property
    def parse_seconds(self) -> float:
        return self.parser.parse_seconds if self._parser else 0.0


class JsonLinesSourceMapper(SourceMapper):
    """JSON-lines payloads: ``map_rows`` parses each line once and emits
    ONE columnar chunk (the parse itself allocates transient dicts — only
    a native parser avoids that — but downstream of the mapper the chunk
    is zero-object end to end). ``map`` is the per-event path."""

    def init(self, definition: StreamDefinition, options: dict) -> None:
        super().init(definition, options)
        self.rows_out = 0
        self.parse_errors = 0
        self.parse_seconds = 0.0

    @property
    def rows_per_s(self) -> float:
        return self.rows_out / self.parse_seconds if self.parse_seconds \
            else 0.0

    def map_rows(self, payload: bytes) -> list[RowsChunk]:
        t0 = time.perf_counter()
        attrs = self.definition.attributes
        raw: list[list] = [[] for _ in attrs]
        n = 0
        for line in bytes(payload).split(b"\n"):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                self.parse_errors += 1
                continue
            body = obj.get("event", obj) if isinstance(obj, dict) else None
            if not isinstance(body, dict):
                self.parse_errors += 1
                continue
            for c, a in zip(raw, attrs):
                c.append(body.get(a.name))
            n += 1
        self.parse_seconds += time.perf_counter() - t0
        self.rows_out += n
        if n == 0:
            return []
        from .columns import _CHAR_NP, TYPE_CHARS
        cols: dict[str, Any] = {}
        for vals, a in zip(raw, attrs):
            if a.type == DataType.STRING:
                arr = np.empty(n, dtype=object)
                arr[:] = vals
                cols[a.name] = arr
            else:
                dt = _CHAR_NP[TYPE_CHARS[a.type]]
                cols[a.name] = np.asarray(
                    [0 if v is None else v for v in vals], dtype=dt)
        return [RowsChunk(cols, None, n)]

    def map(self, payload: Any) -> list[list]:
        obj = json.loads(payload) if isinstance(payload, (str, bytes)) \
            else payload
        events = obj if isinstance(obj, list) else [obj]
        out = []
        for e in events:
            body = e.get("event", e) if isinstance(e, dict) else None
            if body is None:
                out.append(list(e))
            else:
                out.append([body.get(a.name)
                            for a in self.definition.attributes])
        return out


class SinkMapper:
    """Rows-capable sink mappers additionally implement ``map_rows(cols,
    ts, n) -> payload`` so a whole output chunk maps in one call (no
    per-event ``Event`` objects on the egress hot path)."""

    map_rows = None             # rows-capable mappers override with a method

    def init(self, definition: StreamDefinition, options: dict) -> None:
        self.definition = definition
        self.options = options

    def map(self, event: Event) -> Any:
        raise NotImplementedError


class PassThroughSinkMapper(SinkMapper):
    def map(self, event: Event) -> Any:
        return event

    def map_rows(self, cols: dict, ts, n: int) -> Any:
        # the chunk IS the payload: downstream columnar consumers (the
        # in-memory broker → a RowsChunk-aware source) keep batch shape
        return RowsChunk(cols, ts, n)


class JsonSinkMapper(SinkMapper):
    def map(self, event: Event) -> Any:
        # OBJECT attributes (e.g. a fault stream's _error exception) fall
        # back to repr — a mapper must not fail on a representable event
        return json.dumps({
            "event": {a.name: v for a, v in zip(self.definition.attributes, event.data)}
        }, default=repr)

    def map_rows(self, cols: dict, ts, n: int) -> Any:
        # one JSON-lines payload per chunk (formatting is inherently
        # per-row string work, but no engine Event objects are built)
        names = [a.name for a in self.definition.attributes]
        rows = columns_to_rows(cols, names, n)
        return "\n".join(
            json.dumps({"event": dict(zip(names, r))}, default=repr)
            for r in rows)


class TextSinkMapper(SinkMapper):
    def map(self, event: Event) -> Any:
        return ", ".join(
            f"{a.name}:{v}" for a, v in zip(self.definition.attributes, event.data))

    def map_rows(self, cols: dict, ts, n: int) -> Any:
        names = [a.name for a in self.definition.attributes]
        rows = columns_to_rows(cols, names, n)
        return "\n".join(
            ", ".join(f"{nm}:{v}" for nm, v in zip(names, r))
            for r in rows)


SOURCE_MAPPERS = {
    "passThrough": PassThroughSourceMapper,
    "json": JsonSourceMapper,
    "csv": CsvSourceMapper,
    "jsonLines": JsonLinesSourceMapper,
}
SINK_MAPPERS = {
    "passThrough": PassThroughSinkMapper,
    "json": JsonSinkMapper,
    "text": TextSinkMapper,
}


# ---------------------------------------------------------------------------
# Source / Sink SPI
# ---------------------------------------------------------------------------

class ConnectionUnavailableError(Exception):
    pass


# ---------------------------------------------------------------------------
# handler interception SPIs
# ---------------------------------------------------------------------------

class SourceHandler:
    """Optional interception stage between a source's mapped rows and the
    stream's ``InputHandler`` (reference ``stream/input/source/
    SourceHandler.java:44`` — there it wraps the InputHandler with optional
    pre-processing; state rides on the instance here instead of the
    reference's StateHolder ceremony).

    Override :meth:`send_event`; call ``input_handler.send(row)`` to forward
    (possibly transformed), or skip the call to drop the event."""

    def init(self, app_name: str, definition: StreamDefinition,
             element_id: str = None) -> None:
        self.app_name = app_name
        self.definition = definition
        # the registry key is the UNIQUE element id (reference registers by
        # the Source's IdGenerator id, not a name-derived one — two @source
        # annotations on one stream must not collide)
        self.id = element_id or \
            f"{app_name}-{definition.id}-{type(self).__name__}"

    def send_event(self, row, input_handler) -> None:
        input_handler.send(row)


class SourceHandlerManager:
    """Per-engine factory + registry of :class:`SourceHandler` instances
    (reference ``SourceHandlerManager.java:27``). Install via
    ``SiddhiManager.set_source_handler_manager``; one handler is generated
    per wired source."""

    def __init__(self):
        self.registered: dict[str, SourceHandler] = {}

    def generate_source_handler(self, source_type: str) -> SourceHandler:
        raise NotImplementedError

    def register_source_handler(self, element_id: str,
                                handler: SourceHandler) -> None:
        self.registered[element_id] = handler

    def unregister_source_handler(self, element_id: str) -> None:
        self.registered.pop(element_id, None)


class SinkHandler:
    """Optional interception stage between a stream's outgoing events and
    its sink mapper (reference ``stream/output/sink/SinkHandler.java:34``).

    Override :meth:`handle`; call ``callback(event)`` to forward to the
    mapper+transport, or skip the call to drop it."""

    def init(self, app_name: str, definition: StreamDefinition,
             callback: Callable[[Event], None],
             element_id: str = None) -> None:
        self.app_name = app_name
        self.definition = definition
        self.callback = callback
        self.id = element_id or \
            f"{app_name}-{definition.id}-{type(self).__name__}"

    def handle(self, event: Event) -> None:
        self.callback(event)


class SinkHandlerManager:
    """Reference ``SinkHandlerManager.java`` — factory + registry of
    :class:`SinkHandler` instances, installed via
    ``SiddhiManager.set_sink_handler_manager``."""

    def __init__(self):
        self.registered: dict[str, SinkHandler] = {}

    def generate_sink_handler(self) -> SinkHandler:
        raise NotImplementedError

    def register_sink_handler(self, element_id: str,
                              handler: SinkHandler) -> None:
        self.registered[element_id] = handler

    def unregister_sink_handler(self, element_id: str) -> None:
        self.registered.pop(element_id, None)


class Source:
    """Transport-agnostic ingress (reference ``Source.java:50``).

    Subclasses implement connect/disconnect and call ``self.handler(payload)``.
    ``connect_with_retry`` applies capped backoff with decorrelating jitter
    like the reference (``connectWithRetry:155``); delays are configurable
    per source via ``retry.delays='0.1,0.5,1'`` (seconds, csv) and the loop
    aborts promptly when the app starts shutting down (the runtime hands
    every wired source its ``shutdown_signal``)."""

    extension_kind = "source"
    RETRY_DELAYS = [0.1, 0.5, 1.0, 5.0]
    shutdown_signal: Optional[threading.Event] = None   # set by the runtime
    connect_attempts = 0        # cumulative, incl. retries — exposed as the
    # siddhi_tpu_source_connect_attempts_total metric (a climbing count on a
    # running app is a flapping transport)

    def init(self, definition: StreamDefinition, options: dict,
             mapper: SourceMapper, handler: Callable[[Any], None]) -> None:
        self.definition = definition
        self.options = options
        self.mapper = mapper
        self.handler = handler

    def connect(self) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    def pause(self) -> None:
        pass

    def resume(self) -> None:
        pass

    def retry_delays(self) -> list[float]:
        raw = (getattr(self, "options", None) or {}).get("retry.delays")
        if not raw:
            return list(self.RETRY_DELAYS)
        delays = [float(x) for x in str(raw).split(",") if x.strip()]
        if any(d < 0 for d in delays):
            raise ValueError(f"retry.delays must be >= 0, got {delays}")
        return delays

    def _aborting(self) -> bool:
        sig = self.shutdown_signal
        return sig is not None and sig.is_set()

    def connect_with_retry(self) -> None:
        for i, delay in enumerate([0.0] + self.retry_delays()):
            if delay:
                # jitter decorrelates a fleet reconnecting after an outage
                wait = delay * (0.5 + random.random() * 0.5)
                sig = self.shutdown_signal
                if sig is not None:
                    sig.wait(wait)
                else:
                    time.sleep(wait)
            if self._aborting():
                log.info("source for stream '%s': connect retry aborted "
                         "(app shutting down)", self.definition.id)
                return
            try:
                self.connect_attempts += 1
                self.connect()
                return
            except ConnectionUnavailableError as e:
                log.warning("source connect failed (attempt %d): %s", i + 1, e)
        raise ConnectionUnavailableError(
            f"source for stream '{self.definition.id}' could not connect")


class InMemorySource(Source):
    def connect(self) -> None:
        topic = self.options.get("topic")
        if topic is None:
            raise ValueError("inMemory source needs topic")
        # a RowsChunk payload published to the topic forwards through the
        # columnar ingress (send_columns) instead of exploding into
        # per-event publishes — the app handler dispatches on payload type
        self._unsub = InMemoryBroker.subscribe(topic, self.handler)

    def disconnect(self) -> None:
        if hasattr(self, "_unsub"):
            self._unsub()


class LineSource(Source):
    """Base for byte-stream transports framed by newlines: buffers torn
    tails across reads, hands ONLY whole lines downstream. With a
    rows-capable mapper the payload goes down as raw bytes (the handler
    parses straight into columns → ``send_columns``, zero per-event
    objects); with a legacy mapper each line maps per event."""

    # torn-tail cap: a peer streaming bytes with no newline must not grow
    # resident memory without bound — past the cap the tail drops (counted)
    MAX_LINE_BYTES = 16 << 20

    def init(self, definition: StreamDefinition, options: dict,
             mapper: SourceMapper, handler: Callable[[Any], None]) -> None:
        super().init(definition, options, mapper, handler)
        self._tail = b""
        self.bytes_in = 0
        self.dropped_bytes = 0
        self._stop = threading.Event()
        self._rows_mapper = callable(getattr(mapper, "map_rows", None))
        self._max_line = int(options.get("max.line.bytes")
                             or self.MAX_LINE_BYTES)

    def feed(self, data: bytes) -> None:
        """One transport read: complete lines flow, the torn tail waits."""
        self.bytes_in += len(data)
        buf = self._tail + data if self._tail else data
        idx = buf.rfind(b"\n")
        if idx < 0:
            if len(buf) > self._max_line:
                self.dropped_bytes += len(buf)
                log.error("source '%s': dropping %d buffered bytes with no "
                          "line terminator (max.line.bytes=%d) — runaway "
                          "or non-line peer", self.definition.id, len(buf),
                          self._max_line)
                buf = b""
            self._tail = buf
            return
        complete, self._tail = buf[:idx + 1], buf[idx + 1:]
        self._dispatch(complete)

    def finish(self) -> None:
        """End of stream: an unterminated final line still counts."""
        if self._tail:
            tail, self._tail = self._tail, b""
            self._dispatch(tail + b"\n")

    def _dispatch(self, payload: bytes) -> None:
        if self._rows_mapper:
            self.handler(payload)
            return
        for line in payload.splitlines():
            if line:
                self.handler(line)

    def _stopping(self) -> bool:
        return self._stop.is_set() or self._aborting()

    def disconnect(self) -> None:
        self._stop.set()


class FileLineSource(LineSource):
    """``@source(type='file', file='/path', @map(type='csv', ...))`` —
    reads the file in chunks on a feeder thread; with a csv rows mapper the
    whole pipeline file-bytes → columns → SoA staging is zero-object."""

    def connect(self) -> None:
        path = self.options.get("file") or self.options.get("path")
        if not path:
            raise ValueError("file source needs file='...'")
        self._stop.clear()
        self.drained = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(path,), daemon=True,
            name=f"file-source-{self.definition.id}")
        self._thread.start()

    def _run(self, path: str) -> None:
        chunk = int(self.options.get("chunk.bytes") or (1 << 20))
        try:
            with open(path, "rb") as f:
                while not self._stopping():
                    data = f.read(chunk)
                    if not data:
                        break
                    self.feed(data)
            if not self._stopping():
                self.finish()
        except OSError as e:
            log.error("file source '%s': %s", path, e)
        finally:
            self.drained.set()

    def wait_drained(self, timeout: float = 30.0) -> bool:
        return self.drained.wait(timeout)

    def disconnect(self) -> None:
        super().disconnect()
        t = getattr(self, "_thread", None)
        if t is not None:
            t.join(timeout=5.0)


class SocketLineSource(LineSource):
    """``@source(type='socket', port='...', format='lines'|'rows')`` — a
    TCP listener parsing raw transport bytes straight into columns.

    ``format='lines'``: newline-framed text (csv/json-lines mappers).
    ``format='rows'``: length-prefixed DCN ``pack_rows`` SoA frames
    (``u32 len`` + payload — the same wire format the DCN shard layer
    ships, see DISTRIBUTED.md), decoded by ``unpack_columns`` with no
    text parse at all.

    Every blocking socket op is deadlined (``accept.timeout.ms``,
    ``read.timeout.ms``) so shutdown never hangs on a quiet peer."""

    def connect(self) -> None:
        host = self.options.get("host") or "127.0.0.1"
        port = int(self.options.get("port") or 0)
        self.format = (self.options.get("format") or "lines").lower()
        if self.format not in ("lines", "rows"):
            raise ValueError(f"socket source: unknown format "
                             f"'{self.format}' (lines|rows)")
        self._accept_t = float(self.options.get("accept.timeout.ms")
                               or 250) / 1000.0
        self._read_t = float(self.options.get("read.timeout.ms")
                             or 250) / 1000.0
        self._stop.clear()
        try:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind((host, port))
            ls.listen(4)
        except OSError as e:
            raise ConnectionUnavailableError(
                f"socket source cannot bind {host}:{port}: {e}") from e
        self._lsock = ls
        self.port = ls.getsockname()[1]     # port=0 → ephemeral, for tests
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"socket-source-{self.definition.id}")
        self._thread.start()

    def _accept_loop(self) -> None:
        ls = self._lsock
        ls.settimeout(self._accept_t)
        while not self._stopping():
            try:
                conn, _addr = ls.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                self._serve(conn)
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(self._read_t)       # every recv below is deadlined
        buf = b""
        while not self._stopping():
            try:
                data = conn.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                break
            if not data:
                break
            if self.format == "lines":
                self.feed(data)
            else:
                buf = self._feed_frames(buf + data)
                if buf is None:         # poisoned frame: drop the peer
                    break
        if self.format == "lines":
            self.finish()

    def _feed_frames(self, buf: bytes):
        """Length-prefixed ``pack_rows`` frames → RowsChunk payloads (the
        zero-object wire path: numeric columns are frombuffer views).
        Returns the unconsumed remainder, or None when a frame claims more
        than ``max.frame.bytes`` (a corrupt/hostile prefix must not make
        the receiver buffer gigabytes) — the caller closes the peer."""
        names = self.definition.attribute_names
        max_frame = int(self.options.get("max.frame.bytes") or (64 << 20))
        while len(buf) >= 4:
            (need,) = struct.unpack_from(">I", buf, 0)
            if need > max_frame:
                self.dropped_bytes += len(buf)
                log.error("socket source '%s': frame claims %d bytes "
                          "(max.frame.bytes=%d) — dropping the connection",
                          self.definition.id, need, max_frame)
                return None
            if len(buf) - 4 < need:
                break
            payload = buf[4:4 + need]
            buf = buf[4 + need:]
            self.bytes_in += need + 4
            try:
                cols_by_pos, ts, n, _types = unpack_columns(payload)
            except (struct.error, ValueError, IndexError) as e:
                log.error("socket source '%s': bad rows frame: %s",
                          self.definition.id, e)
                continue
            if n:
                self.handler(RowsChunk(
                    {nm: cols_by_pos[i] for i, nm in enumerate(names)},
                    ts, n))
        return buf

    def disconnect(self) -> None:
        super().disconnect()
        ls = getattr(self, "_lsock", None)
        if ls is not None:
            try:
                ls.close()
            except OSError:
                pass
        t = getattr(self, "_thread", None)
        if t is not None:
            t.join(timeout=5.0)


class PartialPublishError(Exception):
    """A rows-capable sink failed PART-way through a chunk: ``published``
    leading rows made it out; the resilience pipeline replays only the
    remainder per event (exactly-once egress for the chunk)."""

    def __init__(self, published: int, cause: Optional[Exception] = None):
        super().__init__(f"chunk publish failed after {published} row(s)"
                         + (f": {cause}" if cause else ""))
        self.published = int(published)
        self.cause = cause


class Sink:
    extension_kind = "sink"

    # rows-capable sinks override with a method: publish_rows(payload, n)
    # publishes one whole mapped chunk (all-or-nothing, or raise
    # PartialPublishError(published) so the pipeline replays the tail)
    publish_rows = None

    def init(self, definition: StreamDefinition, options: dict,
             mapper: SinkMapper) -> None:
        self.definition = definition
        self.options = options
        self.mapper = mapper

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def publish(self, payload: Any) -> None:
        raise NotImplementedError

    def on_event(self, event: Event) -> None:
        self.publish(self.mapper.map(event))

    @property
    def rows_capable(self) -> bool:
        """True when both this sink and its mapper handle whole chunks —
        the junction then delivers columns with zero per-event objects."""
        return type(self).publish_rows is not None and \
            callable(getattr(self.mapper, "map_rows", None))

    def on_columns(self, cols: dict, ts, n: int) -> None:
        self.publish_rows(self.mapper.map_rows(cols, ts, n), n)


class InMemorySink(Sink):
    def publish(self, payload: Any) -> None:
        InMemoryBroker.publish(self.options["topic"], payload)

    def publish_rows(self, payload: Any, n: int) -> None:
        InMemoryBroker.publish(self.options["topic"], payload)


class LogSink(Sink):
    def publish(self, payload: Any) -> None:
        prefix = self.options.get("prefix", self.definition.id)
        log.info("%s : %s", prefix, payload)

    def publish_rows(self, payload: Any, n: int) -> None:
        prefix = self.options.get("prefix", self.definition.id)
        log.info("%s : [%d rows] %s", prefix, n, payload)


class SinkReceiver:
    """Direct junction subscription for a wired sink (per-event path)."""

    def __init__(self, sink):
        self.sink = sink

    def receive(self, event) -> None:
        if event.type in (EventType.CURRENT, EventType.EXPIRED):
            self.sink.on_event(Event(event.timestamp, event.data,
                                     event.type == EventType.EXPIRED))


class RowsSinkReceiver(SinkReceiver):
    """Columns-capable sink subscription: whole chunks flow through
    ``Sink.on_columns`` (→ ``SinkMapper.map_rows`` → ``publish_rows``) with
    zero per-event Python objects on the happy path."""

    def receive_columns(self, cols: dict, ts, n: int) -> None:
        self.sink.on_columns(cols, ts, n)


SOURCES = {"inMemory": InMemorySource, "file": FileLineSource,
           "socket": SocketLineSource}
SINKS = {"inMemory": InMemorySink, "log": LogSink}


class DistributionStrategy:
    """Reference: ``stream/output/sink/distributed/DistributionStrategy.java`` —
    picks destination index(es) per event."""

    def __init__(self, destinations: int):
        self.n = destinations

    def destinations_for(self, event: Event) -> list[int]:
        raise NotImplementedError


class RoundRobinStrategy(DistributionStrategy):
    def __init__(self, destinations: int):
        super().__init__(destinations)
        self._i = 0

    def destinations_for(self, event: Event) -> list[int]:
        i = self._i
        self._i = (self._i + 1) % self.n
        return [i]


class PartitionedStrategy(DistributionStrategy):
    def __init__(self, destinations: int, key_pos: int):
        super().__init__(destinations)
        self.key_pos = key_pos

    def destinations_for(self, event: Event) -> list[int]:
        import zlib
        # stable across processes (Python's hash() is randomized) so a key
        # always lands on the same endpoint after restarts
        key = str(event.data[self.key_pos]).encode()
        return [zlib.crc32(key) % self.n]


class BroadcastStrategy(DistributionStrategy):
    def destinations_for(self, event: Event) -> list[int]:
        return list(range(self.n))


class DistributedSink:
    """Multi-endpoint egress (reference ``MultiClientDistributedSink.java``):
    one underlying sink per @destination, events routed per strategy."""

    def __init__(self, sinks: list[Sink], strategy: DistributionStrategy):
        self.sinks = sinks
        self.strategy = strategy

    def on_event(self, event: Event) -> None:
        for i in self.strategy.destinations_for(event):
            self.sinks[i].on_event(event)

    def connect(self) -> None:
        for s in self.sinks:
            s.connect()

    def disconnect(self) -> None:
        for s in self.sinks:
            s.disconnect()


def parse_io_annotations(definition: StreamDefinition):
    """Extract (@source, @sink) configs from a stream definition's annotations."""
    sources, sinks = [], []
    for ann in definition.annotations:
        low = ann.name.lower()
        if low in ("source", "sink"):
            opts = {e.key: e.value for e in ann.elements if e.key}
            map_ann = ann.nested("map")
            map_type = map_ann.get("type") if map_ann else "passThrough"
            # @map's own options (e.g. ts.last for the csv mapper) reach the
            # mapper alongside the transport options
            map_opts = {e.key: e.value for e in map_ann.elements if e.key} \
                if map_ann else {}
            entry = {"type": opts.get("type"), "options": opts,
                     "map": map_type, "map_options": map_opts}
            dist = ann.nested("distribution")
            if dist is not None and low == "sink":
                entry["distribution"] = {
                    "strategy": dist.get("strategy", "roundRobin"),
                    "partitionKey": dist.get("partitionKey"),
                    "destinations": [
                        {e.key: e.value for e in d.elements if e.key}
                        for d in dist.annotations if d.name.lower() == "destination"
                    ],
                }
            (sources if low == "source" else sinks).append(entry)
    return sources, sinks
