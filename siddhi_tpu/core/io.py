"""Sources, sinks, mappers, and the in-memory broker.

Reference: ``core/stream/input/source/`` (``Source.java`` with connect/retry,
``SourceMapper``), ``core/stream/output/sink/`` (``Sink.java``, ``SinkMapper``,
``LogSink``, ``InMemorySink``), ``core/util/transport/InMemoryBroker.java``.
Transports are host-side by design — on TPU they feed the batching ingress.
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
from typing import Any, Callable, Optional

from ..query_api.annotation import Annotation
from ..query_api.definition import StreamDefinition
from .event import Event

log = logging.getLogger("siddhi_tpu.io")


# ---------------------------------------------------------------------------
# In-memory broker (static topic pub/sub, test transport)
# ---------------------------------------------------------------------------

class InMemoryBroker:
    _topics: dict[str, list[Callable[[Any], None]]] = {}
    _lock = threading.RLock()

    @classmethod
    def subscribe(cls, topic: str, receiver: Callable[[Any], None]) -> Callable[[], None]:
        with cls._lock:
            cls._topics.setdefault(topic, []).append(receiver)

        def unsubscribe():
            with cls._lock:
                if receiver in cls._topics.get(topic, []):
                    cls._topics[topic].remove(receiver)

        return unsubscribe

    @classmethod
    def publish(cls, topic: str, payload: Any) -> None:
        with cls._lock:
            receivers = list(cls._topics.get(topic, []))
        for r in receivers:
            r(payload)

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._topics.clear()


# ---------------------------------------------------------------------------
# Mappers
# ---------------------------------------------------------------------------

class SourceMapper:
    """payload → list of event payload lists."""

    def init(self, definition: StreamDefinition, options: dict) -> None:
        self.definition = definition
        self.options = options

    def map(self, payload: Any) -> list[list]:
        raise NotImplementedError


class PassThroughSourceMapper(SourceMapper):
    def map(self, payload: Any) -> list[list]:
        if isinstance(payload, Event):
            return [list(payload.data)]
        if isinstance(payload, (list, tuple)):
            if payload and isinstance(payload[0], (list, tuple, Event)):
                return [list(p.data) if isinstance(p, Event) else list(p)
                        for p in payload]
            return [list(payload)]
        raise ValueError(f"passThrough cannot map {type(payload).__name__}")


class JsonSourceMapper(SourceMapper):
    def map(self, payload: Any) -> list[list]:
        obj = json.loads(payload) if isinstance(payload, (str, bytes)) else payload
        events = obj if isinstance(obj, list) else [obj]
        out = []
        for e in events:
            if isinstance(e, dict):
                body = e.get("event", e)
                out.append([body.get(a.name) for a in self.definition.attributes])
            else:
                out.append(list(e))
        return out


class SinkMapper:
    def init(self, definition: StreamDefinition, options: dict) -> None:
        self.definition = definition
        self.options = options

    def map(self, event: Event) -> Any:
        raise NotImplementedError


class PassThroughSinkMapper(SinkMapper):
    def map(self, event: Event) -> Any:
        return event


class JsonSinkMapper(SinkMapper):
    def map(self, event: Event) -> Any:
        # OBJECT attributes (e.g. a fault stream's _error exception) fall
        # back to repr — a mapper must not fail on a representable event
        return json.dumps({
            "event": {a.name: v for a, v in zip(self.definition.attributes, event.data)}
        }, default=repr)


class TextSinkMapper(SinkMapper):
    def map(self, event: Event) -> Any:
        return ", ".join(
            f"{a.name}:{v}" for a, v in zip(self.definition.attributes, event.data))


SOURCE_MAPPERS = {
    "passThrough": PassThroughSourceMapper,
    "json": JsonSourceMapper,
}
SINK_MAPPERS = {
    "passThrough": PassThroughSinkMapper,
    "json": JsonSinkMapper,
    "text": TextSinkMapper,
}


# ---------------------------------------------------------------------------
# Source / Sink SPI
# ---------------------------------------------------------------------------

class ConnectionUnavailableError(Exception):
    pass


# ---------------------------------------------------------------------------
# handler interception SPIs
# ---------------------------------------------------------------------------

class SourceHandler:
    """Optional interception stage between a source's mapped rows and the
    stream's ``InputHandler`` (reference ``stream/input/source/
    SourceHandler.java:44`` — there it wraps the InputHandler with optional
    pre-processing; state rides on the instance here instead of the
    reference's StateHolder ceremony).

    Override :meth:`send_event`; call ``input_handler.send(row)`` to forward
    (possibly transformed), or skip the call to drop the event."""

    def init(self, app_name: str, definition: StreamDefinition,
             element_id: str = None) -> None:
        self.app_name = app_name
        self.definition = definition
        # the registry key is the UNIQUE element id (reference registers by
        # the Source's IdGenerator id, not a name-derived one — two @source
        # annotations on one stream must not collide)
        self.id = element_id or \
            f"{app_name}-{definition.id}-{type(self).__name__}"

    def send_event(self, row, input_handler) -> None:
        input_handler.send(row)


class SourceHandlerManager:
    """Per-engine factory + registry of :class:`SourceHandler` instances
    (reference ``SourceHandlerManager.java:27``). Install via
    ``SiddhiManager.set_source_handler_manager``; one handler is generated
    per wired source."""

    def __init__(self):
        self.registered: dict[str, SourceHandler] = {}

    def generate_source_handler(self, source_type: str) -> SourceHandler:
        raise NotImplementedError

    def register_source_handler(self, element_id: str,
                                handler: SourceHandler) -> None:
        self.registered[element_id] = handler

    def unregister_source_handler(self, element_id: str) -> None:
        self.registered.pop(element_id, None)


class SinkHandler:
    """Optional interception stage between a stream's outgoing events and
    its sink mapper (reference ``stream/output/sink/SinkHandler.java:34``).

    Override :meth:`handle`; call ``callback(event)`` to forward to the
    mapper+transport, or skip the call to drop it."""

    def init(self, app_name: str, definition: StreamDefinition,
             callback: Callable[[Event], None],
             element_id: str = None) -> None:
        self.app_name = app_name
        self.definition = definition
        self.callback = callback
        self.id = element_id or \
            f"{app_name}-{definition.id}-{type(self).__name__}"

    def handle(self, event: Event) -> None:
        self.callback(event)


class SinkHandlerManager:
    """Reference ``SinkHandlerManager.java`` — factory + registry of
    :class:`SinkHandler` instances, installed via
    ``SiddhiManager.set_sink_handler_manager``."""

    def __init__(self):
        self.registered: dict[str, SinkHandler] = {}

    def generate_sink_handler(self) -> SinkHandler:
        raise NotImplementedError

    def register_sink_handler(self, element_id: str,
                              handler: SinkHandler) -> None:
        self.registered[element_id] = handler

    def unregister_sink_handler(self, element_id: str) -> None:
        self.registered.pop(element_id, None)


class Source:
    """Transport-agnostic ingress (reference ``Source.java:50``).

    Subclasses implement connect/disconnect and call ``self.handler(payload)``.
    ``connect_with_retry`` applies capped backoff with decorrelating jitter
    like the reference (``connectWithRetry:155``); delays are configurable
    per source via ``retry.delays='0.1,0.5,1'`` (seconds, csv) and the loop
    aborts promptly when the app starts shutting down (the runtime hands
    every wired source its ``shutdown_signal``)."""

    extension_kind = "source"
    RETRY_DELAYS = [0.1, 0.5, 1.0, 5.0]
    shutdown_signal: Optional[threading.Event] = None   # set by the runtime
    connect_attempts = 0        # cumulative, incl. retries — exposed as the
    # siddhi_tpu_source_connect_attempts_total metric (a climbing count on a
    # running app is a flapping transport)

    def init(self, definition: StreamDefinition, options: dict,
             mapper: SourceMapper, handler: Callable[[Any], None]) -> None:
        self.definition = definition
        self.options = options
        self.mapper = mapper
        self.handler = handler

    def connect(self) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    def pause(self) -> None:
        pass

    def resume(self) -> None:
        pass

    def retry_delays(self) -> list[float]:
        raw = (getattr(self, "options", None) or {}).get("retry.delays")
        if not raw:
            return list(self.RETRY_DELAYS)
        delays = [float(x) for x in str(raw).split(",") if x.strip()]
        if any(d < 0 for d in delays):
            raise ValueError(f"retry.delays must be >= 0, got {delays}")
        return delays

    def _aborting(self) -> bool:
        sig = self.shutdown_signal
        return sig is not None and sig.is_set()

    def connect_with_retry(self) -> None:
        for i, delay in enumerate([0.0] + self.retry_delays()):
            if delay:
                # jitter decorrelates a fleet reconnecting after an outage
                wait = delay * (0.5 + random.random() * 0.5)
                sig = self.shutdown_signal
                if sig is not None:
                    sig.wait(wait)
                else:
                    time.sleep(wait)
            if self._aborting():
                log.info("source for stream '%s': connect retry aborted "
                         "(app shutting down)", self.definition.id)
                return
            try:
                self.connect_attempts += 1
                self.connect()
                return
            except ConnectionUnavailableError as e:
                log.warning("source connect failed (attempt %d): %s", i + 1, e)
        raise ConnectionUnavailableError(
            f"source for stream '{self.definition.id}' could not connect")


class InMemorySource(Source):
    def connect(self) -> None:
        topic = self.options.get("topic")
        if topic is None:
            raise ValueError("inMemory source needs topic")
        self._unsub = InMemoryBroker.subscribe(topic, self.handler)

    def disconnect(self) -> None:
        if hasattr(self, "_unsub"):
            self._unsub()


class Sink:
    extension_kind = "sink"

    def init(self, definition: StreamDefinition, options: dict,
             mapper: SinkMapper) -> None:
        self.definition = definition
        self.options = options
        self.mapper = mapper

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def publish(self, payload: Any) -> None:
        raise NotImplementedError

    def on_event(self, event: Event) -> None:
        self.publish(self.mapper.map(event))


class InMemorySink(Sink):
    def publish(self, payload: Any) -> None:
        InMemoryBroker.publish(self.options["topic"], payload)


class LogSink(Sink):
    def publish(self, payload: Any) -> None:
        prefix = self.options.get("prefix", self.definition.id)
        log.info("%s : %s", prefix, payload)


SOURCES = {"inMemory": InMemorySource}
SINKS = {"inMemory": InMemorySink, "log": LogSink}


class DistributionStrategy:
    """Reference: ``stream/output/sink/distributed/DistributionStrategy.java`` —
    picks destination index(es) per event."""

    def __init__(self, destinations: int):
        self.n = destinations

    def destinations_for(self, event: Event) -> list[int]:
        raise NotImplementedError


class RoundRobinStrategy(DistributionStrategy):
    def __init__(self, destinations: int):
        super().__init__(destinations)
        self._i = 0

    def destinations_for(self, event: Event) -> list[int]:
        i = self._i
        self._i = (self._i + 1) % self.n
        return [i]


class PartitionedStrategy(DistributionStrategy):
    def __init__(self, destinations: int, key_pos: int):
        super().__init__(destinations)
        self.key_pos = key_pos

    def destinations_for(self, event: Event) -> list[int]:
        import zlib
        # stable across processes (Python's hash() is randomized) so a key
        # always lands on the same endpoint after restarts
        key = str(event.data[self.key_pos]).encode()
        return [zlib.crc32(key) % self.n]


class BroadcastStrategy(DistributionStrategy):
    def destinations_for(self, event: Event) -> list[int]:
        return list(range(self.n))


class DistributedSink:
    """Multi-endpoint egress (reference ``MultiClientDistributedSink.java``):
    one underlying sink per @destination, events routed per strategy."""

    def __init__(self, sinks: list[Sink], strategy: DistributionStrategy):
        self.sinks = sinks
        self.strategy = strategy

    def on_event(self, event: Event) -> None:
        for i in self.strategy.destinations_for(event):
            self.sinks[i].on_event(event)

    def connect(self) -> None:
        for s in self.sinks:
            s.connect()

    def disconnect(self) -> None:
        for s in self.sinks:
            s.disconnect()


def parse_io_annotations(definition: StreamDefinition):
    """Extract (@source, @sink) configs from a stream definition's annotations."""
    sources, sinks = [], []
    for ann in definition.annotations:
        low = ann.name.lower()
        if low in ("source", "sink"):
            opts = {e.key: e.value for e in ann.elements if e.key}
            map_ann = ann.nested("map")
            map_type = map_ann.get("type") if map_ann else "passThrough"
            entry = {"type": opts.get("type"), "options": opts, "map": map_type}
            dist = ann.nested("distribution")
            if dist is not None and low == "sink":
                entry["distribution"] = {
                    "strategy": dist.get("strategy", "roundRobin"),
                    "partitionKey": dist.get("partitionKey"),
                    "destinations": [
                        {e.key: e.value for e in d.elements if e.key}
                        for d in dist.annotations if d.name.lower() == "destination"
                    ],
                }
            (sources if low == "source" else sinks).append(entry)
    return sources, sinks
