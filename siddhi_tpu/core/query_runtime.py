"""Query planner: AST → processor chains.

Reference: ``core/util/parser/`` — ``QueryParser.parse`` (QueryParser.java:90),
``SingleInputStreamParser`` (per-stream chains), ``StateInputStreamParser`` (NFA),
``JoinInputStreamParser``, ``SelectorParser``, ``OutputParser``.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..query_api import (
    Constant,
    DataType,
    DeleteStream,
    EventTrigger,
    Filter,
    InsertIntoStream,
    JoinInputStream,
    OutputEventsFor,
    Query,
    ReturnStream,
    SingleInputStream,
    StateInputStream,
    StreamFunction,
    UpdateOrInsertStream,
    UpdateStream,
    Variable,
    Window,
)
from ..query_api.definition import StreamDefinition
from .event import EventType, StreamEvent
from .executor import (
    ExecutorBuilder,
    JoinResolver,
    StateResolver,
    StreamFrame,
    StreamResolver,
)
from .join import JoinRuntime, JoinSide
from .named_window import NamedWindow
from .output import (
    DeleteTableCallback,
    FanoutProcessor,
    InsertIntoStreamCallback,
    InsertIntoTableCallback,
    InsertIntoWindowCallback,
    QueryCallbackAdapter,
    UpdateOrInsertTableCallback,
    UpdateTableCallback,
)
from .pattern import CompiledPattern, PatternCompiler, PatternRuntime
from .processors import FilterProcessor, Processor, SinkProcessor
from .ratelimit import build_rate_limiter
from .selector import build_selector
from .table import compile_table_condition
from . import windows as W


class QueryBuildError(Exception):
    pass


def _within_bound(expr) -> int:
    """One bound of a two-arg aggregation-join ``within start, end``."""
    from .aggregation import parse_within_value
    from .errors import SiddhiAppRuntimeError
    try:
        return parse_within_value(getattr(expr, "value", None))
    except (ValueError, SiddhiAppRuntimeError) as e:
        raise QueryBuildError(str(e)) from None


# ---------------------------------------------------------------------------
# Window factory
# ---------------------------------------------------------------------------

def _const(p, what: str):
    if not isinstance(p, Constant):
        raise QueryBuildError(f"{what} expects constant parameter")
    return p.value


def make_window_processor(win: Window, definition: StreamDefinition,
                          app_context, query_ctx_id: str) -> W.WindowProcessor:
    """Instantiate a window processor from its AST node.

    Reference catalog: ``query/processor/stream/window/*WindowProcessor``.
    """
    name = win.name
    params = win.params
    builder = ExecutorBuilder(StreamResolver(definition), app_context)

    def executor(i):
        return builder.build(params[i])[0]

    if name == "length":
        proc = W.LengthWindow(int(_const(params[0], "length")))
    elif name == "lengthBatch":
        proc = W.LengthBatchWindow(int(_const(params[0], "lengthBatch")))
    elif name == "time":
        proc = W.TimeWindow(int(_const(params[0], "time")))
    elif name == "timeBatch":
        start = int(_const(params[1], "timeBatch")) if len(params) > 1 else None
        proc = W.TimeBatchWindow(int(_const(params[0], "timeBatch")), start)
    elif name == "timeLength":
        proc = W.TimeLengthWindow(int(_const(params[0], "timeLength")),
                                  int(_const(params[1], "timeLength")))
    elif name == "externalTime":
        proc = W.ExternalTimeWindow(executor(0), int(_const(params[1], "externalTime")))
    elif name == "externalTimeBatch":
        start = int(_const(params[2], "externalTimeBatch")) if len(params) > 2 else None
        proc = W.ExternalTimeBatchWindow(
            executor(0), int(_const(params[1], "externalTimeBatch")), start)
    elif name == "session":
        gap = int(_const(params[0], "session"))
        key_fn = executor(1) if len(params) > 1 else None
        latency = int(_const(params[2], "session")) if len(params) > 2 else 0
        proc = W.SessionWindow(gap, key_fn, latency)
    elif name == "batch":
        proc = W.BatchWindow()
    elif name == "delay":
        proc = W.DelayWindow(int(_const(params[0], "delay")))
    elif name == "sort":
        n = int(_const(params[0], "sort"))
        key_fns, orders = [], []
        i = 1
        while i < len(params):
            key_fns.append(builder.build(params[i])[0])
            i += 1
            if i < len(params) and isinstance(params[i], Constant) \
                    and str(params[i].value).lower() in ("asc", "desc"):
                orders.append(str(params[i].value).lower())
                i += 1
            else:
                orders.append("asc")
        proc = W.SortWindow(n, key_fns, orders)
    elif name == "frequent":
        n = int(_const(params[0], "frequent"))
        key_fns = [builder.build(p)[0] for p in params[1:]] or None
        proc = W.FrequentWindow(n, key_fns)
    elif name == "lossyFrequent":
        support = float(_const(params[0], "lossyFrequent"))
        error = float(_const(params[1], "lossyFrequent")) if len(params) > 1 and \
            isinstance(params[1], Constant) and not isinstance(params[1].value, str) else None
        key_start = 2 if error is not None else 1
        key_fns = [builder.build(p)[0] for p in params[key_start:]] or None
        proc = W.LossyFrequentWindow(support, error, key_fns)
    elif name in ("expression", "expressionBatch"):
        from ..compiler.parser import Parser
        from .expression_window import (
            DynamicExpressionBatchWindow,
            DynamicExpressionWindow,
        )
        expr_text = str(_const(params[0], name))
        expr_ast = Parser(expr_text).parse_expression()
        cls = DynamicExpressionWindow if name == "expression" \
            else DynamicExpressionBatchWindow
        proc = cls(expr_ast, definition, app_context)
    elif name == "cron":
        proc = W.CronWindow(str(_const(params[0], "cron")))
    elif name == "hopping":
        proc = W.HoppingWindow(int(_const(params[0], "hopping")),
                               int(_const(params[1], "hopping")))
    elif name == "":
        proc = W.EmptyWindow()
    else:
        # extension windows
        ext = app_context.siddhi_context.extensions.get(f"window:{name}")
        if ext is None:
            raise QueryBuildError(f"unknown window type '{name}'")
        proc = ext(params, definition, app_context)
    proc.setup(app_context, app_context.element_id(f"{query_ctx_id}-window-{name}"))
    return proc


# ---------------------------------------------------------------------------
# Stream function factory
# ---------------------------------------------------------------------------

def make_stream_function(sf: StreamFunction, definition: StreamDefinition,
                         app_context):
    """Returns (processor, output_definition)."""
    key = f"{sf.namespace}:{sf.name}" if sf.namespace else sf.name
    ext = app_context.siddhi_context.extensions.get(key)
    if ext is None or getattr(ext, "extension_kind", None) != "stream_function":
        raise QueryBuildError(f"unknown stream function '{key}'")
    inst = ext()
    builder = ExecutorBuilder(StreamResolver(definition), app_context)
    param_fns = [builder.build(p)[0] for p in sf.params]
    out_def = inst.init(definition, sf.params, param_fns)
    from .processors import StreamFunctionProcessor

    def fn(ev: StreamEvent):
        return inst.process(ev, [p(StreamFrame(ev)) for p in param_fns])

    return StreamFunctionProcessor(fn), out_def


# ---------------------------------------------------------------------------
# Single-stream chain
# ---------------------------------------------------------------------------

class StreamReceiver:
    """Junction subscriber feeding a query's processor chain."""

    def __init__(self, head: Processor):
        self.head = head

    def receive(self, event: StreamEvent) -> None:
        self.head.process([event])

    def receive_chunk(self, events: list[StreamEvent]) -> None:
        self.head.process(list(events))


class ObservedReceiver:
    """Outermost receiver wrapper: per-query end-to-end latency (the
    ``query.{name}`` histogram, reference ``LatencyTracker`` sites around
    ``StreamJunction`` delivery) plus the ``query`` trace span. One level
    check per event when statistics are OFF and no trace is active."""

    def __init__(self, inner, app_context, query_name: str,
                 metric_name: Optional[str] = None):
        from .metrics import Level
        self._off = Level.OFF
        self.inner = inner
        self.app_context = app_context
        self.query_name = query_name
        sm = app_context.statistics_manager      # None on bare contexts
        # metric_name caps cardinality: partition key instances share the
        # LOGICAL query's histogram (a per-key tracker per partition key
        # would grow without bound), while trace spans keep the full name
        self.tracker = sm.latency_tracker(
            f"query.{metric_name or query_name}") if sm is not None else None

    def _observing(self):
        ctx = self.app_context
        tracer = ctx.tracer
        tr = tracer.active if tracer is not None else None
        sm = ctx.statistics_manager
        return (sm is not None and sm.level is not self._off
                and self.tracker is not None), tr

    def receive(self, event: StreamEvent) -> None:
        track, tr = self._observing()
        if not track and tr is None:
            self.inner.receive(event)
            return
        t0 = time.perf_counter_ns()
        try:
            self.inner.receive(event)
        finally:
            dt = time.perf_counter_ns() - t0
            if track:
                # a sampled trace becomes the bucket's exemplar: the tail
                # links to a concrete journey
                self.tracker.record_seconds(
                    dt / 1e9,
                    exemplar=tr.trace_id if tr is not None else None)
            if tr is not None:
                tr.add_span("query", self.query_name, dt, 1)

    def receive_chunk(self, events: list[StreamEvent]) -> None:
        track, tr = self._observing()
        if not track and tr is None:
            self.inner.receive_chunk(events)
            return
        t0 = time.perf_counter_ns()
        try:
            self.inner.receive_chunk(events)
        finally:
            dt = time.perf_counter_ns() - t0
            if track:
                self.tracker.record_seconds(
                    dt / 1e9,
                    exemplar=tr.trace_id if tr is not None else None)
            if tr is not None:
                tr.add_span("query", self.query_name, dt, len(events))


class _StageProcessor(Processor):
    """Trace-only pass-through: when a sampled trace is active, times the
    chain from here down as one ``stage`` span (span durations nest, like
    a span tree)."""

    def __init__(self, app_context, stage: str, detail: str):
        super().__init__()
        self.app_context = app_context
        self.stage = stage
        self.detail = detail

    def process(self, events):
        tracer = self.app_context.tracer
        tr = tracer.active if tracer is not None else None
        if tr is None:
            self.forward(events)
            return
        t0 = time.perf_counter_ns()
        try:
            self.forward(events)
        finally:
            tr.add_span(self.stage, self.detail,
                        time.perf_counter_ns() - t0, len(events))


class _ChainHead(Processor):
    def process(self, events):
        self.forward(events)


# windows whose flush chunks are BATCHES for the selector (reference: the
# processors extending BatchingWindowProcessor; chunks carry isBatch=true)
BATCHING_WINDOWS = frozenset(
    {"batch", "lengthBatch", "timeBatch", "externalTimeBatch", "cron",
     "expressionBatch", "hopping"})


def build_single_chain(stream: SingleInputStream, definition: StreamDefinition,
                       app_context, query_id: str):
    """Build filter/window/function chain. Returns (head, tail, effective_def,
    window_processor_or_None)."""
    head = _ChainHead()
    tail: Processor = head
    eff_def = definition
    window_proc = None
    for h in stream.handlers:
        if isinstance(h, Filter):
            builder = ExecutorBuilder(StreamResolver(eff_def), app_context)
            cond, _ = builder.build(h.expr)
            tail = tail.set_next(FilterProcessor(cond))
        elif isinstance(h, Window):
            window_proc = make_window_processor(h, eff_def, app_context, query_id)
            if app_context.tracer is not None:
                tail = tail.set_next(_StageProcessor(
                    app_context, "window", h.name or "empty"))
            tail = tail.set_next(window_proc)
        elif isinstance(h, StreamFunction):
            proc, eff_def = make_stream_function(h, eff_def, app_context)
            tail = tail.set_next(proc)
    return head, tail, eff_def, window_proc


# ---------------------------------------------------------------------------
# QueryRuntime
# ---------------------------------------------------------------------------

class QueryRuntime:
    def __init__(self, query: Query, name: str):
        self.query = query
        self.name = name
        self.callback_adapter = QueryCallbackAdapter()
        self.subscriptions: list[tuple[str, object]] = []   # (stream_id, receiver)
        self.output_schema: tuple[list[str], list[DataType]] = ([], [])
        self.pattern_runtime: Optional[PatternRuntime] = None

    def add_callback(self, cb) -> None:
        self.callback_adapter.callbacks.append(cb)

    def start(self) -> None:
        if self.pattern_runtime is not None:
            self.pattern_runtime.start()


def build_query_runtime(query: Query, app_context, stream_defs: dict,
                        get_junction: Callable, name: str,
                        inner_defs: Optional[dict] = None,
                        metric_name: Optional[str] = None) -> QueryRuntime:
    """Construct a QueryRuntime. ``get_junction(stream_id, inner)`` resolves
    junctions (partition-local for inner streams). ``metric_name`` (default:
    ``name``) keys the latency histogram — partition key instances pass the
    logical query name so cardinality stays bounded."""
    rt = QueryRuntime(query, name)
    rt.metric_name = metric_name or name
    qid = name
    ist = query.input_stream

    def stream_def(sid: str, inner: bool) -> StreamDefinition:
        defs = inner_defs if inner and inner_defs is not None else stream_defs
        if sid in app_context.named_windows:
            return app_context.named_windows[sid].definition
        if sid not in defs:
            raise QueryBuildError(f"query '{name}': undefined stream '{sid}'")
        return defs[sid]

    # ---------------- input side -------------------------------------------
    if isinstance(ist, SingleInputStream):
        sid_eff = ("!" + ist.stream_id) if ist.is_fault_stream else ist.stream_id
        d = stream_def(sid_eff, ist.is_inner_stream)
        head, tail, eff_def, _ = build_single_chain(ist, d, app_context, qid)
        selector_builder = ExecutorBuilder(StreamResolver(eff_def), app_context)
        selector = build_selector(query.selector, selector_builder,
                                  eff_def.attribute_names,
                                  [a.type for a in eff_def.attributes],
                                  app_context.element_id(f"{qid}-selector"))
        # aggregated chunks from BATCHING windows collapse to one row per
        # flush (reference QuerySelector.process:81 — isBatch chunks);
        # reading FROM a named window inherits ITS window type's batching
        # (CustomJoinWindowTestCase.testMultipleStreamsToWindow pins one
        # collapsed row per lengthBatch named-window flush)
        selector.batching = any(
            isinstance(h, Window) and h.name in BATCHING_WINDOWS
            for h in ist.handlers)
        nw_src = app_context.named_windows.get(ist.stream_id)
        if nw_src is not None:
            wh = nw_src.definition.window_handler
            if wh is not None and getattr(wh, "name", None) in BATCHING_WINDOWS:
                selector.batching = True
        ef = getattr(query.output_stream, "events_for",
                     OutputEventsFor.CURRENT_EVENTS)
        selector.current_on = ef != OutputEventsFor.EXPIRED_EVENTS
        selector.expired_on = ef != OutputEventsFor.CURRENT_EVENTS
        app_context.register_state(selector.element_id, selector)
        if app_context.tracer is not None:
            tail = tail.set_next(_StageProcessor(app_context, "selector",
                                                 name))
        tail.set_next(_SelectorBridge(selector))
        from .debugger import DebuggedReceiver
        receiver = ObservedReceiver(
            DebuggedReceiver(StreamReceiver(head), name, app_context),
            app_context, name, rt.metric_name)
        rt.subscriptions.append((sid_eff, receiver))

    elif isinstance(ist, StateInputStream):
        defs_for_pattern = dict(stream_defs)
        compiler = PatternCompiler(ist, defs_for_pattern)
        compiled = compiler.compile()
        pattern_rt = PatternRuntime(
            compiled, app_context, app_context.element_id(f"{qid}-pattern"))
        rt.pattern_runtime = pattern_rt
        resolver = StateResolver(compiled.alias_defs)
        selector_builder = ExecutorBuilder(resolver, app_context)
        # pattern output schema: alias attributes referenced via select
        names, types = _selector_schema_from_alias(compiled)
        selector = build_selector(query.selector, selector_builder, names, types,
                                  app_context.element_id(f"{qid}-selector"))
        app_context.register_state(selector.element_id, selector)
        pattern_rt.next = selector
        from .debugger import DebuggedReceiver
        from .pattern import PatternStreamReceiver
        for sid in compiled.stream_ids:
            rt.subscriptions.append((sid, ObservedReceiver(
                DebuggedReceiver(PatternStreamReceiver(pattern_rt, sid),
                                 name, app_context),
                app_context, name, rt.metric_name)))

    elif isinstance(ist, JoinInputStream):
        selector = _build_join(ist, rt, app_context, stream_defs, stream_def,
                               query, qid)
    else:
        raise QueryBuildError(f"unsupported input stream {type(ist).__name__}")

    # ---------------- output side ------------------------------------------
    out_names = selector.output_names
    out_types = selector.output_types
    rt.output_schema = (out_names, out_types)

    limiter = build_rate_limiter(query.output_rate, app_context,
                                 grouped=bool(query.selector.group_by))
    app_context.register_state(app_context.element_id(f"{qid}-ratelimit"), limiter)
    selector.next = limiter

    targets: list = [rt.callback_adapter]
    from .debugger import DebuggedOutput
    os = query.output_stream
    if isinstance(os, InsertIntoStream):
        if os.target_id in app_context.tables:
            targets.append(InsertIntoTableCallback(
                app_context.tables[os.target_id], os.events_for))
        elif os.target_id in app_context.named_windows:
            targets.append(InsertIntoWindowCallback(
                app_context.named_windows[os.target_id], os.events_for))
        else:
            junction = get_junction(os.target_id, os.is_inner_stream)
            targets.append(InsertIntoStreamCallback(junction, os.events_for))
    elif isinstance(os, DeleteStream):
        table = app_context.get_table(os.target_id)
        cond = compile_table_condition(table, os.on_condition, out_names,
                                       out_types, app_context)
        targets.append(DeleteTableCallback(table, cond))
    elif isinstance(os, (UpdateStream, UpdateOrInsertStream)):
        table = app_context.get_table(os.target_id)
        cond = compile_table_condition(table, os.on_condition, out_names,
                                       out_types, app_context)
        setters = _build_setters(os.set_attributes, table, out_names, out_types,
                                 app_context)
        cls = UpdateTableCallback if isinstance(os, UpdateStream) \
            else UpdateOrInsertTableCallback
        targets.append(cls(table, cond, setters))
    elif isinstance(os, ReturnStream) or os is None:
        pass
    limiter.next = DebuggedOutput(FanoutProcessor(targets), name, app_context)
    return rt


class _SelectorBridge(Processor):
    def __init__(self, selector):
        super().__init__()
        self.selector = selector

    def process(self, events):
        self.selector.process(events)


def _selector_schema_from_alias(compiled: CompiledPattern):
    names: list[str] = []
    types: list[DataType] = []
    for alias, d in compiled.alias_defs.items():
        for a in d.attributes:
            if a.name not in names:
                names.append(a.name)
                types.append(a.type)
    return names, types


def _build_setters(set_attributes, table, out_names, out_types, app_context):
    from .table import TableMatchResolver
    resolver = TableMatchResolver(table.definition, out_names, out_types)
    builder = ExecutorBuilder(resolver, app_context)
    setters = []
    for sa in set_attributes:
        pos = table.definition.attribute_position(sa.table_variable.attribute)
        fn, _ = builder.build(sa.value_expr)
        setters.append((pos, fn))
    if not setters:
        # no SET clause: update every column from the matching event by name
        for i, n in enumerate(out_names):
            if n in table.definition.attribute_names:
                pos = table.definition.attribute_position(n)
                setters.append((pos, lambda f, i=i: f.out[i]))
    return setters


def _record_store_find(table, table_ref, table_is_left, on_condition, builder):
    """Push the join condition down to a record store (reference
    ``AbstractQueryableRecordTable.java:99``): the store receives a
    StoreExpression once plus per-probe parameter values and returns
    pre-filtered rows. None when the table isn't record-backed, the
    condition doesn't convert, or the store declines."""
    from .table import AbstractRecordTable, CacheTable, StoreExpression, \
        build_store_tree
    backing = table.backing if isinstance(table, CacheTable) else table
    if not isinstance(backing, AbstractRecordTable) or on_condition is None:
        return None
    tdef = table.definition
    table_ids = {table_ref, tdef.id}

    def classify(var):
        if var.stream_id in table_ids:
            if var.attribute not in tdef.attribute_names:
                return "bail"
            return ("attribute", var.attribute)
        if var.stream_id is None and var.attribute in tdef.attribute_names:
            return "bail"      # ambiguous bare ref: no pushdown, host decides
        return "param"

    def build_param(expr):
        try:
            fn, _ = builder.build(expr)
        except Exception:       # noqa: BLE001
            return None
        return fn

    node, params = build_store_tree(on_condition, classify, build_param)
    if node is None:
        return None
    compiled = backing.record_compile_condition(StoreExpression(node))
    if compiled is None:
        return None
    from .event import StreamEvent as _SE
    from .executor import JoinFrame as _JF

    def find(probe_ev, t=backing, left=table_is_left):
        frame = _JF(None, probe_ev, probe_ev.timestamp) if left \
            else _JF(probe_ev, None, probe_ev.timestamp)
        p = {name: fn(frame) for name, fn in params.items()}
        return [_SE(probe_ev.timestamp, r) for r in t.record_find(p, compiled)]

    return find


def _table_pushdown_find(table, table_ref, table_is_left, on_condition, builder):
    """Compile ``T.pk == <probe expr>`` into a point lookup fn(probe_ev),
    or None if the condition has no such conjunct (falls back to scan)."""
    if on_condition is None or not hasattr(table, "pk_lookup"):
        return None
    pk_positions = getattr(table, "pk_positions", [])
    if len(pk_positions) != 1:
        return None
    pk_name = table.definition.attributes[pk_positions[0]].name
    table_ids = {table_ref, table.definition.id}
    rhs = _find_join_pk_rhs(on_condition, table_ids, pk_name,
                            table.definition.attribute_names)
    if rhs is None:
        return None
    try:
        val_fn, _ = builder.build(rhs)
    except Exception:
        return None
    from .event import StreamEvent as _SE
    from .executor import JoinFrame as _JF

    def find(probe_ev, t=table, left=table_is_left):
        frame = _JF(None, probe_ev, probe_ev.timestamp) if left \
            else _JF(probe_ev, None, probe_ev.timestamp)
        return [_SE(probe_ev.timestamp, r) for r in t.pk_lookup(val_fn(frame))]

    return find


def _find_join_pk_rhs(expr, table_ids, pk_name, table_attr_names):
    from ..query_api import And, Compare, CompareOp, Variable
    if isinstance(expr, And):
        return _find_join_pk_rhs(expr.left, table_ids, pk_name, table_attr_names) \
            or _find_join_pk_rhs(expr.right, table_ids, pk_name, table_attr_names)
    if isinstance(expr, Compare) and expr.op == CompareOp.EQ:
        for a, b in ((expr.left, expr.right), (expr.right, expr.left)):
            if isinstance(a, Variable) and a.attribute == pk_name \
                    and a.stream_id in table_ids \
                    and not _expr_touches_table(b, table_ids, table_attr_names):
                return b
    return None


def _expr_touches_table(expr, table_ids, table_attr_names):
    from ..query_api import AttributeFunction, Expression, Variable
    if isinstance(expr, Variable):
        return expr.stream_id in table_ids or \
            (expr.stream_id is None and expr.attribute in table_attr_names)
    for attr in ("left", "right", "expr"):
        sub = getattr(expr, attr, None)
        if isinstance(sub, Expression) and \
                _expr_touches_table(sub, table_ids, table_attr_names):
            return True
    if isinstance(expr, AttributeFunction):
        return any(_expr_touches_table(a, table_ids, table_attr_names)
                   for a in expr.args)
    return False


def _build_join(ist: JoinInputStream, rt: QueryRuntime, app_context,
                stream_defs: dict, stream_def_fn, query: Query, qid: str):
    sides = {}
    for label, s in (("left", ist.left), ("right", ist.right)):
        sid = s.stream_id
        if sid in app_context.aggregations:
            agg = app_context.aggregations[sid]
            if ist.per is None:
                raise QueryBuildError(
                    "aggregation join needs `per '<granularity>'`")
            from ..query_api import Constant as _Const
            w = ist.within
            dynamic = not isinstance(ist.per, _Const) or (
                isinstance(w, tuple) and not all(
                    isinstance(x, _Const) for x in w)) or (
                w is not None and not isinstance(w, tuple)
                and not isinstance(w, _Const))
            if dynamic:
                # per/within read from the DRIVING event's attributes
                # (reference Aggregation1TestCase test6: `within i.startTime,
                # i.endTime per i.perValue`) — resolved per probe in the
                # post-pass below, once both sides' schemas exist
                sides[label] = {
                    "kind": "aggregation", "def": agg.output_definition,
                    "ref": s.ref(), "find": None, "stream": s, "agg": agg,
                    "dynamic": True,
                }
                continue
            from .errors import SiddhiAppRuntimeError
            try:
                duration = agg.duration_for(ist.per.value)
            except SiddhiAppRuntimeError as e:
                raise QueryBuildError(str(e)) from None
            start = end = None
            if isinstance(w, tuple):
                start, end = _within_bound(w[0]), _within_bound(w[1])
            elif w is not None:
                from .aggregation import parse_within_single
                try:
                    start, end = parse_within_single(getattr(w, "value", None))
                except (ValueError, SiddhiAppRuntimeError) as e:
                    raise QueryBuildError(str(e)) from None
            def agg_find(agg=agg, duration=duration, start=start, end=end):
                from .event import StreamEvent as _SE
                return [_SE(r[0], r) for r in agg.rows_for(duration, start, end)]
            sides[label] = {
                "kind": "aggregation", "def": agg.output_definition,
                "ref": s.ref(), "find": agg_find, "stream": s,
            }
        elif sid in app_context.tables:
            table = app_context.tables[sid]
            sides[label] = {
                "kind": "table", "def": table.definition, "ref": s.ref(),
                "find": (lambda t=table: t.all_events()), "stream": s,
            }
        elif sid in app_context.named_windows:
            nw = app_context.named_windows[sid]
            sides[label] = {
                "kind": "window", "def": nw.definition, "ref": s.ref(),
                "find": nw.find_events, "stream": s,
            }
        else:
            d = stream_def_fn(sid, s.is_inner_stream)
            head, tail, eff_def, win = build_single_chain(s, d, app_context, qid)
            if win is None:
                win = W.EmptyWindow()
                win.setup(app_context, app_context.element_id(f"{qid}-joinwin"))
                tail = tail.set_next(win)
            sides[label] = {
                "kind": "stream", "def": eff_def, "ref": s.ref(),
                "find": win.find_events, "stream": s, "head": head, "tail": tail,
            }

    resolver = JoinResolver(sides["left"]["ref"], sides["left"]["def"],
                            sides["right"]["ref"], sides["right"]["def"])
    builder = ExecutorBuilder(resolver, app_context)
    cond_fn = None
    if ist.on_condition is not None:
        cond_fn, _ = builder.build(ist.on_condition)

    # dynamic aggregation sides: compile per/within executors over the
    # joined frame (the probe event rides its own side; the aggregation side
    # of the frame stays None) and rebuild the rollup row-set per probe
    for label, is_left in (("left", True), ("right", False)):
        side = sides[label]
        if not side.get("dynamic"):
            continue
        from ..query_api import Constant as _Const
        from .aggregation import parse_within_single, parse_within_value
        from .errors import SiddhiAppRuntimeError
        from .event import StreamEvent as _SE
        from .executor import JoinFrame as _JF

        def _valfn(e):
            if isinstance(e, _Const):
                v = e.value
                return lambda fr, v=v: v
            fn, _t = builder.build(e)
            return fn

        agg = side["agg"]
        per_fn = _valfn(ist.per)
        w = ist.within
        if isinstance(w, tuple):
            w_fns = (_valfn(w[0]), _valfn(w[1]))
            w_single = None
        elif w is not None:
            w_fns = None
            w_single = _valfn(w)
        else:
            w_fns = w_single = None
        probe_is_left = not is_left     # the driving event is the other side

        def agg_find(probe_ev=None, agg=agg, per_fn=per_fn, w_fns=w_fns,
                     w_single=w_single, probe_is_left=probe_is_left):
            ts = probe_ev.timestamp if probe_ev is not None else 0
            fr = _JF(probe_ev if probe_is_left else None,
                     None if probe_is_left else probe_ev, ts)
            try:
                duration = agg.duration_for(per_fn(fr))
                if w_fns is not None:
                    start = parse_within_value(w_fns[0](fr))
                    end = parse_within_value(w_fns[1](fr))
                elif w_single is not None:
                    start, end = parse_within_single(w_single(fr))
                else:
                    start = end = None
            except ValueError as e:
                raise SiddhiAppRuntimeError(str(e)) from None
            return [_SE(r[0], r) for r in agg.rows_for(duration, start, end)]

        side["find"] = agg_find

    within_ms = None
    if ist.per is None and ist.within is not None:
        from ..query_api import Constant as _Const
        if isinstance(ist.within, tuple) or not isinstance(ist.within, _Const):
            raise QueryBuildError(
                "stream join `within` takes a single time constant "
                "(range/expression forms apply to aggregation joins with `per`)")
        within_ms = ist.within.value
    # finds take the probing event; table sides push a PK point-lookup down
    # (reference: OperatorParser.java:64 compiles `T.pk == probe.expr` into an
    # IndexOperator instead of an exhaustive scan)
    finds = {}
    for label, is_left in (("left", True), ("right", False)):
        side = sides[label]
        fn = None
        if side["kind"] == "table":
            table = app_context.tables[side["stream"].stream_id]
            fn = _table_pushdown_find(table, side["ref"], is_left,
                                      ist.on_condition, builder)
            if fn is None:
                fn = _record_store_find(table, side["ref"], is_left,
                                        ist.on_condition, builder)
            if fn is None:
                # scan fallback stamps rows with the probe's timestamp, same
                # as the pushdown path, so `within` sees consistent times
                fn = lambda probe_ev=None, t=table: t.all_events(  # noqa: E731
                    probe_ev.timestamp if probe_ev is not None else 0)
        if fn is None:
            if side.get("dynamic"):
                fn = side["find"]          # per-probe per/within resolution
            else:
                fn = lambda probe_ev=None, f=side["find"]: f()  # noqa: E731
        finds[label] = fn
    jr = JoinRuntime(ist.join_type, ist.trigger, cond_fn,
                     finds["left"], finds["right"], within_ms)

    # selector over the combined schema
    names = (sides["left"]["def"].attribute_names
             + [n for n in sides["right"]["def"].attribute_names
                if n not in sides["left"]["def"].attribute_names])
    types = []
    for n in names:
        d = sides["left"]["def"] if n in sides["left"]["def"].attribute_names \
            else sides["right"]["def"]
        types.append(d.attribute_type(n))
    selector = build_selector(query.selector, builder, names, types,
                              app_context.element_id(f"{qid}-selector"))
    app_context.register_state(selector.element_id, selector)
    jr.next = selector

    for label, is_left in (("left", True), ("right", False)):
        side = sides[label]
        if side["kind"] == "stream":
            from .debugger import DebuggedReceiver
            side["tail"].set_next(JoinSide(jr, is_left))
            rt.subscriptions.append((side["stream"].stream_id, ObservedReceiver(
                DebuggedReceiver(StreamReceiver(side["head"]), rt.name,
                                 app_context),
                app_context, rt.name,
                getattr(rt, "metric_name", rt.name))))
        elif side["kind"] == "window":
            nw = app_context.named_windows[side["stream"].stream_id]
            bridge = _ChainHead()
            bridge.set_next(JoinSide(jr, is_left))
            nw.subscribe(StreamReceiver(bridge))
        # table sides are passive: probed only
    return selector
