"""Snapshot service & persistence stores — checkpoint/restore.

Reference: ``core/util/snapshot/SnapshotService.java`` (fullSnapshot:90,
incrementalSnapshot:189, restore:333), ``util/snapshot/IncrementalSnapshot.java``,
``util/persistence/`` (in-memory + filesystem stores, incremental variants,
revisions), op-log window buffers
``event/stream/holder/SnapshotableStreamEventQueue.java:37``.
Design: every stateful element registered in ``app_context.state_registry``
exposes ``snapshot_state() -> dict`` / ``restore_state(dict)``; a full snapshot is
the pickled map of all of them, taken under the app's root lock (the reference's
ThreadBarrier quiesce). Incremental snapshots record, per element, either an
op-log since the last snapshot (elements exposing ``incremental_snapshot_state``
/ ``apply_increment``), a skip marker (state digest unchanged), or a fresh full
state. A revision chain is [base, inc, inc, ...] with periodic full baselines.
On the TPU path the same protocol serializes device pytrees fetched with
``jax.device_get``.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
from typing import Any, Optional

from .event import StreamEvent


class SnapshotableEventBuffer:
    """Event buffer with operation-log snapshotting.

    Reference: ``SnapshotableStreamEventQueue.java:37`` — windows buffer events
    here; a full snapshot captures the whole buffer and starts a fresh op-log;
    an incremental snapshot returns only the operations since the previous
    snapshot. If the op-log outgrows the buffer, it is abandoned and the next
    incremental snapshot falls back to a full capture (same as the reference's
    forceFullSnapshot).
    """

    def __init__(self, max_oplog: int = 4096):
        self.items: list[StreamEvent] = []
        self._oplog: list[tuple] = []
        self._baseline = False           # a snapshot exists to diff against
        self.max_oplog = max_oplog

    # -- list-ish API used by windows -----------------------------------------
    def append(self, ev: StreamEvent) -> None:
        self.items.append(ev)
        self._record(("a", ev.timestamp, list(ev.data), ev.type))

    def popleft(self) -> StreamEvent:
        ev = self.items.pop(0)
        self._record(("p",))
        return ev

    def clear(self) -> None:
        self.items = []
        self._record(("c",))

    def __len__(self) -> int:
        return len(self.items)

    def __iter__(self):
        return iter(self.items)

    def __getitem__(self, i):
        return self.items[i]

    def _record(self, op: tuple) -> None:
        if not self._baseline:
            return
        self._oplog.append(op)
        if len(self._oplog) > self.max_oplog:
            self._oplog = []
            self._baseline = False       # force full on next snapshot

    # -- snapshot protocol -----------------------------------------------------
    def capture(self) -> list[tuple]:
        """Pure full capture — does NOT touch the op-log (plain snapshots
        must not disturb an in-flight incremental chain)."""
        return [(e.timestamp, list(e.data), e.type) for e in self.items]

    def begin_oplog(self) -> None:
        """Start a fresh op-log: the current contents are the new baseline."""
        self._oplog = []
        self._baseline = True

    def full_snapshot(self) -> list[tuple]:
        self.begin_oplog()
        return self.capture()

    def incremental_snapshot(self) -> Optional[list[tuple]]:
        """Ops since last snapshot, or None if a full capture is needed."""
        if not self._baseline:
            return None
        ops, self._oplog = self._oplog, []
        return ops

    def restore(self, base: list[tuple]) -> None:
        self.items = [StreamEvent(ts, list(d), t) for ts, d, t in base]
        self._oplog = []
        self._baseline = True

    def apply_ops(self, ops: list[tuple]) -> None:
        for op in ops:
            if op[0] == "a":
                self.items.append(StreamEvent(op[1], list(op[2]), op[3]))
            elif op[0] == "p":
                self.items.pop(0)
            elif op[0] == "c":
                self.items = []


class SnapshotService:
    def __init__(self, app_context):
        self.app_context = app_context
        self._digests: dict[str, bytes] = {}    # element -> last state digest

    # -- collection ------------------------------------------------------------
    # collect_* return plain dicts (one pickle at the persist layer); the
    # plain-full path is PURE — it must not disturb an incremental chain.
    def collect_full(self, update_baseline: bool = False) -> dict:
        with self.app_context.root_lock:
            states = {}
            if update_baseline:
                self._digests = {}
            for element_id, holder in self.app_context.state_registry.items():
                state = holder.snapshot_state()
                states[element_id] = state
                if update_baseline:
                    self._digests[element_id] = self._digest(state)
                    if hasattr(holder, "reset_increment_baseline"):
                        holder.reset_increment_baseline()
            return {
                "app": self.app_context.name,
                "states": states,
                "time": self.app_context.current_time(),
            }

    def collect_incremental(self) -> dict:
        with self.app_context.root_lock:
            states: dict[str, tuple] = {}
            for element_id, holder in self.app_context.state_registry.items():
                if hasattr(holder, "incremental_snapshot_state"):
                    inc = holder.incremental_snapshot_state()
                    if inc is not None:
                        states[element_id] = ("inc", inc)
                        continue
                    states[element_id] = ("full", holder.snapshot_state())
                    if hasattr(holder, "reset_increment_baseline"):
                        holder.reset_increment_baseline()
                    continue
                state = holder.snapshot_state()
                digest = self._digest(state)
                if self._digests.get(element_id) == digest:
                    states[element_id] = ("skip",)
                else:
                    states[element_id] = ("full", state)
                    self._digests[element_id] = digest
            return {
                "app": self.app_context.name,
                "states": states,
                "time": self.app_context.current_time(),
            }

    @staticmethod
    def _digest(state: Any) -> bytes:
        return hashlib.sha1(
            pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)).digest()

    # -- public API ------------------------------------------------------------
    def full_snapshot(self, update_baseline: bool = False) -> bytes:
        return pickle.dumps(self.collect_full(update_baseline))

    def incremental_snapshot(self) -> bytes:
        """Delta since the previous snapshot in the current revision chain
        (reference ``SnapshotService.incrementalSnapshot:189``)."""
        data = self.collect_incremental()
        data["type"] = "increment"
        return pickle.dumps(data)

    def restore(self, blob: bytes) -> None:
        data = pickle.loads(blob)
        if data.get("type") == "increment":
            raise ValueError(
                "cannot restore an increment alone; restore its chain")
        with self.app_context.root_lock:
            # pre-restore digests would otherwise mark unchanged-looking
            # elements as ('skip',) against a baseline that no longer exists
            self._digests = {}
            for element_id, state in data["states"].items():
                holder = self.app_context.state_registry.get(element_id)
                if holder is not None:
                    holder.restore_state(state)
            if self.app_context.timestamp_generator.playback:
                self.app_context.timestamp_generator.advance(data.get("time", 0))

    def restore_chain(self, blobs: list[bytes]) -> None:
        """Restore [base, inc, inc, ...] in order."""
        if not blobs:
            return
        self.restore(blobs[0])
        last = pickle.loads(blobs[0])
        with self.app_context.root_lock:
            for blob in blobs[1:]:
                last = pickle.loads(blob)
                for element_id, entry in last["states"].items():
                    holder = self.app_context.state_registry.get(element_id)
                    if holder is None:
                        continue
                    kind = entry[0]
                    if kind == "skip":
                        continue
                    if kind == "full":
                        holder.restore_state(entry[1])
                    elif kind == "inc":
                        holder.apply_increment(entry[1])
            if self.app_context.timestamp_generator.playback:
                self.app_context.timestamp_generator.advance(last.get("time", 0))


class PersistenceStore:
    def save(self, app_name: str, revision: str, blob: bytes) -> None:
        raise NotImplementedError

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        raise NotImplementedError

    def last_revision(self, app_name: str) -> Optional[str]:
        raise NotImplementedError

    def clear_all_revisions(self, app_name: str) -> None:
        raise NotImplementedError


class InMemoryPersistenceStore(PersistenceStore):
    def __init__(self):
        self._store: dict[str, dict[str, bytes]] = {}

    def save(self, app_name, revision, blob):
        self._store.setdefault(app_name, {})[revision] = blob

    def load(self, app_name, revision):
        return self._store.get(app_name, {}).get(revision)

    def last_revision(self, app_name):
        revs = self._store.get(app_name)
        if not revs:
            return None
        return sorted(revs)[-1]

    def clear_all_revisions(self, app_name):
        self._store.pop(app_name, None)


class FileSystemPersistenceStore(PersistenceStore):
    def __init__(self, base_dir: str):
        self.base_dir = base_dir

    def _dir(self, app_name: str) -> str:
        d = os.path.join(self.base_dir, app_name)
        os.makedirs(d, exist_ok=True)
        return d

    def save(self, app_name, revision, blob):
        with open(os.path.join(self._dir(app_name), revision), "wb") as f:
            f.write(blob)

    def load(self, app_name, revision):
        path = os.path.join(self._dir(app_name), revision)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def last_revision(self, app_name):
        files = sorted(os.listdir(self._dir(app_name)))
        return files[-1] if files else None

    def clear_all_revisions(self, app_name):
        d = self._dir(app_name)
        for f in os.listdir(d):
            os.remove(os.path.join(d, f))


class IncrementalPersistenceStore(InMemoryPersistenceStore):
    """In-memory store for incremental revision chains (reference
    ``util/persistence/IncrementalPersistenceStore.java``). Marker class: a
    PersistenceManager writes increments (with periodic full baselines) when
    the configured store sets ``incremental = True``."""

    incremental = True


class IncrementalFileSystemPersistenceStore(FileSystemPersistenceStore):
    """Filesystem store for incremental revision chains (reference
    ``IncrementalFileSystemPersistenceStore.java:37``)."""

    incremental = True


class PersistenceManager:
    """persist()/restoreRevision()/restoreLastRevision() façade.

    With an incremental store, every ``base_interval``-th persist writes a full
    baseline; others write deltas chained by a ``parent`` pointer (reference:
    periodic full baselines in ``AsyncIncrementalSnapshotPersistor`` flow)."""

    def __init__(self, app_context, snapshot_service: SnapshotService,
                 store: Optional[PersistenceStore]):
        self.app_context = app_context
        self.snapshot_service = snapshot_service
        self.store = store
        self._counter = 0
        self.base_interval = 5
        self._since_base = 0
        self._last_revision: Optional[str] = None

    def persist(self) -> str:
        if self.store is None:
            raise RuntimeError("no persistence store configured")
        self._counter += 1
        revision = f"{int(time.time() * 1000)}_{self._counter:06d}"
        if getattr(self.store, "incremental", False):
            is_base = self._last_revision is None or \
                self._since_base >= self.base_interval
            if is_base:
                data = self.snapshot_service.collect_full(update_baseline=True)
                data["parent"] = None
                self._since_base = 0
            else:
                data = self.snapshot_service.collect_incremental()
                data["type"] = "increment"
                data["parent"] = self._last_revision
                self._since_base += 1
            blob = pickle.dumps(data)
            self._last_revision = revision
        else:
            blob = self.snapshot_service.full_snapshot()
        self.store.save(self.app_context.name, revision, blob)
        return revision

    def invalidate_chain(self) -> None:
        """After any restore, the live state no longer continues the persisted
        chain — the next persist must write a fresh base."""
        self._last_revision = None
        self._since_base = 0

    def restore_revision(self, revision: str) -> None:
        blob = self.store.load(self.app_context.name, revision)
        if blob is None:
            raise KeyError(f"no revision {revision!r}")
        data = pickle.loads(blob)
        if data.get("type") != "increment":
            self.snapshot_service.restore(blob)
            self.invalidate_chain()
            return
        # walk parents back to the base, then apply base→...→revision
        chain = [blob]
        while data.get("type") == "increment":
            parent = data.get("parent")
            if parent is None:
                raise KeyError(f"broken increment chain at {revision!r}")
            blob = self.store.load(self.app_context.name, parent)
            if blob is None:
                raise KeyError(f"missing parent revision {parent!r}")
            chain.insert(0, blob)
            data = pickle.loads(blob)
        self.snapshot_service.restore_chain(chain)
        self.invalidate_chain()

    def restore_last_revision(self) -> Optional[str]:
        rev = self.store.last_revision(self.app_context.name)
        if rev is not None:
            self.restore_revision(rev)
        return rev

    def clear_all_revisions(self) -> None:
        self.store.clear_all_revisions(self.app_context.name)
