"""Snapshot service & persistence stores — checkpoint/restore.

Reference: ``core/util/snapshot/SnapshotService.java`` (fullSnapshot:90,
restore:333), ``util/persistence/`` (in-memory + filesystem stores, revisions).
Design: every stateful element registered in ``app_context.state_registry``
exposes ``snapshot_state() -> dict`` / ``restore_state(dict)``; a full snapshot is
the pickled map of all of them, taken under the app's root lock (the reference's
ThreadBarrier quiesce). On the TPU path the same protocol serializes device
pytrees fetched with ``jax.device_get``.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Optional


class SnapshotService:
    def __init__(self, app_context):
        self.app_context = app_context

    def full_snapshot(self) -> bytes:
        with self.app_context.root_lock:
            states = {}
            for element_id, holder in self.app_context.state_registry.items():
                states[element_id] = holder.snapshot_state()
            return pickle.dumps({
                "app": self.app_context.name,
                "states": states,
                "time": self.app_context.current_time(),
            })

    def restore(self, blob: bytes) -> None:
        data = pickle.loads(blob)
        with self.app_context.root_lock:
            for element_id, state in data["states"].items():
                holder = self.app_context.state_registry.get(element_id)
                if holder is not None:
                    holder.restore_state(state)
            if self.app_context.timestamp_generator.playback:
                self.app_context.timestamp_generator.advance(data.get("time", 0))


class PersistenceStore:
    def save(self, app_name: str, revision: str, blob: bytes) -> None:
        raise NotImplementedError

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        raise NotImplementedError

    def last_revision(self, app_name: str) -> Optional[str]:
        raise NotImplementedError

    def clear_all_revisions(self, app_name: str) -> None:
        raise NotImplementedError


class InMemoryPersistenceStore(PersistenceStore):
    def __init__(self):
        self._store: dict[str, dict[str, bytes]] = {}

    def save(self, app_name, revision, blob):
        self._store.setdefault(app_name, {})[revision] = blob

    def load(self, app_name, revision):
        return self._store.get(app_name, {}).get(revision)

    def last_revision(self, app_name):
        revs = self._store.get(app_name)
        if not revs:
            return None
        return sorted(revs)[-1]

    def clear_all_revisions(self, app_name):
        self._store.pop(app_name, None)


class FileSystemPersistenceStore(PersistenceStore):
    def __init__(self, base_dir: str):
        self.base_dir = base_dir

    def _dir(self, app_name: str) -> str:
        d = os.path.join(self.base_dir, app_name)
        os.makedirs(d, exist_ok=True)
        return d

    def save(self, app_name, revision, blob):
        with open(os.path.join(self._dir(app_name), revision), "wb") as f:
            f.write(blob)

    def load(self, app_name, revision):
        path = os.path.join(self._dir(app_name), revision)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def last_revision(self, app_name):
        files = sorted(os.listdir(self._dir(app_name)))
        return files[-1] if files else None

    def clear_all_revisions(self, app_name):
        d = self._dir(app_name)
        for f in os.listdir(d):
            os.remove(os.path.join(d, f))


class PersistenceManager:
    """persist()/restoreRevision()/restoreLastRevision() façade."""

    def __init__(self, app_context, snapshot_service: SnapshotService,
                 store: Optional[PersistenceStore]):
        self.app_context = app_context
        self.snapshot_service = snapshot_service
        self.store = store
        self._counter = 0

    def persist(self) -> str:
        if self.store is None:
            raise RuntimeError("no persistence store configured")
        self._counter += 1
        revision = f"{int(time.time() * 1000)}_{self._counter:06d}"
        blob = self.snapshot_service.full_snapshot()
        self.store.save(self.app_context.name, revision, blob)
        return revision

    def restore_revision(self, revision: str) -> None:
        blob = self.store.load(self.app_context.name, revision)
        if blob is None:
            raise KeyError(f"no revision {revision!r}")
        self.snapshot_service.restore(blob)

    def restore_last_revision(self) -> Optional[str]:
        rev = self.store.last_revision(self.app_context.name)
        if rev is not None:
            self.restore_revision(rev)
        return rev

    def clear_all_revisions(self) -> None:
        self.store.clear_all_revisions(self.app_context.name)
