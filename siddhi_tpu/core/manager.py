"""SiddhiManager: engine façade.

Reference: ``core/SiddhiManager.java`` — extension registry, persistence store,
app lifecycle, ``createSiddhiAppRuntime`` (parse → build).
"""

from __future__ import annotations

from typing import Optional, Union

from ..compiler import parse as _parse, update_variables
from ..query_api import SiddhiApp
from .app_runtime import SiddhiAppRuntime
from .context import SiddhiContext
from .errors import ErrorStore
from .extension import GLOBAL_EXTENSIONS
from .snapshot import PersistenceStore


class SiddhiManager:
    def __init__(self):
        self.context = SiddhiContext()
        self.context.extensions.update(GLOBAL_EXTENSIONS)
        self.context.error_store = ErrorStore()
        self.runtimes: dict[str, SiddhiAppRuntime] = {}

    def create_siddhi_app_runtime(
            self, app: Union[str, SiddhiApp],
            playback: Optional[bool] = None,
            start_time: int = 0,
            env: Optional[dict] = None) -> SiddhiAppRuntime:
        if isinstance(app, str):
            app = _parse(update_variables(
                app, env, self.context.config_manager) if "${" in app else app)
        runtime = SiddhiAppRuntime(app, self.context, playback, start_time)
        self.runtimes[runtime.name] = runtime
        return runtime

    # reference-style alias
    createSiddhiAppRuntime = create_siddhi_app_runtime

    def create_sandbox_siddhi_app_runtime(
            self, app: Union[str, SiddhiApp],
            playback: Optional[bool] = None,
            start_time: int = 0) -> SiddhiAppRuntime:
        """Runs the app WITHOUT its external sources/sinks/stores (reference
        ``SiddhiManager.createSandboxSiddhiAppRuntime:105`` — non-inMemory
        @source/@sink annotations and every @store are stripped, so the app
        can be driven by input handlers/callbacks in isolation)."""
        if isinstance(app, str):
            app = _parse(update_variables(app, None, self.context.config_manager)
                         if "${" in app else app)
        for sd in app.stream_definitions.values():
            sd.annotations = [
                a for a in sd.annotations
                if a.name.lower() not in ("source", "sink")
                or (a.get("type") or "").lower() == "inmemory"]
        for td in app.table_definitions.values():
            td.annotations = [a for a in td.annotations
                              if a.name.lower() != "store"]
        return self.create_siddhi_app_runtime(app, playback, start_time)

    createSandboxSiddhiAppRuntime = create_sandbox_siddhi_app_runtime

    def validate_siddhi_app(self, app: Union[str, SiddhiApp]) -> None:
        """Full validation: parse + build the runtime, then discard it
        (reference ``SiddhiManager.validateSiddhiApp:145`` does exactly
        this — creation IS the validator). Raises on any invalid app."""
        if isinstance(app, str):
            app = _parse(update_variables(app, None, self.context.config_manager)
                         if "${" in app else app)
        runtime = SiddhiAppRuntime(app, self.context, playback=True)
        runtime.shutdown()

    validateSiddhiApp = validate_siddhi_app

    # -- multi-tenant fleet (shared compilation / cross-app lane batching) --
    @property
    def fleet(self):
        """The engine's :class:`~siddhi_tpu.fleet.FleetManager` (created on
        first use): shared plan cache stats, live groups, admission
        config — the cross-app face of ``@app:fleet``."""
        return self.context.fleet()

    # -- engine-level attribute map (reference get/setAttributes) -----------
    def get_attributes(self) -> dict:
        return self.context.attributes

    def set_attribute(self, key: str, value) -> None:
        self.context.attributes[key] = value

    def get_extensions(self) -> dict:
        return dict(self.context.extensions)

    def remove_extension(self, name: str) -> None:
        self.context.extensions.pop(name, None)

    def set_error_store(self, store) -> None:
        """Reference ``SiddhiManager.setErrorStore`` — replayable store for
        events that failed with OnErrorAction.STORE or a sink STORE policy.
        Pass a :class:`~siddhi_tpu.core.errors.FileErrorStore` for entries
        that survive restarts."""
        self.context.error_store = store

    def replay_errors(self, app_name: str, stream_name: Optional[str] = None,
                      min_id: Optional[int] = None,
                      max_id: Optional[int] = None) -> dict:
        """Re-inject stored failed events for one app (occurrence-aware:
        stream failures re-enter through the ``InputHandler``, sink failures
        re-publish through the sink pipeline). Returns the replay report."""
        rt = self.runtimes.get(app_name)
        if rt is None:
            raise KeyError(f"no app '{app_name}' running")
        store = self.context.error_store
        if store is None:
            raise ValueError("no error store configured")
        return store.replay(rt, stream_name, min_id, max_id)

    # -- engine-wide persistence (reference persist()/restoreLastState()) ---
    def persist(self) -> dict:
        """Persist every running app; returns {app name: revision}."""
        return {name: rt.persist() for name, rt in self.runtimes.items()}

    def restore_last_state(self) -> None:
        for rt in self.runtimes.values():
            rt.restore_last_revision()

    restoreLastState = restore_last_state

    def set_extension(self, name: str, cls: type) -> None:
        self.context.extensions[name] = cls

    def set_config_manager(self, config_manager) -> None:
        """Reference ``SiddhiManager.setConfigManager`` (ConfigManager SPI)."""
        self.context.config_manager = config_manager

    def set_source_handler_manager(self, manager) -> None:
        """Reference ``SiddhiManager.setSourceHandlerManager`` — every source
        wired after this routes mapped rows through a generated
        :class:`~siddhi_tpu.core.io.SourceHandler`."""
        self.context.source_handler_manager = manager

    def set_sink_handler_manager(self, manager) -> None:
        """Reference ``SiddhiManager.setSinkHandlerManager``."""
        self.context.sink_handler_manager = manager

    def set_record_table_handler_manager(self, manager) -> None:
        """Reference ``SiddhiManager.setRecordTableHandlerManager`` — every
        record-store table built after this routes its ops through a
        generated :class:`~siddhi_tpu.core.table.RecordTableHandler`."""
        self.context.record_table_handler_manager = manager

    def set_persistence_store(self, store: PersistenceStore) -> None:
        self.context.persistence_store = store
        for rt in self.runtimes.values():
            rt.persistence.store = store

    def get_siddhi_app_runtime(self, name: str) -> Optional[SiddhiAppRuntime]:
        return self.runtimes.get(name)

    def shutdown(self) -> None:
        for rt in self.runtimes.values():
            rt.shutdown()
        self.runtimes.clear()
