"""Output callbacks: route selector output to streams, tables, windows, callbacks.

Reference: ``core/query/output/callback/`` — ``InsertIntoStreamCallback``,
``InsertIntoTableCallback``, ``Update/Delete/UpdateOrInsertTableCallback``.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..query_api import OutputEventsFor
from .event import Event, EventType, StreamEvent


def _allowed(ev: StreamEvent, events_for: OutputEventsFor) -> bool:
    if ev.type == EventType.CURRENT:
        return events_for in (OutputEventsFor.CURRENT_EVENTS, OutputEventsFor.ALL_EVENTS)
    if ev.type == EventType.EXPIRED:
        return events_for in (OutputEventsFor.EXPIRED_EVENTS, OutputEventsFor.ALL_EVENTS)
    return False


class InsertIntoStreamCallback:
    """Forwards selected events into a target junction as CURRENT events."""

    def __init__(self, junction, events_for: OutputEventsFor):
        self.junction = junction
        self.events_for = events_for

    def process(self, events: list[StreamEvent]) -> None:
        for ev in events:
            if _allowed(ev, self.events_for):
                self.junction.send_event(
                    StreamEvent(ev.timestamp, list(ev.data), EventType.CURRENT))


class InsertIntoWindowCallback:
    def __init__(self, window, events_for: OutputEventsFor):
        self.window = window
        self.events_for = events_for

    def process(self, events: list[StreamEvent]) -> None:
        for ev in events:
            if _allowed(ev, self.events_for):
                self.window.add(
                    StreamEvent(ev.timestamp, list(ev.data), EventType.CURRENT))


class InsertIntoTableCallback:
    def __init__(self, table, events_for: OutputEventsFor):
        self.table = table
        self.events_for = events_for

    def process(self, events: list[StreamEvent]) -> None:
        rows = [list(ev.data) for ev in events if _allowed(ev, self.events_for)]
        if rows:
            self.table.add(rows, events[-1].timestamp)


class DeleteTableCallback:
    def __init__(self, table, condition):
        self.table = table
        self.condition = condition

    def process(self, events: list[StreamEvent]) -> None:
        for ev in events:
            if ev.type == EventType.CURRENT:
                self.table.delete(self.condition, ev.data, ev.timestamp)


class UpdateTableCallback:
    def __init__(self, table, condition, setters):
        self.table = table
        self.condition = condition
        self.setters = setters

    def process(self, events: list[StreamEvent]) -> None:
        for ev in events:
            if ev.type == EventType.CURRENT:
                self.table.update(self.condition, ev.data, self.setters, ev.timestamp)


class UpdateOrInsertTableCallback:
    def __init__(self, table, condition, setters):
        self.table = table
        self.condition = condition
        self.setters = setters

    def process(self, events: list[StreamEvent]) -> None:
        for ev in events:
            if ev.type == EventType.CURRENT:
                self.table.update_or_add(self.condition, ev.data, self.setters,
                                         ev.timestamp)


class QueryCallbackAdapter:
    """Terminal: delivers chunks to a user QueryCallback as (ts, current, expired)."""

    def __init__(self):
        self.callbacks: list = []

    def process(self, events: list[StreamEvent]) -> None:
        if not self.callbacks:
            return
        currents = [Event(e.timestamp, e.data) for e in events
                    if e.type == EventType.CURRENT]
        expireds = [Event(e.timestamp, e.data, True) for e in events
                    if e.type == EventType.EXPIRED]
        ts = events[-1].timestamp if events else 0
        for cb in self.callbacks:
            cb.receive(ts, currents or None, expireds or None)


class FanoutProcessor:
    """Sends the selector output to multiple downstream consumers."""

    def __init__(self, targets: list):
        self.targets = targets

    def process(self, events: list[StreamEvent]) -> None:
        for t in self.targets:
            t.process(events)
