"""NFA pattern & sequence engine (host interpreter).

Reference: ``core/query/input/stream/state/`` — ``StreamPreStateProcessor`` (pending
partial-match lists, ``processAndReturn:364``), ``StreamPostStateProcessor`` (NFA
advance), ``LogicalPreStateProcessor`` (and/or), ``CountPreStateProcessor`` (<m:n>),
``AbsentStreamPreStateProcessor`` (scheduler-driven non-occurrence), plus the
``every`` re-seeding protocol (``addEveryState``). Redesigned: the state-element tree
compiles to a flat list of ``StateNode``s; partial matches are ``StateEvent``s held
in per-node pending lists; events are applied to nodes in reverse order so one event
cannot advance a single partial through two states. This interpreter is the
semantic oracle for the vectorized TPU NFA (``siddhi_tpu/tpu/nfa.py``).

Semantics notes (matching the reference):
- PATTERN = skip-till-any-match between states; SEQUENCE = strict continuity (any
  event on the pattern's streams that cannot extend a partial kills it).
- ``every`` scope re-seeds when its last node advances, cloning the advancing
  partial minus the scope's own bindings.
- ``<m:n>`` counting accumulates in place; at ``min`` occurrences the same partial
  becomes eligible at the successor node (shared reference, not a copy).
- ``within`` drops partials whose candidate event is too late vs. the first bound
  event (stream-level) or the previous element's bind time (element-level).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..query_api import (
    AbsentStreamStateElement,
    CountStateElement,
    EveryStateElement,
    LogicalStateElement,
    LogicalType,
    NextStateElement,
    StateElement,
    StateInputStream,
    StateInputStreamType,
    StreamStateElement,
)
from .event import EventType, PatternEvent, StateEvent, StreamEvent
from .executor import ExecutorBuilder, StateFrame, StateResolver


@dataclass
class Branch:
    stream_id: str
    alias: str
    filter_fn: Optional[Callable] = None     # built after alias map known
    is_absent: bool = False


@dataclass
class StateNode:
    index: int
    kind: str                                 # 'stream' | 'logical' | 'count' | 'absent'
    branches: list[Branch] = field(default_factory=list)
    logical_type: Optional[LogicalType] = None
    min_count: int = 1
    max_count: int = 1                        # -1 = unbounded
    waiting_time_ms: Optional[int] = None     # absent `for`
    within_ms: Optional[int] = None           # element-level within
    reseed_to: Optional[int] = None           # every-scope start (on this node's advance)
    reseed_aliases: list[str] = field(default_factory=list)   # aliases to clear on reseed

    @property
    def is_count(self) -> bool:
        return self.kind == "count"


class PatternCompiler:
    """State-element tree → flat StateNode list + alias→definition map."""

    def __init__(self, state_stream: StateInputStream, stream_defs: dict):
        self.state_stream = state_stream
        self.stream_defs = stream_defs
        self.nodes: list[StateNode] = []
        self.alias_defs: dict[str, Any] = {}
        self.alias_is_list: dict[str, bool] = {}
        self._auto = itertools.count()
        self._filters: list[tuple[Branch, Any]] = []   # (branch, filter AST)

    def compile(self) -> "CompiledPattern":
        self._flatten(self.state_stream.state)
        # build filter executors now that every alias is known
        for branch, filter_ast in self._filters:
            if filter_ast is None:
                continue
            resolver = StateResolver(self.alias_defs, default_alias=branch.alias)
            builder = ExecutorBuilder(resolver)
            branch.filter_fn, _ = builder.build(filter_ast)
        within = None
        if self.state_stream.within is not None:
            within = self.state_stream.within.value
        return CompiledPattern(
            nodes=self.nodes,
            alias_defs=self.alias_defs,
            alias_is_list=self.alias_is_list,
            within_ms=within,
            is_sequence=self.state_stream.type == StateInputStreamType.SEQUENCE,
        )

    # -- flattening -----------------------------------------------------------
    def _flatten(self, el: StateElement) -> tuple[int, int]:
        """Returns (first_node_index, last_node_index) of the flattened element."""
        if isinstance(el, NextStateElement):
            first, _ = self._flatten(el.first)
            _, last = self._flatten(el.next)
            return first, last
        if isinstance(el, EveryStateElement):
            start = len(self.nodes)
            first, last = self._flatten(el.inner)
            node = self.nodes[last]
            node.reseed_to = first
            node.reseed_aliases = [
                b.alias for n in self.nodes[first:last + 1] for b in n.branches
            ]
            if el.within is not None:
                for n in self.nodes[first:last + 1]:
                    n.within_ms = el.within.value
            return first, last
        if isinstance(el, StreamStateElement):
            node = self._new_node("stream")
            node.branches.append(self._branch(el.stream))
            if el.within is not None:
                node.within_ms = el.within.value
            return node.index, node.index
        if isinstance(el, CountStateElement):
            node = self._new_node("count")
            node.branches.append(self._branch(el.stream.stream))
            node.min_count = el.min_count
            node.max_count = el.max_count
            self.alias_is_list[node.branches[0].alias] = True
            if el.within is not None:
                node.within_ms = el.within.value
            return node.index, node.index
        if isinstance(el, LogicalStateElement):
            node = self._new_node("logical")
            node.logical_type = el.type
            for sub in (el.first, el.second):
                if isinstance(sub, AbsentStreamStateElement):
                    b = self._branch(sub.stream)
                    b.is_absent = True
                    node.branches.append(b)
                    if sub.waiting_time_ms is not None:
                        node.waiting_time_ms = sub.waiting_time_ms
                else:
                    node.branches.append(self._branch(sub.stream))
            if el.within is not None:
                node.within_ms = el.within.value
            return node.index, node.index
        if isinstance(el, AbsentStreamStateElement):
            node = self._new_node("absent")
            b = self._branch(el.stream)
            b.is_absent = True
            node.branches.append(b)
            node.waiting_time_ms = el.waiting_time_ms
            if el.within is not None:
                node.within_ms = el.within.value
            return node.index, node.index
        raise ValueError(f"unsupported state element {el!r}")

    def _new_node(self, kind: str) -> StateNode:
        node = StateNode(index=len(self.nodes), kind=kind)
        self.nodes.append(node)
        return node

    def _branch(self, stream) -> Branch:
        sid = stream.stream_id
        if sid not in self.stream_defs:
            raise KeyError(f"pattern references undefined stream '{sid}'")
        alias = stream.alias or f"${next(self._auto)}"
        if alias in self.alias_defs and stream.alias is not None:
            raise ValueError(f"duplicate pattern alias '{alias}'")
        self.alias_defs[alias] = self.stream_defs[sid]
        filter_ast = None
        from ..query_api import And as _And, Filter as _F
        for h in stream.handlers:
            if isinstance(h, _F):
                filter_ast = h.expr if filter_ast is None else _And(filter_ast, h.expr)
            else:
                # loud, not silent: windows / stream functions on pattern
                # stream elements aren't modelled by this NFA (reference
                # allows them via SingleInputStreamParser.java:83)
                raise ValueError(
                    f"pattern stream '{sid}': handler {type(h).__name__} "
                    f"is not supported inside pattern/sequence elements")
        b = Branch(stream_id=sid, alias=alias)
        self._filters.append((b, filter_ast))
        return b


@dataclass
class CompiledPattern:
    nodes: list[StateNode]
    alias_defs: dict[str, Any]
    alias_is_list: dict[str, bool]
    within_ms: Optional[int]
    is_sequence: bool

    @property
    def stream_ids(self) -> list[str]:
        seen, out = set(), []
        for n in self.nodes:
            for b in n.branches:
                if b.stream_id not in seen:
                    seen.add(b.stream_id)
                    out.append(b.stream_id)
        return out


class PatternRuntime:
    """Executes a CompiledPattern; emits PatternEvents to ``self.next``."""

    def __init__(self, compiled: CompiledPattern, app_context, element_id: str):
        self.c = compiled
        self.app_context = app_context
        self.element_id = element_id
        self.pending: list[list[StateEvent]] = [[] for _ in compiled.nodes]
        self.next = None      # selector
        self.started = False
        self._created: set[int] = set()   # ids of partials placed this event
        app_context.register_state(element_id, self)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self.started:
            return
        self.started = True
        seed = StateEvent()
        self._place(0, seed, self.app_context.current_time())

    def _place(self, node_idx: int, p: StateEvent, now: int) -> None:
        """Put a partial at a node, handling absent timers and zero-min counts."""
        if node_idx >= len(self.c.nodes):
            self._emit(p, now)
            return
        node = self.c.nodes[node_idx]
        self.pending[node_idx].append(p)
        self._created.add(id(p))
        if node.kind in ("absent", "logical") and \
                node.waiting_time_ms is not None:
            # absent nodes AND logical nodes with an `... for t` side start
            # their non-occurrence clock on arrival at the state
            arrival_key = f"absent_arrival_{node.index}"
            p.meta[arrival_key] = now
            fire_at = now + node.waiting_time_ms
            self.app_context.scheduler.notify_at(
                fire_at, lambda ts, ni=node_idx, pp=p: self._absent_timer(ni, pp, ts))
        if node.is_count and node.min_count == 0:
            if node_idx == len(self.c.nodes) - 1:
                # final zero-min count: a partial ARRIVING here with earlier
                # bindings is already complete (reference emits immediately
                # with the count empty; SequenceTestCase.testQuery3). A bare
                # seed stays pending — emitting it would recurse through the
                # every-reseed forever with no event driving it.
                if p.events:
                    self._emit_from(node, p, now)
                    self._remove_everywhere(p)
                return
            # zero occurrences allowed: immediately eligible at the successor
            self._make_eligible(node_idx, p, now)

    def _make_eligible(self, count_idx: int, p: StateEvent, now: int) -> None:
        nxt = count_idx + 1
        if nxt >= len(self.c.nodes):
            # count node is final: emission happens on min-reach (handled in step)
            return
        if p not in self.pending[nxt]:
            self.pending[nxt].append(p)     # shared reference, per reference semantics
        node = self.c.nodes[nxt]
        if node.kind in ("absent", "logical") and \
                node.waiting_time_ms is not None:
            arrival_key = f"absent_arrival_{node.index}"
            if arrival_key not in p.meta:
                p.meta[arrival_key] = now
                self.app_context.scheduler.notify_at(
                    now + node.waiting_time_ms,
                    lambda ts, ni=nxt, pp=p: self._absent_timer(ni, pp, ts))

    # -- event handling -------------------------------------------------------
    def receive(self, event: StreamEvent, stream_id: str) -> None:
        if event.type != EventType.CURRENT:
            return
        if not self.started:
            self.start()
        touched: set[int] = set()
        self._created = set()
        created = self._created
        matched_any = False

        for i in range(len(self.c.nodes) - 1, -1, -1):
            node = self.c.nodes[i]
            listens = [b for b in node.branches if b.stream_id == stream_id]
            if not listens:
                continue
            for p in list(self.pending[i]):
                if id(p) in created:
                    continue
                if self._expired_partial(node, p, event.timestamp):
                    self._remove_everywhere(p)
                    # an `every` scope whose instance expired re-initializes
                    # its start state, and the CURRENT event may consume the
                    # fresh seed (reference StreamPreStateProcessor expiry +
                    # init; WithinPatternTestCase.testQuery4)
                    reseeded = self._reseed_on_expiry(i, p, event.timestamp)
                    if reseeded is not None:
                        seed, start = reseeded
                        # start < i is revisited by the reverse loop; a seed
                        # landing AT i must be offered this event explicitly
                        # (the loop iterates a snapshot of pending[i])
                        if start == i and seed in self.pending[start]:
                            slist = [b for b in self.c.nodes[start].branches
                                     if b.stream_id == stream_id]
                            if slist:
                                self._try_match(start, self.c.nodes[start],
                                                slist, seed, event, touched,
                                                created)
                    continue
                res = self._try_match(i, node, listens, p, event, touched, created)
                matched_any = matched_any or res

        if self.c.is_sequence:
            self._enforce_strict(stream_id, event, touched, created)

    def _reseed_on_expiry(self, i: int, p: StateEvent, now: int):
        """Re-seed the `every` scope after its pending instance expired or
        was strict-killed at node i. The scope is [reseed_to .. j] of an
        every end-node: ENCLOSING (reseed_to ≤ i ≤ j) or — when the partial
        had already advanced PAST the scope before dying — the nearest
        preceding end-node j < i (fuzz regression: `every e1=A[..]<1:3> ->
        e2=B[..]` killed at e2 by `within` never re-seeded, losing every
        later chain). Returns the new seed."""
        ends = [j for j in range(i, len(self.c.nodes))
                if self.c.nodes[j].reseed_to is not None
                and self.c.nodes[j].reseed_to <= i]
        if not ends:
            ends = [j for j in range(i - 1, -1, -1)
                    if self.c.nodes[j].reseed_to is not None][:1]
        for j in ends:
            node_j = self.c.nodes[j]
            start = node_j.reseed_to
            # another live instance of the scope → nothing to re-seed
            if any(self.pending[k] for k in range(start, j + 1)):
                return None
            seed = self._build_seed(node_j, p)
            self._place(start, seed, now)
            # unlike completion re-seeds, an expiry re-seed is visible to
            # the event being processed (the reference re-inits the start
            # state during expiry, before matching)
            self._created.discard(id(seed))
            return seed, start
        return None

    def _expired_partial(self, node: StateNode, p: StateEvent, ts: int) -> bool:
        w = self.c.within_ms
        if w is not None and p.first_timestamp is not None and ts - p.first_timestamp > w:
            return True
        if node.within_ms is not None and p.timestamp is not None \
                and ts - p.timestamp > node.within_ms:
            return True
        return False

    def _try_match(self, i: int, node: StateNode, branches: list[Branch],
                   p: StateEvent, event: StreamEvent,
                   touched: set[int], created: set[int]) -> bool:
        now = event.timestamp
        matched = False
        for b in branches:
            frame = StateFrame(p, current_alias=b.alias, current_event=event)
            ok = True
            if b.filter_fn is not None:
                ok = bool(b.filter_fn(frame))
            if not ok:
                continue
            matched = True
            touched.add(id(p))
            if b.is_absent:
                if node.index == 0 and node.kind == "logical" \
                        and node.waiting_time_ms is not None:
                    # start-state `X and/or not Y for t`: the forbidden event
                    # RESTARTS the wait (reference keeps start states live;
                    # LogicalAbsentPatternTestCase.testQueryAbsent8_2/10)
                    arrival_key = f"absent_arrival_{node.index}"
                    p.meta[arrival_key] = now
                    p.meta.pop(f"logical_established_{i}", None)
                    self.app_context.scheduler.notify_at(
                        now + node.waiting_time_ms,
                        lambda ts, ni=i, pp=p: self._absent_timer(ni, pp, ts))
                    return True
                if node.kind == "logical" \
                        and node.logical_type == LogicalType.OR:
                    # `... or not Y for t`: Y's arrival only kills the
                    # ABSENT alternative — the present side can still match
                    # later (reference LogicalAbsentPatternTestCase
                    # testQueryAbsent15)
                    p.meta[f"logical_absent_dead_{i}"] = True
                    return True
                if node.index == 0 and node.kind == "absent" \
                        and node.waiting_time_ms is not None:
                    # start-state absent: the forbidden event RESTARTS the
                    # wait instead of killing the pattern (reference
                    # AbsentStreamPreStateProcessor keeps start states live;
                    # AbsentPatternTestCase.testQueryAbsent6/8)
                    arrival_key = f"absent_arrival_{node.index}"
                    p.meta[arrival_key] = now
                    self.app_context.scheduler.notify_at(
                        now + node.waiting_time_ms,
                        lambda ts, ni=i, pp=p: self._absent_timer(ni, pp, ts))
                    return True
                # the forbidden event arrived → kill the partial
                self._remove_everywhere(p)
                return True
            if node.kind == "stream":
                # an open count node the partial is leaving completes its
                # `every` scope now (the scope's reseed lives on the count
                # node; consumption is its completion —
                # SequenceTestCase.testQuery4 shape)
                prev_reseed = None
                if i > 0:
                    prev = self.c.nodes[i - 1]
                    if prev.is_count and prev.reseed_to is not None \
                            and p in self.pending[i - 1]:
                        prev_reseed = prev
                # consume from EVERY node (count partials are shared into the
                # successor's pending via _make_eligible — advancing must
                # consume the count instance too, reference
                # CountPatternTestCase.testQuery2)
                self._remove_everywhere(p)
                adv = p.copy()
                adv.bind(b.alias, event)
                if prev_reseed is not None:
                    self._do_reseed(prev_reseed, p, now)
                self._advance(node, adv, now)
            elif node.kind == "count":
                p.bind(b.alias, event, append=True)
                cnt = len(p.events[b.alias])
                if cnt >= node.min_count:
                    if i == len(self.c.nodes) - 1:
                        # final count node: emit ONCE at min-count and
                        # consume (reference CountPatternTestCase.testQuery13
                        # — further extensions do not re-emit)
                        self._emit_from(node, p, now)
                        self._remove_everywhere(p)
                        return True
                    self._make_eligible(i, p, now)
                if node.max_count != -1 and cnt >= node.max_count:
                    if p in self.pending[i]:
                        self.pending[i].remove(p)
                    # a maxed-out count node ends its own `every` scope: the
                    # scope restarts while the closed partial waits at the
                    # successor (SequenceTestCase.testQuery6: `every e1?`)
                    if node.reseed_to is not None:
                        self._do_reseed(node, p, now)
            elif node.kind == "logical":
                other = [x for x in node.branches if x is not b]
                p.bind(b.alias, event)
                sides = p.meta.setdefault(f"logical_{i}", set())
                sides.add(b.alias)
                need_both = node.logical_type == LogicalType.AND
                if need_both:
                    # ONE event can satisfy both AND sides (the reference's
                    # two pre-state processors each receive it;
                    # LogicalPatternTestCase.testQuery5)
                    for ob in other:
                        # b is a listening branch, so b.stream_id IS the
                        # current event's stream
                        if ob.is_absent or ob.alias in sides or \
                                ob.stream_id != b.stream_id:
                            continue
                        oframe = StateFrame(p, current_alias=ob.alias,
                                            current_event=event)
                        if ob.filter_fn is None or bool(ob.filter_fn(oframe)):
                            p.bind(ob.alias, event)
                            sides.add(ob.alias)
                absent_other = other and other[0].is_absent
                done = (not need_both) or absent_other or all(
                    x.alias in sides for x in node.branches if not x.is_absent
                )
                if done and not absent_other:
                    self.pending[i].remove(p)
                    adv = p.copy()
                    adv.meta.pop(f"logical_{i}", None)
                    self._advance(node, adv, now)
                elif done and absent_other:
                    # `X or not Y for t`: X advances immediately (first of
                    # the two alternatives wins). `X and not Y [for t]`:
                    # advance if no timer is required, or if the
                    # non-occurrence was already established; otherwise the
                    # timer decides later.
                    established = p.meta.get(f"logical_established_{i}")
                    if node.logical_type == LogicalType.OR \
                            or node.waiting_time_ms is None \
                            or established is not None:
                        self.pending[i].remove(p)
                        adv = p.copy()
                        adv.meta.pop(f"logical_{i}", None)
                        adv.meta.pop(f"logical_established_{i}", None)
                        self._advance(node, adv, now)
            break
        return matched

    def _advance(self, node: StateNode, p: StateEvent, now: int) -> None:
        self._do_reseed(node, p, now)
        nxt = node.index + 1
        if nxt >= len(self.c.nodes):
            self._emit(p, now)
        else:
            self._place(nxt, p, now)

    def _emit_from(self, node: StateNode, p: StateEvent, now: int) -> None:
        """Emit a completed match from a final count node (partial keeps going)."""
        self._do_reseed(node, p, now)
        self._emit(p.copy(), now)

    def _build_seed(self, node: StateNode, p: StateEvent) -> StateEvent:
        """Clone ``p`` minus the `every` scope's own bindings, recomputing
        timestamps from the surviving (pre-scope) bindings."""
        seed = p.copy()
        for alias in node.reseed_aliases:
            seed.events.pop(alias, None)
        seed.meta.clear()
        ts_list = []
        for v in seed.events.values():
            if isinstance(v, list):
                ts_list.extend(e.timestamp for e in v)
            elif v is not None:
                ts_list.append(v.timestamp)
        seed.first_timestamp = min(ts_list) if ts_list else None
        seed.timestamp = max(ts_list) if ts_list else None
        return seed

    def _do_reseed(self, node: StateNode, p: StateEvent, now: int) -> None:
        if node.reseed_to is None:
            return
        self._place(node.reseed_to, self._build_seed(node, p), now)

    def _emit(self, p: StateEvent, now: int) -> None:
        self._remove_everywhere(p)
        if self.next is not None:
            self.next.process([PatternEvent(now, p)])

    def _remove_everywhere(self, p: StateEvent) -> None:
        for lst in self.pending:
            if p in lst:
                lst.remove(p)

    def _absent_timer(self, node_idx: int, p: StateEvent, ts: int) -> None:
        node = self.c.nodes[node_idx]
        if p not in self.pending[node_idx]:
            return                       # already killed or advanced
        arrival = p.meta.get(f"absent_arrival_{node.index}")
        if arrival is None:
            return
        if node.kind == "absent":
            if ts < arrival + node.waiting_time_ms:
                return                   # stale timer: the wait was restarted
            # non-occurrence established → advance
            self.pending[node_idx].remove(p)
            adv = p.copy()
            adv.meta.pop(f"absent_arrival_{node.index}", None)
            self._advance(node, adv, ts)
        elif node.kind == "logical":
            if ts < arrival + node.waiting_time_ms:
                return                   # stale timer (wait was restarted)
            if p.meta.get(f"logical_absent_dead_{node_idx}"):
                return                   # forbidden event spoiled the wait
            sides = p.meta.get(f"logical_{node_idx}", set())
            required = [b.alias for b in node.branches if not b.is_absent]
            if node.logical_type == LogicalType.OR \
                    or all(a in sides for a in required):
                # OR: established non-occurrence completes the state with
                # the present side unbound (null). AND: complete iff the
                # present side already matched.
                self.pending[node_idx].remove(p)
                adv = p.copy()
                adv.meta.pop(f"logical_{node_idx}", None)
                adv.meta.pop(f"logical_established_{node_idx}", None)
                self._advance(node, adv, ts)
            else:
                # AND, X not yet bound: remember the establishment so a
                # later X advances immediately
                p.meta[f"logical_established_{node_idx}"] = ts

    # -- sequence strictness --------------------------------------------------
    def _enforce_strict(self, stream_id: str, event: StreamEvent,
                        touched: set[int], created: set[int]) -> None:
        seen: set[int] = set()
        for i, lst in enumerate(self.pending):
            node = self.c.nodes[i]
            for p in list(lst):
                pid = id(p)
                if pid in seen:
                    continue            # shared count/eligible partial:
                seen.add(pid)           # judge it once, at its lowest node
                if pid in touched or pid in created:
                    continue
                if not p.events:
                    # start seed: with `every`, seeds persist (retry at every
                    # position); without, the failed first attempt dies
                    has_every = any(n.reseed_to == 0 for n in self.c.nodes)
                    if has_every:
                        continue
                self._remove_everywhere(p)
                # strict continuity killed an `every` instance mid-scope: the
                # scope restarts and the fresh attempt may consume THIS very
                # event (reference SequenceTestCase.testQuery6 — the killing
                # event seeds the next instance)
                reseeded = self._reseed_on_expiry(i, p, event.timestamp)
                if reseeded is not None:
                    seed, start = reseeded
                    if seed in self.pending[start]:
                        snode = self.c.nodes[start]
                        listens = [b for b in snode.branches
                                   if b.stream_id == stream_id]
                        if listens:
                            self._try_match(start, snode, listens, seed,
                                            event, touched, created)

    # -- snapshot -------------------------------------------------------------
    def snapshot_state(self) -> dict:
        def enc_ev(e: StreamEvent):
            return (e.timestamp, list(e.data))

        def enc_state(p: StateEvent):
            return {
                "events": {
                    k: ([enc_ev(x) for x in v] if isinstance(v, list) else enc_ev(v))
                    for k, v in p.events.items()
                },
                "first": p.first_timestamp,
                "ts": p.timestamp,
                "meta": {k: (list(v) if isinstance(v, set) else v)
                         for k, v in p.meta.items()},
            }

        return {
            "pending": [[enc_state(p) for p in lst] for lst in self.pending],
            "started": self.started,
        }

    def restore_state(self, state: dict) -> None:
        def dec_ev(t):
            return StreamEvent(t[0], t[1])

        def dec_state(d) -> StateEvent:
            p = StateEvent()
            p.events = {
                k: ([dec_ev(x) for x in v] if v and isinstance(v[0], (list, tuple)) and
                    self.c.alias_is_list.get(k) else
                    ([dec_ev(x) for x in v] if self.c.alias_is_list.get(k) else dec_ev(v)))
                for k, v in d["events"].items()
            }
            p.first_timestamp = d["first"]
            p.timestamp = d["ts"]
            p.meta = {k: (set(v) if isinstance(v, list) and k.startswith("logical") else v)
                      for k, v in d["meta"].items()}
            return p

        self.pending = [[dec_state(p) for p in lst] for lst in state["pending"]]
        self.started = state["started"]
        # re-arm absent-state non-occurrence timers (fresh scheduler)
        for node_idx, lst in enumerate(self.pending):
            node = self.c.nodes[node_idx]
            if node.waiting_time_ms is None:
                continue
            for partial in lst:
                arrival = partial.meta.get(f"absent_arrival_{node.index}")
                if arrival is not None:
                    self.app_context.scheduler.notify_at(
                        arrival + node.waiting_time_ms,
                        lambda ts, ni=node_idx, pp=partial: self._absent_timer(
                            ni, pp, ts))


class PatternStreamReceiver:
    """Junction subscriber forwarding one stream's events into the runtime."""

    def __init__(self, runtime: PatternRuntime, stream_id: str):
        self.runtime = runtime
        self.stream_id = stream_id

    def receive(self, event: StreamEvent) -> None:
        self.runtime.receive(event, self.stream_id)
