"""Time machinery: clocks, timers, TIMER event injection.

Reference: ``core/util/Scheduler.java`` (notifyAt/sendTimerEvents),
``util/timestamp/TimestampGeneratorImpl.java`` (playback event-time clock with idle
heartbeat). Redesigned watermark-style: in playback mode the clock only advances via
event timestamps (or explicit ``advance_time``); due timers fire deterministically
*before* the event that advanced time is processed — no wall-clock callbacks, no
sleeps, matching the batch-synchronous TPU design where TIMER rows are injected into
micro-batches.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Optional


class TimestampGenerator:
    """Engine clock. ``playback=True`` → event-time; else wall clock (ms)."""

    def __init__(self, playback: bool = False, start_time: int = 0,
                 idle_timeout_ms: int = 0):
        self.playback = playback
        self._current = start_time
        self.idle_timeout_ms = idle_timeout_ms
        # wall time of the last clock advance — read by PlaybackHeartbeat
        self.last_advance_wall = time.time() * 1000

    def current_time(self) -> int:
        if self.playback:
            return self._current
        return int(time.time() * 1000)

    def advance(self, ts: int) -> None:
        self.last_advance_wall = time.time() * 1000
        if ts > self._current:
            self._current = ts


class Scheduler:
    """Deterministic timer service.

    Processors call ``notify_at(ts, callback)``; ``fire_until(now)`` pops and runs
    every due timer in timestamp order. The app runtime calls ``fire_until`` each
    time the clock advances (event arrival in playback mode; a background ticker in
    system-time mode).
    """

    def __init__(self, clock: TimestampGenerator):
        self.clock = clock
        self._heap: list[tuple[int, int, Callable[[int], None]]] = []
        self._counter = itertools.count()
        self._lock = threading.RLock()

    def notify_at(self, ts: int, callback: Callable[[int], None]) -> None:
        with self._lock:
            heapq.heappush(self._heap, (ts, next(self._counter), callback))

    def fire_until(self, now: int) -> None:
        """Run all timers with fire-time <= now (in order)."""
        while True:
            with self._lock:
                if not self._heap or self._heap[0][0] > now:
                    return
                ts, _, cb = heapq.heappop(self._heap)
            cb(ts)

    def has_pending(self) -> bool:
        return bool(self._heap)

    def next_fire_time(self) -> Optional[int]:
        with self._lock:
            return self._heap[0][0] if self._heap else None

    def clear(self) -> None:
        with self._lock:
            self._heap.clear()


class PlaybackHeartbeat:
    """``@app:playback(idle.time='...', increment='...')`` — after
    ``idle.time`` of WALL-clock silence on the ingress, the playback clock
    jumps forward by ``increment`` and due timers fire (reference
    ``util/timestamp/EventTimeBasedMillisTimestampGenerator``'s heartbeat).
    The one deliberate wall-clock element in playback mode: everything else
    stays event-time deterministic."""

    def __init__(self, app_context, idle_ms: int, increment_ms: int):
        self.app_context = app_context
        self.idle_ms = idle_ms
        self.increment_ms = increment_ms
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._stop.wait(max(self.idle_ms / 2000.0, 0.005))
            clock = self.app_context.timestamp_generator
            if (time.time() * 1000) - clock.last_advance_wall < self.idle_ms:
                continue
            with self.app_context.root_lock:
                self.app_context.advance_time(
                    clock.current_time() + self.increment_ms)

    def stop(self) -> None:
        self._stop.set()


class SystemTicker:
    """Background thread firing scheduler timers in wall-clock mode.

    Only started when the app runs with a system clock (playback off); playback apps
    are fully deterministic and never spawn threads.
    """

    def __init__(self, scheduler: Scheduler, resolution_ms: int = 10):
        self.scheduler = scheduler
        self.resolution = resolution_ms / 1000.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.scheduler.fire_until(self.scheduler.clock.current_time())
            self._stop.wait(self.resolution)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None
