"""Host interpreter runtime — the semantic oracle and cold-path engine.

Mirrors the reference's ``siddhi-core`` module structure: event model, stream
junctions, processor chains, windows, NFA pattern engine, joins, selectors,
tables, partitions, triggers, snapshots, sources/sinks.
"""

from .errors import ErrorEntry, ErrorStore, FileErrorStore
from .event import Event, EventType, StateEvent, StreamEvent
from .manager import SiddhiManager
from .app_runtime import SiddhiAppRuntime
from .stream import InputHandler, QueryCallback, StreamCallback
from .snapshot import (
    FileSystemPersistenceStore,
    IncrementalFileSystemPersistenceStore,
    IncrementalPersistenceStore,
    InMemoryPersistenceStore,
    PersistenceStore,
    SnapshotableEventBuffer,
)
from .extension import (
    ScalarFunctionExtension,
    StreamFunctionExtension,
    extension,
)
from .io import (
    InMemoryBroker,
    SinkHandler,
    SinkHandlerManager,
    SourceHandler,
    SourceHandlerManager,
)
from .table import RecordTableHandler, RecordTableHandlerManager
from .metrics import Level
from .config import (
    ConfigManager,
    ConfigReader,
    InMemoryConfigManager,
    YAMLConfigManager,
)
