"""Output rate limiters.

Reference: ``core/query/output/ratelimit/`` — event/ (per-N-events), time/
(per-period), snapshot/ (periodic state snapshot). Time-driven limiters use the
deterministic Scheduler.
"""

from __future__ import annotations

from typing import Optional

from ..query_api import (
    EventOutputRate,
    OutputRateType,
    SnapshotOutputRate,
    TimeOutputRate,
)
from .event import EventType, StreamEvent


class PassThroughRateLimiter:
    def __init__(self):
        self.next = None

    def process(self, events: list[StreamEvent]) -> None:
        if self.next is not None and events:
            self.next.process(events)

    def snapshot_state(self) -> dict:
        return {}

    def restore_state(self, state: dict) -> None:
        pass


class EventRateLimiter(PassThroughRateLimiter):
    """``output [all|first|last] every N events``.

    With ``grouped`` (the query has a group-by), first/last behave PER KEY:
    first keeps a per-key occurrence counter — emit a key's first arrival,
    suppress its next N−1, then its next arrival emits again (reference
    ``FirstGroupByPerEventOutputRateLimiter`` — no global batch at all);
    last keeps the global N-event batch but emits every key's final row at
    the boundary in first-seen order
    (``LastGroupByPerEventOutputRateLimiter``'s LinkedHashMap)."""

    def __init__(self, n: int, mode: OutputRateType, grouped: bool = False):
        super().__init__()
        self.n = n
        self.mode = mode
        self.grouped = grouped
        self.counter = 0
        self.pending: list[StreamEvent] = []
        self.last: Optional[StreamEvent] = None
        self.key_counts: dict = {}
        self.last_by_key: dict = {}

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if self.mode == OutputRateType.FIRST and self.grouped:
                c = self.key_counts.get(ev.group_key)
                if c is None:
                    self.key_counts[ev.group_key] = 1
                    out.append(ev)
                elif c == self.n - 1:
                    del self.key_counts[ev.group_key]
                else:
                    self.key_counts[ev.group_key] = c + 1
                continue
            self.counter += 1
            if self.mode == OutputRateType.ALL:
                self.pending.append(ev)
                if self.counter == self.n:
                    out.extend(self.pending)
                    self.pending = []
                    self.counter = 0
            elif self.mode == OutputRateType.FIRST:
                if self.counter == 1:
                    out.append(ev)
                if self.counter == self.n:
                    self.counter = 0
            else:  # LAST
                if self.grouped:
                    self.last_by_key[ev.group_key] = ev
                else:
                    self.last = ev
                if self.counter == self.n:
                    if self.grouped:
                        out.extend(self.last_by_key.values())
                        self.last_by_key = {}
                    elif self.last is not None:
                        out.append(self.last)
                    self.last = None
                    self.counter = 0
        if self.next is not None and out:
            self.next.process(out)

    def snapshot_state(self) -> dict:
        enc = lambda e: (e.timestamp, list(e.data), e.type.value)  # noqa: E731
        return {"counter": self.counter,
                "pending": [enc(e) for e in self.pending],
                "last": enc(self.last) if self.last is not None else None,
                "key_counts": list(self.key_counts.items()),
                "last_by_key": [(k, enc(e))
                                for k, e in self.last_by_key.items()]}

    def restore_state(self, state: dict) -> None:
        self.counter = state["counter"]
        self.pending = [StreamEvent(t, d, EventType(ty)) for t, d, ty in state["pending"]]
        self.last = StreamEvent(*state["last"][:2], EventType(state["last"][2])) \
            if state.get("last") else None
        self.key_counts = {
            (tuple(k) if isinstance(k, list) else k): c
            for k, c in state.get("key_counts", [])}
        self.last_by_key = {}
        for k, (t, d, ty) in state.get("last_by_key", []):
            self.last_by_key[tuple(k) if isinstance(k, list) else k] = \
                StreamEvent(t, d, EventType(ty))


class TimeRateLimiter(PassThroughRateLimiter):
    """``output [all|first|last] every <time>`` — flush on scheduler ticks.
    Grouped first is a per-key SLIDING gate: a key emits when the period
    has elapsed since its own last emission (reference
    ``FirstGroupByPerTimeOutputRateLimiter`` tracks per-key output times);
    grouped last flushes every key's final row on the period timer
    (``LastGroupByPerTimeOutputRateLimiter``)."""

    def __init__(self, period_ms: int, mode: OutputRateType, app_context,
                 grouped: bool = False):
        super().__init__()
        self.period = period_ms
        self.mode = mode
        self.grouped = grouped
        self.app_context = app_context
        self.pending: list[StreamEvent] = []
        self.first_sent = False
        self.last: Optional[StreamEvent] = None
        self.window_end: Optional[int] = None
        self.key_out_time: dict = {}
        self.last_by_key: dict = {}

    def _arm(self, ts: int) -> None:
        if self.window_end is None:
            self.window_end = ts + self.period
            self.app_context.scheduler.notify_at(self.window_end, self._on_timer)

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            self._arm(ev.timestamp)
            if self.mode == OutputRateType.ALL:
                self.pending.append(ev)
            elif self.mode == OutputRateType.FIRST:
                if self.grouped:
                    now = self.app_context.current_time()
                    lo = self.key_out_time.get(ev.group_key)
                    if lo is None or lo + self.period <= now:
                        self.key_out_time[ev.group_key] = now
                        out.append(ev)
                elif not self.first_sent:
                    out.append(ev)
                    self.first_sent = True
            else:
                if self.grouped:
                    self.last_by_key[ev.group_key] = ev
                else:
                    self.last = ev
        if self.next is not None and out:
            self.next.process(out)

    def _on_timer(self, ts: int) -> None:
        out: list[StreamEvent] = []
        if self.mode == OutputRateType.ALL:
            out, self.pending = self.pending, []
        elif self.mode == OutputRateType.FIRST:
            self.first_sent = False
        else:
            if self.grouped:
                out = list(self.last_by_key.values())
                self.last_by_key = {}
            elif self.last is not None:
                out = [self.last]
            self.last = None
        self.window_end = ts + self.period
        self.app_context.scheduler.notify_at(self.window_end, self._on_timer)
        if self.next is not None and out:
            self.next.process(out)


class SnapshotRateLimiter(PassThroughRateLimiter):
    """``output snapshot every <time>`` — each period emits the latest
    output value; for group-by queries, EVERY group's latest row in
    first-seen order (reference ``WrappedSnapshotOutputRateLimiter``'s
    per-group snapshot limiters)."""

    def __init__(self, period_ms: int, app_context, grouped: bool = False):
        super().__init__()
        self.period = period_ms
        self.app_context = app_context
        self.grouped = grouped
        self.latest: Optional[StreamEvent] = None
        self.latest_by_key: dict = {}
        self.window_end: Optional[int] = None

    def process(self, events: list[StreamEvent]) -> None:
        for ev in events:
            if self.window_end is None:
                self.window_end = ev.timestamp + self.period
                self.app_context.scheduler.notify_at(self.window_end, self._on_timer)
            if ev.type == EventType.CURRENT:
                if self.grouped:
                    self.latest_by_key[ev.group_key] = ev
                else:
                    self.latest = ev

    def _on_timer(self, ts: int) -> None:
        out = []
        if self.grouped:
            out = [StreamEvent(ts, e.data, EventType.CURRENT)
                   for e in self.latest_by_key.values()]
        elif self.latest is not None:
            out = [StreamEvent(ts, self.latest.data, EventType.CURRENT)]
        self.window_end = ts + self.period
        self.app_context.scheduler.notify_at(self.window_end, self._on_timer)
        if self.next is not None and out:
            self.next.process(out)


def build_rate_limiter(output_rate, app_context, grouped: bool = False):
    if output_rate is None:
        return PassThroughRateLimiter()
    if isinstance(output_rate, EventOutputRate):
        return EventRateLimiter(output_rate.value, output_rate.type, grouped)
    if isinstance(output_rate, TimeOutputRate):
        return TimeRateLimiter(output_rate.value_ms, output_rate.type,
                               app_context, grouped)
    if isinstance(output_rate, SnapshotOutputRate):
        return SnapshotRateLimiter(output_rate.value_ms, app_context, grouped)
    raise ValueError(f"unknown output rate {output_rate!r}")
