"""Output rate limiters.

Reference: ``core/query/output/ratelimit/`` — event/ (per-N-events), time/
(per-period), snapshot/ (periodic state snapshot). Time-driven limiters use the
deterministic Scheduler.
"""

from __future__ import annotations

from typing import Optional

from ..query_api import (
    EventOutputRate,
    OutputRateType,
    SnapshotOutputRate,
    TimeOutputRate,
)
from .event import EventType, StreamEvent


class PassThroughRateLimiter:
    def __init__(self):
        self.next = None

    def process(self, events: list[StreamEvent]) -> None:
        if self.next is not None and events:
            self.next.process(events)

    def snapshot_state(self) -> dict:
        return {}

    def restore_state(self, state: dict) -> None:
        pass


class EventRateLimiter(PassThroughRateLimiter):
    """`output [all|first|last] every N events`."""

    def __init__(self, n: int, mode: OutputRateType):
        super().__init__()
        self.n = n
        self.mode = mode
        self.counter = 0
        self.pending: list[StreamEvent] = []
        self.last: Optional[StreamEvent] = None

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            self.counter += 1
            if self.mode == OutputRateType.ALL:
                self.pending.append(ev)
                if self.counter == self.n:
                    out.extend(self.pending)
                    self.pending = []
                    self.counter = 0
            elif self.mode == OutputRateType.FIRST:
                if self.counter == 1:
                    out.append(ev)
                if self.counter == self.n:
                    self.counter = 0
            else:  # LAST
                self.last = ev
                if self.counter == self.n:
                    out.append(self.last)
                    self.last = None
                    self.counter = 0
        if self.next is not None and out:
            self.next.process(out)

    def snapshot_state(self) -> dict:
        enc = lambda e: (e.timestamp, list(e.data), e.type.value)  # noqa: E731
        return {"counter": self.counter,
                "pending": [enc(e) for e in self.pending],
                "last": enc(self.last) if self.last is not None else None}

    def restore_state(self, state: dict) -> None:
        self.counter = state["counter"]
        self.pending = [StreamEvent(t, d, EventType(ty)) for t, d, ty in state["pending"]]
        self.last = StreamEvent(*state["last"][:2], EventType(state["last"][2])) \
            if state.get("last") else None


class TimeRateLimiter(PassThroughRateLimiter):
    """`output [all|first|last] every <time>` — flush on scheduler ticks."""

    def __init__(self, period_ms: int, mode: OutputRateType, app_context):
        super().__init__()
        self.period = period_ms
        self.mode = mode
        self.app_context = app_context
        self.pending: list[StreamEvent] = []
        self.first_sent = False
        self.last: Optional[StreamEvent] = None
        self.window_end: Optional[int] = None

    def _arm(self, ts: int) -> None:
        if self.window_end is None:
            self.window_end = ts + self.period
            self.app_context.scheduler.notify_at(self.window_end, self._on_timer)

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            self._arm(ev.timestamp)
            if self.mode == OutputRateType.ALL:
                self.pending.append(ev)
            elif self.mode == OutputRateType.FIRST:
                if not self.first_sent:
                    out.append(ev)
                    self.first_sent = True
            else:
                self.last = ev
        if self.next is not None and out:
            self.next.process(out)

    def _on_timer(self, ts: int) -> None:
        out: list[StreamEvent] = []
        if self.mode == OutputRateType.ALL:
            out, self.pending = self.pending, []
        elif self.mode == OutputRateType.FIRST:
            self.first_sent = False
        else:
            if self.last is not None:
                out = [self.last]
                self.last = None
        self.window_end = ts + self.period
        self.app_context.scheduler.notify_at(self.window_end, self._on_timer)
        if self.next is not None and out:
            self.next.process(out)


class SnapshotRateLimiter(PassThroughRateLimiter):
    """`output snapshot every <time>` — emits the latest value (per group when the
    output has repeating keys is approximated by last event) each period."""

    def __init__(self, period_ms: int, app_context):
        super().__init__()
        self.period = period_ms
        self.app_context = app_context
        self.latest: Optional[StreamEvent] = None
        self.window_end: Optional[int] = None

    def process(self, events: list[StreamEvent]) -> None:
        for ev in events:
            if self.window_end is None:
                self.window_end = ev.timestamp + self.period
                self.app_context.scheduler.notify_at(self.window_end, self._on_timer)
            if ev.type == EventType.CURRENT:
                self.latest = ev

    def _on_timer(self, ts: int) -> None:
        out = []
        if self.latest is not None:
            out = [StreamEvent(ts, self.latest.data, EventType.CURRENT)]
        self.window_end = ts + self.period
        self.app_context.scheduler.notify_at(self.window_end, self._on_timer)
        if self.next is not None and out:
            self.next.process(out)


def build_rate_limiter(output_rate, app_context):
    if output_rate is None:
        return PassThroughRateLimiter()
    if isinstance(output_rate, EventOutputRate):
        return EventRateLimiter(output_rate.value, output_rate.type)
    if isinstance(output_rate, TimeOutputRate):
        return TimeRateLimiter(output_rate.value_ms, output_rate.type, app_context)
    if isinstance(output_rate, SnapshotOutputRate):
        return SnapshotRateLimiter(output_rate.value_ms, app_context)
    raise ValueError(f"unknown output rate {output_rate!r}")
