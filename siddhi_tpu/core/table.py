"""Tables: in-memory storage with primary-key/index acceleration + record SPI.

Reference: ``core/table/`` — ``InMemoryTable.java``, ``holder/IndexEventHolder.java``
(primaryKeyData map + indexData TreeMaps), ``record/AbstractRecordTable.java``
(external store SPI), compiled conditions via ``util/collection/``. The
interpreter's "compiled condition" is a closure over (row, matching event) frames;
the PK fast path mirrors IndexOperator.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional

from ..query_api import (
    Compare,
    CompareOp,
    DataType,
    Expression,
    Variable,
)
from ..query_api.annotation import find_annotation
from ..query_api.definition import TableDefinition
from .event import Event, StreamEvent
from .executor import ExecutorBuilder, VariableResolver


def _pk_key(row: list, pk_positions: list[int]) -> Any:
    """Single-PK → scalar key, composite → tuple (shared by table + cache)."""
    if len(pk_positions) == 1:
        return row[pk_positions[0]]
    return tuple(row[p] for p in pk_positions)


class TableMatchFrame:
    """Frame pairing a table row with the matching (output) event."""

    __slots__ = ("row", "out", "ts")

    def __init__(self, row: Optional[list], out: Optional[list], ts: int = 0):
        self.row = row
        self.out = out
        self.ts = ts

    def timestamp(self) -> int:
        return self.ts


class TableMatchResolver(VariableResolver):
    """``T.attr`` → row side; bare/other → matching-event side."""

    def __init__(self, table_def: TableDefinition, out_names: list[str],
                 out_types: list[DataType], stream_ref: Optional[str] = None):
        self.table_def = table_def
        self.out_names = out_names
        self.out_types = out_types
        self.stream_ref = stream_ref

    def resolve(self, var: Variable):
        sid = var.stream_id
        if sid == self.table_def.id:
            pos = self.table_def.attribute_position(var.attribute)
            return (lambda f: None if f.row is None else f.row[pos]), \
                self.table_def.attributes[pos].type
        if sid is None and var.attribute not in self.out_names \
                and var.attribute in self.table_def.attribute_names:
            pos = self.table_def.attribute_position(var.attribute)
            return (lambda f: None if f.row is None else f.row[pos]), \
                self.table_def.attributes[pos].type
        if var.attribute in self.out_names:
            pos = self.out_names.index(var.attribute)
            return (lambda f: None if f.out is None else f.out[pos]), self.out_types[pos]
        raise KeyError(f"cannot resolve '{var.attribute}' in table condition")


class _RowDependentSet(Exception):
    """Probe signal: a set expression touched a table column."""


class _RaisingRow:
    """Row stand-in whose every column access raises — used to detect
    row-dependent set expressions before a record-store update."""

    def __init__(self, table_id: str):
        self._table_id = table_id

    def __getitem__(self, i):
        raise _RowDependentSet(self._table_id)


class StoreExpression:
    """Store-visitable condition tree (the analog of the reference's
    ``ExpressionBuilder``/``ExpressionVisitor`` output handed to record
    stores, ``table/record/ExpressionBuilder.java``). Nodes:

    - ``('attribute', name)`` — a table column
    - ``('constant', value)`` — a literal
    - ``('param', name)`` — a streaming-side value, resolved per lookup and
      passed in ``condition_params``
    - ``('compare', op, lhs, rhs)`` — op in ``== != < <= > >=``
    - ``('and'|'or', lhs, rhs)``, ``('not', sub)``
    - ``('math', op, lhs, rhs)`` — op in ``+ - * / %``

    Stores walk the tree with :meth:`visit` or translate it to their native
    query language (e.g. a SQL WHERE clause).
    """

    def __init__(self, node: tuple):
        self.node = node

    def visit(self, visitor) -> Any:
        """visitor: object with ``attribute(name)``, ``constant(value)``,
        ``param(name)``, ``compare(op, l, r)``, ``logical(op, l, r)``,
        ``negate(sub)``, ``math(op, l, r)`` — called bottom-up."""
        return _visit_store_expr(self.node, visitor)

    def __repr__(self):
        return f"StoreExpression({self.node!r})"


def _visit_store_expr(node: tuple, v) -> Any:
    kind = node[0]
    if kind == "attribute":
        return v.attribute(node[1])
    if kind == "constant":
        return v.constant(node[1])
    if kind == "param":
        return v.param(node[1])
    if kind == "compare":
        return v.compare(node[1], _visit_store_expr(node[2], v),
                         _visit_store_expr(node[3], v))
    if kind in ("and", "or"):
        return v.logical(kind, _visit_store_expr(node[1], v),
                         _visit_store_expr(node[2], v))
    if kind == "not":
        return v.negate(_visit_store_expr(node[1], v))
    if kind == "math":
        return v.math(node[1], _visit_store_expr(node[2], v),
                      _visit_store_expr(node[3], v))
    raise ValueError(f"unknown store-expression node {kind!r}")


class CompiledTableCondition:
    """condition fn + optional primary-key fast path + optional store-
    pushdown form."""

    def __init__(self, fn: Callable[[TableMatchFrame], bool],
                 pk_extractor: Optional[Callable[[list], Any]] = None,
                 store_expr: Optional[StoreExpression] = None,
                 param_fns: Optional[dict] = None):
        self.fn = fn
        self.pk_extractor = pk_extractor    # out_data -> pk value
        self.store_expr = store_expr        # pushdown tree (None: host-only)
        self.param_fns = param_fns or {}    # param name -> fn(frame) -> value
        self._store_compiled: dict = {}     # per-table compiled handle cache


class Table:
    """Base table API (reference ``table/Table.java``)."""

    def __init__(self, definition: TableDefinition, app_context):
        self.definition = definition
        self.app_context = app_context
        self.id = definition.id

    def add(self, rows: list[list], ts: int = 0) -> None:
        raise NotImplementedError

    def find(self, cond: Optional[CompiledTableCondition],
             out_data: Optional[list], ts: int = 0) -> list[list]:
        raise NotImplementedError

    def contains(self, cond: CompiledTableCondition, out_data: list, ts: int = 0) -> bool:
        return bool(self.find(cond, out_data, ts))

    def delete(self, cond: CompiledTableCondition, out_data: list, ts: int = 0) -> int:
        raise NotImplementedError

    def update(self, cond: CompiledTableCondition, out_data: list,
               setters: list[tuple[int, Callable]], ts: int = 0) -> int:
        raise NotImplementedError

    def update_or_add(self, cond: CompiledTableCondition, out_data: list,
                      setters: list[tuple[int, Callable]], ts: int = 0) -> None:
        raise NotImplementedError


class InMemoryTable(Table):
    def __init__(self, definition: TableDefinition, app_context):
        super().__init__(definition, app_context)
        self.rows: list[list] = []
        # @PrimaryKey('attr'[, 'attr2']) / @Index('attr')
        self.pk_positions: list[int] = []
        pk = find_annotation(definition.annotations, "PrimaryKey")
        if pk:
            self.pk_positions = [
                definition.attribute_position(v) for v in pk.indexed_values()
            ]
        self.pk_map: dict[Any, list] = {}
        self.index_positions: list[int] = []
        for idx_ann in definition.annotations:
            if idx_ann.name.lower() == "index":
                for v in idx_ann.indexed_values():
                    self.index_positions.append(definition.attribute_position(v))
        self.indexes: dict[int, dict[Any, list[list]]] = {
            p: {} for p in self.index_positions
        }
        app_context.register_state(f"table-{self.id}", self)

    # -- helpers --------------------------------------------------------------
    def _pk_of_row(self, row: list) -> Any:
        return _pk_key(row, self.pk_positions)

    def _index_add(self, row: list) -> None:
        for p in self.index_positions:
            self.indexes[p].setdefault(row[p], []).append(row)

    def _index_remove(self, row: list) -> None:
        for p in self.index_positions:
            lst = self.indexes[p].get(row[p])
            if lst and row in lst:
                lst.remove(row)

    # -- operations -----------------------------------------------------------
    def add(self, rows: list[list], ts: int = 0) -> None:
        for r in rows:
            row = list(r)
            if self.pk_positions:
                key = self._pk_of_row(row)
                if key in self.pk_map:
                    raise ValueError(
                        f"primary key violation on table '{self.id}': {key!r}")
                self.pk_map[key] = row
            self.rows.append(row)
            self._index_add(row)

    def _candidates(self, cond: Optional[CompiledTableCondition],
                    out_data: Optional[list]) -> list[list]:
        if cond is None:
            return self.rows
        if cond.pk_extractor is not None and self.pk_positions:
            key = cond.pk_extractor(out_data)
            row = self.pk_map.get(key)
            return [row] if row is not None else []
        return self.rows

    def find(self, cond, out_data, ts: int = 0) -> list[list]:
        if cond is None:
            return [list(r) for r in self.rows]
        return [
            list(r) for r in self._candidates(cond, out_data)
            if cond.fn(TableMatchFrame(r, out_data, ts))
        ]

    def delete(self, cond, out_data, ts: int = 0) -> int:
        victims = [
            r for r in self._candidates(cond, out_data)
            if cond.fn(TableMatchFrame(r, out_data, ts))
        ]
        for r in victims:
            self.rows.remove(r)
            self._index_remove(r)
            if self.pk_positions:
                self.pk_map.pop(self._pk_of_row(r), None)
        return len(victims)

    def update(self, cond, out_data, setters, ts: int = 0) -> int:
        n = 0
        for r in self._candidates(cond, out_data):
            if cond is None or cond.fn(TableMatchFrame(r, out_data, ts)):
                self._apply_set(r, out_data, setters, ts)
                n += 1
        return n

    def _apply_set(self, row: list, out_data: list, setters, ts: int) -> None:
        if self.pk_positions:
            old_key = self._pk_of_row(row)
        self._index_remove(row)
        for pos, value_fn in setters:
            row[pos] = value_fn(TableMatchFrame(row, out_data, ts))
        self._index_add(row)
        if self.pk_positions:
            new_key = self._pk_of_row(row)
            if new_key != old_key:
                self.pk_map.pop(old_key, None)
                self.pk_map[new_key] = row

    def update_or_add(self, cond, out_data, setters, ts: int = 0) -> None:
        if self.update(cond, out_data, setters, ts) == 0:
            # insert the matching event's payload (schema-aligned)
            self.add([list(out_data)], ts)

    def contains_value(self, value: Any) -> bool:
        """`expr in Table` — single-attribute membership (first column or PK)."""
        if self.pk_positions and len(self.pk_positions) == 1:
            return value in self.pk_map
        return any(value in r for r in self.rows)

    def pk_lookup(self, key: Any) -> list[list]:
        """Single-PK point lookup (reference ``IndexOperator`` fast path)."""
        row = self.pk_map.get(key)
        return [list(row)] if row is not None else []

    def all_events(self, ts: int = 0) -> list[StreamEvent]:
        return [StreamEvent(ts, list(r)) for r in self.rows]

    # -- state ----------------------------------------------------------------
    def snapshot_state(self) -> dict:
        return {"rows": [list(r) for r in self.rows]}

    def restore_state(self, state: dict) -> None:
        self.rows = []
        self.pk_map = {}
        self.indexes = {p: {} for p in self.index_positions}
        self.add(state["rows"])


class RecordTableHandler:
    """Optional interception stage around a record store's operations
    (reference ``table/record/RecordTableHandler.java:41`` — there each op
    routes through the handler with a RecordTableHandlerCallback; here the
    underlying op arrives as the ``do`` callable and the default forwards).

    Override the ops you care about (audit, caching, latency injection);
    always return ``do(...)``'s result (or a transformed one)."""

    def init(self, app_name: str, definition: TableDefinition) -> None:
        self.app_name = app_name
        self.definition = definition
        self.id = f"{app_name}-{definition.id}-{type(self).__name__}"

    def add(self, timestamp: int, rows: list[list], do) -> None:
        return do(rows)

    def find(self, timestamp: int, params: dict, compiled, do) -> list[list]:
        return do(params, compiled)

    def delete(self, timestamp: int, params: dict, compiled, do) -> int:
        return do(params, compiled)

    def update(self, timestamp: int, params: dict, values: dict,
               compiled, do) -> int:
        return do(params, values, compiled)


class RecordTableHandlerManager:
    """Reference ``RecordTableHandlerManager`` — factory + registry of
    :class:`RecordTableHandler` instances, installed via
    ``SiddhiManager.set_record_table_handler_manager``."""

    def __init__(self):
        self.registered: dict[str, RecordTableHandler] = {}

    def generate_record_table_handler(self) -> RecordTableHandler:
        raise NotImplementedError

    def register_record_table_handler(self, element_id: str,
                                      handler: RecordTableHandler) -> None:
        self.registered[element_id] = handler

    def unregister_record_table_handler(self, element_id: str) -> None:
        self.registered.pop(element_id, None)


class AbstractRecordTable(Table):
    """External store SPI (reference ``record/AbstractRecordTable.java:57``).

    Subclass and implement the ``record_*`` hooks to back a table with an
    external store; register via the extension registry under
    ``store:<type>``. Condition pushdown (the queryable-record analog,
    ``AbstractQueryableRecordTable.java:99``): when a lookup condition
    converts to a :class:`StoreExpression`, it is offered ONCE to
    :meth:`record_compile_condition`; a store returning a non-None handle
    receives it (plus per-lookup parameter values) in ``record_find`` and
    must return pre-filtered rows. Stores that return None — the default —
    fall back to the exhaustive scan with host-side filtering.
    """

    extension_kind = "store"

    def init(self, definition: TableDefinition, options: dict) -> None:
        pass

    def record_add(self, rows: list[list]) -> None:
        raise NotImplementedError

    def record_compile_condition(self, store_expr: StoreExpression):
        """Translate a condition to a store-native form (e.g. a SQL WHERE
        template). None (default) = no pushdown; exhaustive scan."""
        return None

    def record_find(self, condition_params: dict,
                    compiled_condition=None) -> list[list]:
        raise NotImplementedError

    def record_delete(self, condition_params: dict,
                      compiled_condition=None) -> int:
        raise NotImplementedError

    def record_update(self, condition_params: dict, values: dict,
                      compiled_condition=None) -> int:
        raise NotImplementedError

    def record_purge(self, column: str, cutoff) -> bool:
        """OPTIONAL: delete rows where ``column`` < ``cutoff``; return True
        when performed. Default False — persisted aggregations then bound
        their reads by retention instead of deleting store rows."""
        return False

    def record_replace(self, match_cols: list[str], rows: list[list]) -> bool:
        """OPTIONAL upsert: delete rows whose ``match_cols`` equal an
        incoming row's, then add ``rows``; return True when performed.
        Default False — callers append and readers apply last-wins, so the
        log grows with superseded versions until the store supports this."""
        return False

    # set by the app builder when a RecordTableHandlerManager is installed
    handler: "RecordTableHandler | None" = None

    def _find_records(self, params: dict, compiled, ts: int) -> list[list]:
        # no-pushdown scans call record_find with ONE argument, as before
        # handlers existed — store subclasses may omit the optional
        # compiled_condition parameter entirely
        def do(p, c):
            return self.record_find(p, c) if c is not None \
                else self.record_find(p)
        if self.handler is not None:
            return self.handler.find(ts, params, compiled, do)
        return do(params, compiled)

    def add(self, rows, ts: int = 0) -> None:
        if self.handler is not None:
            self.handler.add(ts, rows, self.record_add)
        else:
            self.record_add(rows)

    def all_events(self, ts: int = 0) -> list[StreamEvent]:
        return [StreamEvent(ts, list(r))
                for r in self._find_records({}, None, ts)]

    def _pushdown(self, cond) -> tuple:
        """(compiled_condition | None, params dict) for this lookup."""
        if cond is None or cond.store_expr is None:
            return None, {}
        key = id(self)
        if key not in cond._store_compiled:
            cond._store_compiled[key] = \
                self.record_compile_condition(cond.store_expr)
        return cond._store_compiled[key], cond.param_fns

    def _params(self, param_fns: dict, out_data, ts: int) -> dict:
        frame = TableMatchFrame(None, out_data, ts)
        return {name: fn(frame) for name, fn in param_fns.items()}

    def find(self, cond, out_data, ts: int = 0) -> list[list]:
        compiled, param_fns = self._pushdown(cond)
        if compiled is not None:
            # the store pre-filters; rows come back final
            return self._find_records(
                self._params(param_fns, out_data, ts), compiled, ts)
        rows = self._find_records({}, None, ts)
        if cond is None:
            return rows
        return [r for r in rows if cond.fn(TableMatchFrame(r, out_data, ts))]

    def delete(self, cond, out_data, ts: int = 0) -> int:
        compiled, param_fns = self._pushdown(cond)
        if compiled is not None:
            params = self._params(param_fns, out_data, ts)
            if self.handler is not None:
                return self.handler.delete(
                    ts, params, compiled,
                    lambda p, c: self.record_delete(p, c))
            return self.record_delete(params, compiled)
        raise NotImplementedError(
            f"store table '{self.id}': delete requires condition pushdown "
            f"(record_compile_condition returned None)")

    def update(self, cond, out_data, setters, ts: int = 0) -> int:
        compiled, param_fns = self._pushdown(cond)
        if compiled is not None:
            # set values are computed ONCE per operation — row-dependent set
            # expressions (e.g. `set T.a = T.b`) would need per-row
            # evaluation the record SPI can't express. Probe each setter
            # with a row that RAISES on column access (a None row would let
            # None-tolerant expressions slip through and silently corrupt
            # every matched row).
            values = {}
            for pos, value_fn in setters:
                name = self.definition.attributes[pos].name
                try:
                    # the successful probe's value IS the operation value —
                    # re-evaluating would run side-effecting extension
                    # functions twice per update (advisor r3)
                    values[name] = value_fn(
                        TableMatchFrame(_RaisingRow(self.id), out_data, ts))
                    continue
                except _RowDependentSet:
                    raise NotImplementedError(
                        f"store table '{self.id}': set expression for "
                        f"'{name}' references table columns — per-row set "
                        f"expressions are not expressible through the "
                        f"record-store SPI") from None
                except Exception:       # noqa: BLE001 — unrelated probe
                    pass                # failure: let the real eval decide
                values[name] = value_fn(TableMatchFrame(None, out_data, ts))
            params = self._params(param_fns, out_data, ts)
            if self.handler is not None:
                return self.handler.update(
                    ts, params, values, compiled,
                    lambda p, v, c: self.record_update(p, v, c))
            return self.record_update(params, values, compiled)
        raise NotImplementedError(
            f"store table '{self.id}': update requires condition pushdown "
            f"(record_compile_condition returned None)")


class CacheTable(Table):
    """Bounded cache in front of a record store.

    Reference: ``table/CacheTable.java`` + policy subclasses
    ``CacheTable{FIFO,LRU,LFU}.java`` — configured via
    ``@store(..., @cache(size='100', cache.policy='LRU'))``. Write-through on
    mutations; primary-key ``find``s are served from the cache on hit; scan
    results are back-filled into the cache. When the whole store fits in the
    cache (``_complete``), scans are served from the cache too.
    """

    POLICIES = ("FIFO", "LRU", "LFU")

    def __init__(self, definition: TableDefinition, app_context, backing: Table,
                 max_size: int, policy: str = "FIFO"):
        super().__init__(definition, app_context)
        policy = policy.upper()
        if policy not in self.POLICIES:
            raise ValueError(f"unknown cache policy '{policy}' "
                             f"(expected one of {self.POLICIES})")
        self.backing = backing
        self.max_size = max(1, int(max_size))
        self.policy = policy
        self.pk_positions: list[int] = []
        pk = find_annotation(definition.annotations, "PrimaryKey")
        if pk:
            self.pk_positions = [
                definition.attribute_position(v) for v in pk.indexed_values()
            ]
        from collections import OrderedDict
        self._cache: "OrderedDict[Any, list]" = OrderedDict()
        self._freq: dict[Any, int] = {}
        self._complete = False      # cache mirrors the entire store
        self.cache_hits = 0
        app_context.register_state(f"table-cache-{self.id}", self)

    # -- policy bookkeeping ----------------------------------------------------
    def _key_of(self, row: list) -> Any:
        if self.pk_positions:
            return _pk_key(row, self.pk_positions)
        return tuple(row)

    def _touch(self, key: Any) -> None:
        if self.policy == "LRU":
            self._cache.move_to_end(key)
        elif self.policy == "LFU":
            self._freq[key] = self._freq.get(key, 0) + 1

    def _evict_one(self) -> None:
        if self.policy == "LFU":
            victim = min(self._cache, key=lambda k: self._freq.get(k, 0))
            self._cache.pop(victim)
            self._freq.pop(victim, None)
        else:   # FIFO and LRU both evict the head (LRU head = least recent)
            key, _ = self._cache.popitem(last=False)
            self._freq.pop(key, None)
        self._complete = False

    def _put(self, row: list) -> None:
        key = self._key_of(row)
        if key in self._cache:
            self._cache[key] = list(row)
            self._touch(key)
            return
        while len(self._cache) >= self.max_size:
            self._evict_one()
        self._cache[key] = list(row)
        if self.policy == "LFU":
            self._freq[key] = 1

    def _invalidate(self, row: list) -> None:
        key = self._key_of(row)
        self._cache.pop(key, None)
        self._freq.pop(key, None)
        # the row may still exist in the store with new values — the cache no
        # longer mirrors the store until the entry is re-fetched
        self._complete = False

    # -- table API (write-through) --------------------------------------------
    def add(self, rows: list[list], ts: int = 0) -> None:
        self.backing.add(rows, ts)
        fits = self._complete and \
            len(self._cache) + len(rows) <= self.max_size
        for r in rows:
            self._put(list(r))
        self._complete = fits

    def preload(self) -> None:
        """Load the store into the cache (reference preloads on connect)."""
        rows = self.backing.find(None, None)
        if len(rows) <= self.max_size:
            for r in rows:
                self._put(list(r))
            self._complete = True

    def find(self, cond: Optional[CompiledTableCondition],
             out_data: Optional[list], ts: int = 0) -> list[list]:
        if cond is None:
            if self._complete:
                return [list(r) for r in self._cache.values()]
            return self.backing.find(None, out_data, ts)
        if cond.pk_extractor is not None and len(self.pk_positions) >= 1:
            key = cond.pk_extractor(out_data)
            row = self._cache.get(key)
            if row is not None:
                self._touch(key)
                self.cache_hits += 1
                return [list(row)] if cond.fn(
                    TableMatchFrame(row, out_data, ts)) else []
        if self._complete:
            hits = [list(r) for r in self._cache.values()
                    if cond.fn(TableMatchFrame(r, out_data, ts))]
            for r in hits:
                self._touch(self._key_of(r))
            return hits
        rows = self.backing.find(cond, out_data, ts)
        for r in rows:
            self._put(list(r))
        return rows

    def delete(self, cond, out_data, ts: int = 0) -> int:
        victims = [r for r in self.backing.find(cond, out_data, ts)]
        n = self.backing.delete(cond, out_data, ts)
        for r in victims:
            self._invalidate(r)
        return n

    def update(self, cond, out_data, setters, ts: int = 0) -> int:
        before = self.backing.find(cond, out_data, ts)
        n = self.backing.update(cond, out_data, setters, ts)
        for r in before:
            self._invalidate(r)   # re-cached on next lookup with fresh values
        return n

    def update_or_add(self, cond, out_data, setters, ts: int = 0) -> None:
        if self.update(cond, out_data, setters, ts) == 0:
            self.add([list(out_data)], ts)

    def pk_lookup(self, key: Any) -> list[list]:
        row = self._cache.get(key)
        if row is not None:
            self._touch(key)
            self.cache_hits += 1
            return [list(row)]
        if self._complete:
            return []
        if hasattr(self.backing, "pk_lookup"):
            rows = self.backing.pk_lookup(key)
        else:
            pos = self.pk_positions[0] if len(self.pk_positions) == 1 else None
            rows = [r for r in self.backing.find(None, None)
                    if (r[pos] if pos is not None else None) == key] \
                if pos is not None else []
        for r in rows:
            self._put(list(r))
        return rows

    def contains_value(self, value: Any) -> bool:
        # single PK: membership = PK membership (InMemoryTable semantics)
        if len(self.pk_positions) == 1:
            if value in self._cache:
                self._touch(value)
                return True
            if self._complete:
                return False
            return bool(self.pk_lookup(value))
        # composite/no PK: any-column membership over the full row set
        rows = self._cache.values() if self._complete \
            else self.backing.find(None, None)
        return any(value in r for r in rows)

    def all_events(self, ts: int = 0) -> list[StreamEvent]:
        if self._complete:
            return [StreamEvent(ts, list(r)) for r in self._cache.values()]
        return [StreamEvent(ts, list(r)) for r in self.backing.find(None, None, ts)]

    # -- state ----------------------------------------------------------------
    # The cache is derived state: a restore invalidates it so lookups refetch
    # from the (authoritative) store.
    def snapshot_state(self) -> dict:
        return {}

    def restore_state(self, state: dict) -> None:
        self._cache.clear()
        self._freq.clear()
        self._complete = False


def build_store_tree(on_condition: Expression, classify, build_param):
    """AST → (StoreExpression node, param extractor fns) or (None, {}).

    ``classify(var)`` returns ``('attribute', name)`` for table columns,
    ``'param'`` for streaming-side refs, or ``'bail'`` when resolution is
    ambiguous; ``build_param(expr)`` returns an extractor fn or None. Any
    unconvertible sub-expression aborts the whole pushdown (the reference
    falls back to ExhaustiveCollectionExecutor there too)."""
    from ..query_api import (
        And as _And, Compare as _Compare, Constant as _Constant,
        MathExpr as _MathExpr, Minus as _Minus, Not as _Not, Or as _Or,
        Variable as _Variable,
    )
    from ..query_api.expression import CompareOp as _CmpOp, MathOp as _MathOp

    cmp_ops = {_CmpOp.EQ: "==", _CmpOp.NEQ: "!=", _CmpOp.LT: "<",
               _CmpOp.LE: "<=", _CmpOp.GT: ">", _CmpOp.GE: ">="}
    math_ops = {_MathOp.ADD: "+", _MathOp.SUB: "-", _MathOp.MUL: "*",
                _MathOp.DIV: "/", _MathOp.MOD: "%"}
    params: dict = {}
    counter = itertools.count()

    def walk(expr):
        if isinstance(expr, _Constant):
            return ("constant", expr.value)
        if isinstance(expr, _Variable):
            kind = classify(expr)
            if kind == "bail":
                return None
            if isinstance(kind, tuple) and kind[0] == "attribute":
                return kind
            # streaming-side value: becomes a per-lookup parameter
            val_fn = build_param(expr)
            if val_fn is None:
                return None
            name = f"p{next(counter)}"
            params[name] = val_fn
            return ("param", name)
        if isinstance(expr, _Compare):
            left, right = walk(expr.left), walk(expr.right)
            if left is None or right is None:
                return None
            return ("compare", cmp_ops[expr.op], left, right)
        if isinstance(expr, (_And, _Or)):
            left, right = walk(expr.left), walk(expr.right)
            if left is None or right is None:
                return None
            return ("and" if isinstance(expr, _And) else "or", left, right)
        if isinstance(expr, _Not):
            sub = walk(expr.expr)
            return None if sub is None else ("not", sub)
        if isinstance(expr, _MathExpr):
            left, right = walk(expr.left), walk(expr.right)
            if left is None or right is None:
                return None
            return ("math", math_ops[expr.op], left, right)
        if isinstance(expr, _Minus):
            sub = walk(expr.expr)
            return None if sub is None else \
                ("math", "-", ("constant", 0), sub)
        return None                 # functions / in-table / is-null etc.

    node = walk(on_condition)
    if node is None:
        return None, {}
    return node, params


def _build_store_expression(table_def, on_condition: Expression,
                            out_names: list[str], out_types: list[DataType],
                            app_context):
    """Table-lookup flavor: table refs by id/bare-name, params resolve
    against the matching event (TableMatchFrame)."""

    def classify(var):
        if var.stream_id == table_def.id or (
                var.stream_id is None
                and var.attribute not in out_names
                and var.attribute in table_def.attribute_names):
            if var.attribute not in table_def.attribute_names:
                return "bail"
            return ("attribute", var.attribute)
        return "param"

    def build_param(expr):
        ob = ExecutorBuilder(
            TableMatchResolver(table_def, out_names, out_types), app_context)
        try:
            val_fn, _ = ob.build(expr)
        except Exception:           # noqa: BLE001 — unresolvable → no pushdown
            return None
        return val_fn

    return build_store_tree(on_condition, classify, build_param)


def compile_table_condition(table: Table, on_condition: Optional[Expression],
                            out_names: list[str], out_types: list[DataType],
                            app_context) -> Optional[CompiledTableCondition]:
    if on_condition is None:
        return None
    resolver = TableMatchResolver(table.definition, out_names, out_types)
    builder = ExecutorBuilder(resolver, app_context)
    fn, _ = builder.build(on_condition)

    # store pushdown form (only meaningful for record tables, but cheap and
    # side-effect-free to build here for any table)
    store_expr = None
    param_fns: dict = {}
    record_backed = isinstance(table, AbstractRecordTable) or (
        isinstance(table, CacheTable)
        and isinstance(table.backing, AbstractRecordTable))
    if record_backed:
        node, param_fns = _build_store_expression(
            table.definition, on_condition, out_names, out_types, app_context)
        if node is not None:
            store_expr = StoreExpression(node)

    # PK fast path: `T.pk == <expr-over-out>` at top level of an AND chain.
    # A bare variable named like the PK only counts as the table side when the
    # resolver would NOT bind it to the matching event (out side wins there).
    pk_extractor = None
    if isinstance(table, (InMemoryTable, CacheTable)) and len(table.pk_positions) == 1:
        pk_pos = table.pk_positions[0]
        pk_name = table.definition.attributes[pk_pos].name
        allow_bare = pk_name not in out_names
        eq = _find_pk_equality(on_condition, table.id, pk_name, allow_bare)
        if eq is not None:
            out_builder = ExecutorBuilder(
                TableMatchResolver(table.definition, out_names, out_types),
                app_context)
            val_fn, _ = out_builder.build(eq)
            pk_extractor = lambda out: val_fn(TableMatchFrame(None, out))  # noqa: E731
    return CompiledTableCondition(fn, pk_extractor, store_expr, param_fns)


def _find_pk_equality(expr: Expression, table_id: str, pk_name: str,
                      allow_bare: bool = True):
    """Finds `T.pk == rhs` (rhs not referencing the table) in a top-level AND chain."""
    from ..query_api import And
    if isinstance(expr, And):
        return _find_pk_equality(expr.left, table_id, pk_name, allow_bare) or \
            _find_pk_equality(expr.right, table_id, pk_name, allow_bare)
    if isinstance(expr, Compare) and expr.op == CompareOp.EQ:
        for a, b in ((expr.left, expr.right), (expr.right, expr.left)):
            if isinstance(a, Variable) and a.attribute == pk_name and \
                    (a.stream_id == table_id
                     or (a.stream_id is None and allow_bare)) and \
                    not _references_table(b, table_id):
                return b
    return None


def _references_table(expr: Expression, table_id: str) -> bool:
    from ..query_api import And, AttributeFunction, MathExpr, Minus, Not, Or
    if isinstance(expr, Variable):
        return expr.stream_id == table_id
    for attr in ("left", "right", "expr"):
        sub = getattr(expr, attr, None)
        if isinstance(sub, Expression) and _references_table(sub, table_id):
            return True
    if isinstance(expr, AttributeFunction):
        return any(_references_table(a, table_id) for a in expr.args)
    return False
