"""Device execution backend integration: per-query offload with host fallback.

The north star (BASELINE.json): the compiled TPU path plugs in as an execution
backend for individual queries — the role the reference reserves for its
``@Extension``/StreamProcessor plugin boundary — while the host interpreter
remains the fallback (the reference's CPU ``QueryRuntime``).

Usage: annotate a query with ``@device`` (optionally ``@device(batch='4096')``).
The app builder tries the device compiler; on ``DeviceCompileError`` the query
silently builds on the host path instead (``@device(strict='true')`` raises).
Events route into a micro-batching bridge; device outputs flow back into the
target junction as CURRENT events. Batching semantics: outputs surface when a
micro-batch fills or on ``SiddhiAppRuntime.flush_device()`` (also invoked by
playback watermark advancement).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..query_api import (
    InsertIntoStream,
    JoinInputStream,
    Query,
    SingleInputStream,
    StateInputStream,
)
from ..query_api.annotation import find_annotation
from .event import EventType, StreamEvent

log = logging.getLogger("siddhi_tpu.device")


class DeviceQueryBridge:
    """Junction subscriber feeding a compiled device query; outputs re-enter the
    engine through the query's output junction."""

    def __init__(self, kind: str, runtime, app_context, stream_ids: list[str],
                 output_junction, query_name: str):
        self.kind = kind                  # 'stream' | 'nfa'
        self.runtime = runtime            # DeviceStreamRuntime | DeviceNFARuntime
        self.app_context = app_context
        self.stream_ids = stream_ids
        self.output_junction = output_junction
        self.query_name = query_name
        self.query_callbacks: list = []
        runtime.add_callback(self._on_rows)
        self._out_ts = 0

    # -- junction receiver(s) -------------------------------------------------
    def receiver_for(self, stream_id: str):
        bridge = self

        class _R:
            def receive(self, event: StreamEvent) -> None:
                bridge.on_event(stream_id, event)

        return _R()

    def on_event(self, stream_id: str, event: StreamEvent) -> None:
        if event.type != EventType.CURRENT:
            return
        self._out_ts = event.timestamp
        if self.kind == "stream":
            self.runtime.send(event.data, timestamp=event.timestamp)
        else:                       # 'nfa' | 'join': merged multi-stream batch
            self.runtime.send(stream_id, event.data, event.timestamp)

    def flush(self) -> None:
        self.runtime.flush()

    def _on_rows(self, rows: list[list]) -> None:
        if self.query_callbacks:
            from .event import Event
            evs = [Event(self._out_ts, row) for row in rows]
            for cb in self.query_callbacks:
                cb.receive(self._out_ts, evs, None)
        if self.output_junction is None:
            return
        for row in rows:
            self.output_junction.send_event(
                StreamEvent(self._out_ts, row, EventType.CURRENT))


def try_build_device_query(query: Query, app_context, stream_defs: dict,
                           get_junction, name: str) -> Optional[DeviceQueryBridge]:
    """Returns a bridge when the query opts in via @device AND compiles on the
    device path; None → caller builds the host runtime."""
    ann = find_annotation(query.annotations, "device")
    if ann is None:
        return None
    strict = (ann.get("strict") or "false").lower() == "true"
    batch = int(ann.get("batch") or 1024)
    slots = int(ann.get("slots") or 64)
    window_cap = int(ann.get("window") or 4096)

    from ..tpu.expr_compile import DeviceCompileError

    target = None
    try:
        if not isinstance(query.output_stream, InsertIntoStream):
            raise DeviceCompileError(
                "device path handles insert-into-stream outputs only")
        tid = query.output_stream.target_id
        if tid in app_context.tables or tid in app_context.named_windows:
            raise DeviceCompileError(
                f"device path cannot target table/window '{tid}'")
        target = get_junction(tid, query.output_stream.is_inner_stream)
        ist = query.input_stream
        if isinstance(ist, SingleInputStream):
            from ..tpu.batch import BatchBuilder
            from ..tpu.query_compile import CompiledStreamQuery

            d = stream_defs.get(ist.stream_id)
            if d is None:
                raise DeviceCompileError(f"undefined stream '{ist.stream_id}'")
            compiled = CompiledStreamQuery(query, d, batch_capacity=batch,
                                           window_capacity=window_cap)

            class _StreamRT:
                def __init__(self):
                    self.compiled = compiled
                    self.builder = BatchBuilder(compiled.schema, batch)
                    self.state = compiled.init_state()
                    self.callback = None

                def add_callback(self, fn):
                    self.callback = fn

                def send(self, row, timestamp=0):
                    self.builder.append(row, timestamp)
                    if self.builder.full:
                        self.flush()

                def flush(self):
                    if len(self.builder) == 0:
                        return
                    b = self.builder.emit()
                    self.state, out = self.compiled.step(self.state, b)
                    rows = self.compiled.decode_outputs(out)
                    self._check_counters()
                    if self.callback and rows:
                        self.callback(rows)

                def _check_counters(self):
                    # surface bounded-state overflow instead of silently
                    # diverging from the host semantics
                    for key, what in (("window_drops", "alive events evicted "
                                       "(raise @device(window='N'))"),
                                      ("ts_regressions", "out-of-order "
                                       "timestamps clamped")):
                        c = self.state.get(key)
                        if c is None:
                            continue
                        c = int(c)
                        if c > getattr(self, f"_warned_{key}", 0):
                            log.warning("query '%s': %d %s", name, c, what)
                            setattr(self, f"_warned_{key}", c)

                def snapshot_state(self):
                    import jax
                    return {"device": jax.device_get(self.state),
                            "dict": self.compiled.schema.snapshot_dictionaries()}

                def restore_state(self, st):
                    import jax
                    if isinstance(st, dict) and "device" in st:
                        self.compiled.schema.restore_dictionaries(
                            st.get("dict", {}))
                        self.state = jax.device_put(st["device"])
                    else:       # pre-round-3 snapshot shape
                        self.state = jax.device_put(st)

            rt = _StreamRT()
            bridge = DeviceQueryBridge("stream", rt, app_context,
                                       [ist.stream_id], target, name)
            bridge.output_schema = ([s.name for s in compiled.specs],
                                    [s.dtype for s in compiled.specs])
        elif isinstance(ist, StateInputStream):
            from ..tpu.nfa import DeviceNFACompiler, DeviceNFARuntime, MergedBatchBuilder

            compiler = DeviceNFACompiler(query, stream_defs, slots, batch)

            class _NFART(DeviceNFARuntime):
                def __init__(self):
                    self.compiler = compiler
                    self.builder = MergedBatchBuilder(
                        compiler.merged, batch, stream_defs)
                    self.state = compiler.init_state()
                    self.callback = None

            rt = _NFART()
            bridge = DeviceQueryBridge("nfa", rt, app_context,
                                       compiler.compiled.stream_ids, target, name)
            bridge.output_schema = ([n for n, _, _ in compiler.out_specs],
                                    [t for _, _, t in compiler.out_specs])
        elif isinstance(ist, JoinInputStream):
            from ..tpu.join_compile import CompiledJoinQuery
            from ..tpu.nfa import MergedBatchBuilder

            ring = int(ann.get("ring") or 1024)
            joined = int(ann.get("joined") or 2048)
            compiled = CompiledJoinQuery(
                query, dict(stream_defs), batch_capacity=batch,
                ring_capacity=ring, joined_capacity=joined)

            class _JoinRT:
                def __init__(self):
                    self.compiled = compiled
                    self.builder = MergedBatchBuilder(
                        compiled.merged, batch, dict(stream_defs))
                    self.state = compiled.init_state()
                    self.callback = None
                    self._warned_drops = 0

                def add_callback(self, fn):
                    self.callback = fn

                def send(self, stream_id, row, timestamp=0):
                    self.builder.append(stream_id, row, timestamp)
                    if self.builder.full:
                        self.flush()

                def flush(self):
                    if len(self.builder) == 0:
                        return
                    b = self.builder.emit()
                    self.state, out = self.compiled.step(self.state, b)
                    rows = self.compiled.decode_outputs(out)
                    drops = int(self.state["join_drops"]) + \
                        int(self.state["ring_drops"])
                    if drops > self._warned_drops:
                        log.warning(
                            "query '%s': %d joined rows/ring entries dropped "
                            "(raise @device(joined=/ring=))", name, drops)
                        self._warned_drops = drops
                    if self.callback and rows:
                        self.callback(rows)

                def snapshot_state(self):
                    import jax
                    return {"device": jax.device_get(self.state),
                            "dict": self.compiled.merged.snapshot_dictionaries()}

                def restore_state(self, st):
                    import jax
                    if isinstance(st, dict) and "device" in st:
                        self.compiled.merged.restore_dictionaries(st["dict"])
                        self.state = jax.device_put(st["device"])
                    else:       # pre-round-3 snapshot shape
                        self.state = jax.device_put(st)

            rt = _JoinRT()
            bridge = DeviceQueryBridge(
                "join", rt, app_context,
                [compiled.left_id, compiled.right_id], target, name)
            bridge.output_schema = ([n for (n, _, t, _) in compiled.out_specs],
                                    [t for (n, _, t, _) in compiled.out_specs])
        else:
            raise DeviceCompileError(
                "device path covers single-stream, pattern/sequence, and "
                "windowed stream-join inputs")
    except DeviceCompileError as e:
        if strict:
            raise
        log.info("query '%s' falls back to host path: %s", name, e)
        return None

    app_context.register_state(f"device-{name}", _BridgeState(bridge))
    return bridge


class _BridgeState:
    """Snapshot adapter: device state pytree is host-fetchable."""

    def __init__(self, bridge: DeviceQueryBridge):
        self.bridge = bridge

    def snapshot_state(self):
        self.bridge.flush()
        return self.bridge.runtime.snapshot_state()

    def restore_state(self, state):
        self.bridge.runtime.restore_state(state)
