"""Device execution backend integration: per-query offload with host fallback.

The north star (BASELINE.json): the compiled TPU path plugs in as an execution
backend for individual queries — the role the reference reserves for its
``@Extension``/StreamProcessor plugin boundary — while the host interpreter
remains the fallback (the reference's CPU ``QueryRuntime``).

Usage: annotate a query with ``@device`` (optionally ``@device(batch='4096')``).
The app builder tries the device compiler; on ``DeviceCompileError`` the query
silently builds on the host path instead (``@device(strict='true')`` raises).
Events route into a micro-batching bridge; device outputs flow back into the
target junction as CURRENT events. Batching semantics: outputs surface when a
micro-batch fills or on ``SiddhiAppRuntime.flush_device()`` (also invoked by
playback watermark advancement).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..query_api import (
    InsertIntoStream,
    JoinInputStream,
    OutputEventsFor,
    Query,
    SingleInputStream,
    StateInputStream,
)
from ..query_api.annotation import find_annotation
from ..flow.adaptive_batch import AdaptiveFlushMixin
from .event import Event, EventType, StreamEvent

log = logging.getLogger("siddhi_tpu.device")


class AsyncDeviceDriver:
    """Double-buffered async device pipeline: pack ∥ step ∥ emit.

    The VERDICT-named analog of the reference's ``@async`` Disruptor mode for
    the device path (``StreamJunction.java:279-316``), rebuilt as a software
    pipeline. Three edges, one FIFO:

    - **pack** (producer, engine lock held): the junction thread packs events
      into the runtime's staging builder; emitted batches enter this driver's
      bounded ring (``depth``);
    - **dispatch** (worker): ``rt.dispatch(batch)`` fires the jitted step and
      returns an UN-FENCED output token — JAX async dispatch returns while
      the device still computes, and the carried state round-trips through
      donated buffers (``jax.jit(..., donate_argnums=(0,))``), so dispatch is
      fire-and-forget;
    - **egress** (worker): ``rt.collect(token)`` fences (the ``np.asarray``
      inside decode is the only host sync on the path) and delivers rows
      under the engine lock.

    With ``window=2`` (double buffering) the worker keeps one dispatch in
    flight while fencing the previous token: the device computes batch N
    while the host decodes batch N−1 and the producer packs batch N+1.
    Tokens collect strictly FIFO, so a mid-pipeline device fault surfaces at
    its own egress slot — the DeviceGuard replays the failed batch's shadow
    there, after every earlier batch delivered, and can neither reorder nor
    double-emit a micro-batch.

    A latency-mode adaptive controller (``@app:adaptive(latency.target.ms)``)
    adds a **deadline flush**: when the pipeline idles with a partial batch
    staged longer than the controller's remaining latency budget, the worker
    flushes it — detection latency stays bounded by ~fill-wait + one step
    instead of waiting for capacity.
    """

    def __init__(self, rt, app_context, depth: int = 4, window: int = 2):
        import collections
        import threading
        self.rt = rt
        self.app_context = app_context
        self.depth = max(1, depth)
        # in-flight dispatch window: 2 = double buffering; runtimes whose
        # collect() reads live state (hopping drain) pin it to 1
        self.window = max(1, window) \
            if getattr(rt, "pipeline_safe", True) else 1
        self._q = collections.deque()            # packed, undispatched
        self._inflight = collections.deque()     # (batch, token, disp_s, err)
        self._cv = threading.Condition()
        self._busy = False          # dispatch/collect/delivery in flight
        self._paused = False
        self._stopped = False
        self.batches_stepped = 0
        self.step_seconds = 0.0          # cumulative dispatch+fence time
        self.pack_seconds = 0.0          # producer pack spans (from batches)
        self.busy_wall_seconds = 0.0     # wall the pipeline was processing
        self.starved_seconds = 0.0       # idle with a partial batch staging
        self.deadline_flushes = 0
        self._span_t0 = None
        # counter-check cadence under sustained load: on_drained normally
        # runs when the pipeline empties, but a saturated pipeline never
        # empties — force the bookkeeping every N collected batches (one
        # amortized fence per N steps) so overflow warnings still surface
        self.drain_check_every = 64
        self._since_drained = 0
        self._thread = threading.Thread(
            target=self._run, name="device-driver", daemon=True)
        self._thread.start()

    # -- producer side (engine lock held) ------------------------------------
    def submit(self, batch) -> None:
        with self._cv:
            # backpressure without deadlock: the producer usually holds the
            # engine lock the delivery path needs, so a full queue waits
            # briefly then grows (bounded in practice by the wait)
            if len(self._q) >= self.depth:
                self._cv.wait(timeout=0.2)
            self._q.append(batch)
            self._cv.notify_all()

    # -- introspection --------------------------------------------------------
    @property
    def pipeline_depth(self) -> int:
        """Batches in the driver: packed-but-undispatched + in flight."""
        return len(self._q) + len(self._inflight)

    def _wall_seconds(self) -> float:
        """Pipeline wall incl. the OPEN busy span — work counters grow per
        batch, so a gauge read mid-span (saturated pipelines may never
        drain) must see the matching wall or the ratios inflate unbounded."""
        wall = self.busy_wall_seconds + self.starved_seconds
        t0 = self._span_t0
        if t0 is not None:
            wall += max(0.0, time.perf_counter() - t0)
        return wall

    @property
    def overlap_efficiency(self) -> float:
        """(pack + step) work per unit of pipeline wall: 1.0 = serialized,
        2.0 = two equal phases perfectly hidden behind each other."""
        wall = self._wall_seconds()
        if wall <= 0.0:
            return 0.0
        return (self.pack_seconds + self.step_seconds) / wall

    @property
    def device_idle_frac(self) -> float:
        """Fraction of pipeline wall the device spent waiting on the host."""
        wall = self._wall_seconds()
        if wall <= 0.0:
            return 0.0
        return max(0.0, 1.0 - self.step_seconds / wall)

    # -- worker ---------------------------------------------------------------
    def _run(self) -> None:
        while True:
            action, batch = self._next_action()
            if action == "stop":
                return
            if action == "dispatch":
                self._dispatch(batch)
            elif action == "collect":
                self._collect_oldest()
            elif action == "drained":
                self._run_drained_checks()
            else:                       # 'deadline'
                self._deadline_flush()

    def _run_drained_checks(self) -> None:
        """Deferred host-sync bookkeeping (counter checks need device_get)
        — OUTSIDE the condition variable: producers blocked in submit()
        hold the engine lock, and a d2h fetch under _cv would freeze
        ingress for its whole round-trip."""
        self._since_drained = 0
        drained = getattr(self.rt, "on_drained", None)
        if drained is not None:
            try:
                drained()
            except Exception:   # noqa: BLE001 — bookkeeping must not kill
                # the sole device worker
                log.exception("on_drained failed")

    def _next_action(self):
        import time
        with self._cv:
            while True:
                if self._q and not self._paused \
                        and len(self._inflight) < self.window:
                    if self._span_t0 is None:
                        self._span_t0 = time.perf_counter()
                    self._busy = True
                    return "dispatch", self._q.popleft()
                if self._inflight:
                    # window full, paused, or queue empty: fence the oldest
                    # token (strict FIFO egress)
                    return "collect", None
                # pipeline drained: close the busy span, then idle-wait
                # (the drained bookkeeping runs in _run, outside this lock)
                if self._busy:
                    if self._span_t0 is not None:
                        self.busy_wall_seconds += \
                            time.perf_counter() - self._span_t0
                        self._span_t0 = None
                    self._busy = False
                    self._cv.notify_all()
                    return "drained", None
                if self._stopped:
                    return "stop", None
                wait_s = 0.5
                staging = self._builder_staging()
                if staging and not self._paused:
                    due_in = self._deadline_due_in_s()
                    if due_in is not None and due_in <= 0.0:
                        return "deadline", None
                    if due_in is not None:
                        wait_s = min(wait_s, max(due_in, 0.001))
                t0 = time.perf_counter()
                self._cv.wait(timeout=wait_s)
                if staging:
                    # the device sat idle while a partial batch staged — the
                    # starvation the overlap accounting must charge as wall
                    # (and, in latency mode, the deadline flush bounds)
                    self.starved_seconds += time.perf_counter() - t0

    def _builder_staging(self) -> bool:
        """Rows staged in the producer's builder while the worker idles —
        time spent here is device starvation, in any controller mode."""
        try:
            return len(self.rt.builder) > 0
        except Exception:   # noqa: BLE001 — advisory read without the lock
            return False

    def _deadline_ms(self):
        """Wall-clock flush deadline for partial batches, or None when no
        latency-mode controller is attached."""
        c = getattr(self.rt, "batch_controller", None)
        if c is None or getattr(c, "mode", "throughput") != "latency":
            return None
        if not self._builder_staging():
            return None
        return c.flush_deadline_ms

    def _deadline_due_in_s(self):
        deadline_ms = self._deadline_ms()
        if deadline_ms is None:
            return None
        t0 = getattr(self.rt.builder, "_pack_t0", None)
        if t0 is None:
            return None
        import time
        return deadline_ms / 1e3 - (time.perf_counter() - t0)

    def _deadline_flush(self) -> None:
        """Flush a partial batch whose staging age exceeded the latency
        budget (worker thread, takes the engine lock like any producer)."""
        with self.app_context.root_lock:
            due = self._deadline_due_in_s()
            if due is None or due > 0.0:
                return      # raced with a producer flush — nothing to do
            self.rt._count_flush("deadline")
            self.deadline_flushes += 1
            # the runtime's own flush: seal + emit + driver submit, so the
            # deadline path can never diverge from producer-side flushes
            self.rt.flush()

    def _dispatch(self, batch) -> None:
        import time
        self.pack_seconds += float(batch.get("pack_s", 0.0) or 0.0)
        t0 = time.perf_counter()
        err = None
        token = None
        try:
            token = self.rt.dispatch(batch)
        except Exception as e:  # noqa: BLE001 — without a DeviceGuard
            # installed a dispatch failure must not kill the worker; the
            # batch is consumed (counted at its egress slot)
            log.exception("device dispatch failed")
            err = e
        disp_s = time.perf_counter() - t0
        with self._cv:
            self._inflight.append((batch, token, t0, disp_s, err))
            self._cv.notify_all()

    def _collect_oldest(self) -> None:
        import time
        with self._cv:
            batch, token, t_disp0, disp_s, err = self._inflight.popleft()
        t0 = time.perf_counter()
        rows = []
        ok = False
        try:
            if err is None:
                rows = self.rt.collect(token)
                ok = True
        except Exception:   # noqa: BLE001 — an async-dispatched step's
            # failure surfaces at the fence; with the resilience layer
            # active the DeviceGuard has already rerouted the batch to the
            # host path before this can trigger
            log.exception("device step failed")
            rows = []
        fence_s = time.perf_counter() - t0
        dt = disp_s + fence_s
        self.step_seconds += dt
        self.batches_stepped += 1
        publish_s = 0.0
        if rows:
            tp0 = time.perf_counter()
            try:
                with self.app_context.root_lock:
                    # stamp outputs with the batch's own last event time —
                    # the producer-side _out_ts has already advanced to
                    # newer events by delivery time
                    self.rt.deliver(rows, batch.get("last_ts"))
            except Exception:   # noqa: BLE001 — a raising downstream
                # receiver must not kill the sole device worker, and the
                # probe below must still see this batch (FIFO trace groups)
                log.exception("device delivery failed")
            publish_s = time.perf_counter() - tp0
        try:
            # the probe must see EVERY consumed batch (success or not) or
            # its FIFO trace groups desynchronize; observed AFTER delivery
            # so the phase attribution covers the whole serial waterfall
            # (fill → pack → ring wait → dispatch → fence → publish)
            observe = getattr(self.rt, "observe_step", None)
            if observe is not None:
                t_emit = batch.get("_t_emit")
                queue_s = max(0.0, t_disp0 - t_emit) \
                    if t_emit is not None else 0.0
                queue_s += max(0.0, t0 - (t_disp0 + disp_s))
                observe(batch.get("count", 0), dt, device_path=ok, phases={
                    "fill_span_s": batch.get("pack_s", 0.0),
                    "pack_s": batch.get("pack_exec_s", 0.0),
                    "queue_s": queue_s,
                    "step_s": disp_s,
                    "fence_s": fence_s,
                    "publish_s": publish_s,
                    "cause": batch.get("_cause"),
                })
        except Exception:   # noqa: BLE001 — a raising observer must not
            # kill the sole device worker
            log.exception("step observer failed")
        self._since_drained += 1
        if self._since_drained >= self.drain_check_every:
            # sustained load never drains the pipeline: run the overflow
            # checks anyway (costs one fence per drain_check_every steps)
            self._run_drained_checks()
        with self._cv:
            self._cv.notify_all()

    # -- barriers --------------------------------------------------------------
    def quiesce(self, timeout: float = 60.0) -> bool:
        """Wait until the ring is empty and no dispatch, fence, or delivery
        is in flight. Must NOT be called while holding the engine lock (the
        worker's egress edge needs it)."""
        import time
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._q or self._inflight or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=min(remaining, 0.5))
        return True

    def snapshot_staged(self) -> list:
        """Queued-but-unstepped batches (numpy dicts) for checkpointing the
        receive-but-not-process gap. Call with the driver paused."""
        with self._cv:
            return list(self._q)

    def restore_staged(self, batches: list) -> None:
        with self._cv:
            self._q.clear()
            self._q.extend(batches)
            self._cv.notify_all()

    def flush_sync(self, cause=None) -> None:
        """Submit any partial batch and drain: device state reflects every
        event sent so far. Call without the engine lock. ``cause`` counts
        the flush and stamps the batch UNDER the lock — cause bookkeeping
        is single-slot, so it must not race producer-side flushes."""
        with self.app_context.root_lock:
            if len(self.rt.builder):
                if cause is not None:
                    self.rt._count_flush(cause)
                self.rt._seal()     # trace group closes WITH the emit,
                # under the lock producers pack under
                b = self.rt.builder.emit()
                b["_cause"] = self.rt._take_cause()
                self.submit(b)
        self.quiesce()

    def pause(self) -> None:
        """Freeze device-state mutation (snapshot walks read ``rt.state``).
        Waits for the whole in-flight cycle — step AND delivery — so a
        snapshot can't capture device state advanced past rows downstream
        hasn't seen. Must not be called holding the engine lock."""
        with self._cv:
            self._paused = True
            while self._busy:
                self._cv.wait(timeout=0.5)

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._paused = False
            self._cv.notify_all()
        self._thread.join(timeout=10.0)


class _DeviceRTBase(AdaptiveFlushMixin):
    """Shared packing→step dispatch for bridge runtimes: a full builder is
    either handed to the async driver (packing overlaps compute) or stepped
    synchronously.

    The step is two-phase: ``dispatch(batch)`` fires the jitted step without
    fencing (JAX async dispatch — state advances through donated buffers)
    and returns the un-fetched output pytree; ``collect(token)`` fences at
    the egress edge (the ``np.asarray`` inside decode) and returns rows.
    ``process`` is one dispatch immediately collected — the synchronous
    path, and the shape the DeviceGuard wraps on both phases. Host-sync
    bookkeeping that would stall the pipeline (counter checks read device
    scalars) lives in ``on_drained``, which the driver calls whenever the
    pipeline empties and the sync path calls after every flush."""

    driver = None
    callback = None
    pipeline_safe = True    # False → the driver pins the window to 1

    def add_callback(self, fn):
        self.callback = fn

    def dispatch(self, batch):
        """Fire-and-forget device step: advances ``self.state`` and returns
        the un-fenced output pytree as the egress token."""
        self.state, out = self.compiled.step(self.state, batch)
        return out

    def collect(self, out):
        """Egress fence + decode for one dispatched step."""
        return self.compiled.decode_outputs(out)

    def process(self, batch):
        """Synchronous step + decode (async: worker thread, no engine lock —
        device state is worker-owned)."""
        return self.collect(self.dispatch(batch))

    def on_drained(self):
        """Called when the pipeline empties — the safe point for host-sync
        bookkeeping (device_get with nothing in flight)."""

    def deliver(self, rows, emit_ts=None):
        fn = self.callback
        if fn and rows:
            if getattr(getattr(fn, "__self__", None),
                       "_on_rows_accepts_ts", False):
                fn(rows, emit_ts)
            else:           # plain user callback: rows only
                fn(rows)

    def flush(self):
        if len(self.builder) == 0:
            return
        self._seal()            # trace group closes exactly at the emit
        b = self.builder.emit()
        b["_cause"] = self._take_cause()    # phase attribution keys the
        # deadline-queueing share off the flush cause riding the batch
        if self.driver is not None:
            self.driver.submit(b)
            return
        self.deliver(self._timed_process(b), b.get("last_ts"))
        self.on_drained()

    def finalize(self):
        """Terminal flush at shutdown (kernels that hold an open segment
        override this via the runtime's ``finalize``)."""


class _LimiterSink:
    """Terminal processor behind the bridge's host-side rate limiter."""

    def __init__(self, bridge: "DeviceQueryBridge"):
        self.bridge = bridge

    def process(self, events: list[StreamEvent]) -> None:
        self.bridge._emit(events)


class DeviceQueryBridge:
    """Junction subscriber feeding a compiled device query; outputs re-enter the
    engine through the query's output junction.

    Output rate limiting (``output [all|first|last] every ...`` /
    ``output snapshot``) runs host-side on the decoded device rows — the
    limiters are sequential post-selector processors in the reference
    (``query/output/ratelimit/OutputRateLimiter.java:43``) and their
    semantics don't depend on chunking, so the same host classes apply
    verbatim after device decode. Device-emitted events carry the batch
    timestamp, so time-driven limiters key off that (documented divergence
    from per-event host timestamps, consistent with the device path's
    output stamping)."""

    def __init__(self, kind: str, runtime, app_context, stream_ids: list[str],
                 output_junction, query_name: str, async_mode: bool = False,
                 output_rate=None, pipeline_window: int = 2):
        self.kind = kind                  # 'stream' | 'nfa' | 'join'
        self.runtime = runtime            # DeviceStreamRuntime | DeviceNFARuntime
        self.app_context = app_context
        self.stream_ids = stream_ids
        self.output_junction = output_junction
        self.query_name = query_name
        self.query_callbacks: list = []
        self.guard = None                   # DeviceGuard (resilience layer)
        self.probe = None                   # DeviceStepProbe (observability)
        self._on_rows_accepts_ts = True     # deliver() passes the batch ts
        runtime.add_callback(self._on_rows)
        self._out_ts = 0
        self.rate_limiter = None
        if output_rate is not None:
            from .ratelimit import build_rate_limiter
            self.rate_limiter = build_rate_limiter(output_rate, app_context)
            self.rate_limiter.next = _LimiterSink(self)
        self.driver = None
        if async_mode:
            self.driver = AsyncDeviceDriver(runtime, app_context,
                                            window=pipeline_window)
            runtime.driver = self.driver

    # -- junction receiver(s) -------------------------------------------------
    def receiver_for(self, stream_id: str):
        bridge = self

        class _R:
            def receive(self, event: StreamEvent) -> None:
                bridge.on_event(stream_id, event)

        if self.kind == "stream" and hasattr(self.runtime, "send_columns"):
            # single-stream device queries take columnar chunks straight
            # into the staging BatchBuilder (append_columns — bulk
            # slice-copy, no per-event appends): the last per-event hop on
            # the DCN-ingest → device path the mesh fabric forwards over.
            # Merged (nfa/join) builders stay per-event by design — their
            # probe/trace FIFO is stamped per interleaved stream event.
            class _ColsR(_R):
                def receive_rows(self, rows: list, timestamps) -> None:
                    bridge.on_rows_chunk(stream_id, rows, timestamps)

                def receive_columns(self, cols: dict, ts, n: int) -> None:
                    bridge.on_columns_chunk(stream_id, cols, ts, n)

            return _ColsR()
        return _R()

    def on_event(self, stream_id: str, event: StreamEvent) -> None:
        if event.type != EventType.CURRENT:
            return
        probe = self.probe
        if probe is not None and probe.tracer is not None:
            # register BEFORE packing: a capacity flush inside send() steps
            # the batch this event is part of, closing the span right away
            tr = probe.tracer.active
            if tr is not None:
                probe.pending.append((tr, time.perf_counter_ns()))
        self._out_ts = event.timestamp
        if self.kind == "stream":
            self.runtime.send(event.data, timestamp=event.timestamp)
        else:                       # 'nfa' | 'join': merged multi-stream batch
            self.runtime.send(stream_id, event.data, event.timestamp)

    def _register_chunk_trace(self) -> None:
        """One pending probe-trace entry per CHUNK (the fleet stager's
        convention) — a chunk's events share one journey, and per-event
        registration is exactly the hop this path exists to remove."""
        probe = self.probe
        if probe is not None and probe.tracer is not None:
            tr = probe.tracer.active
            if tr is not None:
                probe.pending.append((tr, time.perf_counter_ns()))

    def on_rows_chunk(self, stream_id: str, rows: list, timestamps) -> None:
        """Zero-wrap row-chunk ingress (``deliver_rows``): no StreamEvent
        materialization, one trace registration per chunk."""
        self._register_chunk_trace()
        send = self.runtime.send
        for row, ts in zip(rows, timestamps):
            send(row, timestamp=ts)
        if timestamps:
            self._out_ts = timestamps[-1]

    def on_columns_chunk(self, stream_id: str, cols: dict, ts,
                         n: int) -> None:
        """Zero-object columnar ingress (``deliver_columns``): the chunk
        bulk-slice-copies into the staging builder via
        ``BatchBuilder.append_columns`` — no per-event appends at all."""
        if n == 0:
            return
        self._register_chunk_trace()
        self.runtime.send_columns(cols, ts)
        self._out_ts = int(ts[-1])

    def flush(self, cause: str = "drain") -> None:
        if self.driver is not None:
            # async: submit the partial batch and drain the device queue.
            # Must not hold the engine lock here (the worker's delivery
            # needs it); the cause is counted inside flush_sync UNDER the
            # lock so concurrent producer/deadline flushes can't swap the
            # single-slot pending cause
            self.driver.flush_sync(cause)
            return
        with self.app_context.root_lock:
            if len(self.runtime.builder):
                self.runtime._count_flush(cause)
            self.runtime.flush()

    def finalize(self) -> None:
        """Shutdown barrier: emit what an open device segment still holds
        (timeBatch terminal bucket — advisor r3)."""
        self.flush(cause="final")
        fin = getattr(self.runtime, "finalize", None)
        if fin is not None:
            fin()
        if self.driver is not None:
            self.driver.flush_sync()

    def _on_rows(self, rows: list[list], emit_ts=None) -> None:
        # async delivery passes the source batch's last event time; the
        # producer-side _out_ts may already have advanced past it
        ts = self._out_ts if emit_ts is None else emit_ts
        events = [StreamEvent(ts, row, EventType.CURRENT) for row in rows]
        if self.rate_limiter is not None:
            self.rate_limiter.process(events)   # → _LimiterSink → _emit
        else:
            self._emit(events)

    def _emit(self, events: list[StreamEvent]) -> None:
        if not events:
            return
        if self.query_callbacks:
            ts = events[-1].timestamp
            evs = [Event(e.timestamp, e.data) for e in events]
            for cb in self.query_callbacks:
                cb.receive(ts, evs, None)
        if self.output_junction is None:
            return
        for e in events:
            self.output_junction.send_event(e)


def _input_single_streams(ist) -> list[SingleInputStream]:
    """Every SingleInputStream reachable from a query input (join sides,
    pattern/sequence stream elements) — for whole-surface audits."""
    out: list[SingleInputStream] = []
    if isinstance(ist, SingleInputStream):
        out.append(ist)
    elif isinstance(ist, JoinInputStream):
        out.extend([ist.left, ist.right])
    elif isinstance(ist, StateInputStream):
        out.extend(ist.single_streams())
    return out


def try_build_device_query(query: Query, app_context, stream_defs: dict,
                           get_junction, name: str) -> Optional[DeviceQueryBridge]:
    """Returns a bridge when the query opts in via @device AND compiles on the
    device path; None → caller builds the host runtime."""
    ann = find_annotation(query.annotations, "device")
    if ann is None:
        return None
    strict = (ann.get("strict") or "false").lower() == "true"
    batch = int(ann.get("batch") or 1024)
    slots = int(ann.get("slots") or 64)
    window_cap = int(ann.get("window") or 4096)
    # in-flight dispatch window of the async pipeline (2 = double
    # buffering; 1 = serialize dispatch/egress, for A/B comparison)
    pipeline_window = int(ann.get("pipeline") or 2)

    def _input_stream_ids(ist) -> list[str]:
        if isinstance(ist, SingleInputStream):
            return [ist.stream_id]
        if isinstance(ist, StateInputStream):
            return ist.stream_ids()
        if isinstance(ist, JoinInputStream):
            out = []
            for side in (ist.left, ist.right):
                sid = getattr(side, "stream_id", None)
                if sid is not None:
                    out.append(sid)
            return out
        return []

    # async packing/compute overlap: explicit @device(async='true'), or any
    # input stream annotated @async (the reference's Disruptor opt-in)
    async_mode = (ann.get("async") or "false").lower() == "true"
    if not async_mode:
        for sid in _input_stream_ids(query.input_stream):
            d = stream_defs.get(sid)
            if d is not None and \
                    find_annotation(d.annotations, "async") is not None:
                async_mode = True
                break

    from ..tpu.expr_compile import DeviceCompileError

    target = None
    try:
        # ---- full Query-surface audit: anything the device compilers do not
        # model must raise DeviceCompileError (→ host fallback) here, never
        # silently drop semantics (reference surface: Query.java — selector
        # order-by/limit/offset QuerySelector.java:44, output_rate
        # OutputRateLimiter.java:43, fault/inner streams, events_for).
        sel = query.selector
        if sel is not None and (sel.order_by or sel.limit is not None
                                or sel.offset is not None):
            raise DeviceCompileError(
                "order by / limit / offset take the host path (device "
                "micro-batch chunking would change their per-chunk "
                "semantics)")
        if query.output_rate is not None:
            from ..query_api import EventOutputRate
            if not isinstance(query.output_rate, EventOutputRate):
                # time/snapshot limiters key off per-event output timestamps,
                # which device batching coarsens to the batch timestamp —
                # host fallback preserves exact semantics
                raise DeviceCompileError(
                    "time/snapshot output rate limiting takes the host path")
            if isinstance(query.input_stream, JoinInputStream):
                # host join selectors can feed EXPIRED events into the
                # limiter; the device join emits CURRENT rows only
                raise DeviceCompileError(
                    "output rate limiting on joins takes the host path")
            from ..query_api import OutputRateType
            if sel is not None and sel.group_by and \
                    query.output_rate.type in (OutputRateType.FIRST,
                                               OutputRateType.LAST):
                # grouped first/last emit PER KEY per batch (reference
                # FirstGroupByPerEventOutputRateLimiter); device rows do
                # not carry group keys through the limiter
                raise DeviceCompileError(
                    "group-by with first/last output rate limiting takes "
                    "the host path")
        if not isinstance(query.output_stream, InsertIntoStream):
            raise DeviceCompileError(
                "device path handles insert-into-stream outputs only")
        if query.output_stream.events_for != OutputEventsFor.CURRENT_EVENTS:
            raise DeviceCompileError(
                "insert into ... for expired/all events takes the host path "
                "(device kernels emit CURRENT rows only)")
        if query.output_stream.is_fault_stream:
            raise DeviceCompileError("fault-stream outputs take the host path")
        for s in _input_single_streams(query.input_stream):
            if s.is_fault_stream or s.is_inner_stream:
                raise DeviceCompileError(
                    "fault / partition-inner input streams take the host "
                    "path")
        tid = query.output_stream.target_id
        if tid in app_context.tables or tid in app_context.named_windows:
            raise DeviceCompileError(
                f"device path cannot target table/window '{tid}'")
        target = get_junction(tid, query.output_stream.is_inner_stream)
        ist = query.input_stream
        if isinstance(ist, SingleInputStream):
            from ..tpu.batch import BatchBuilder
            from ..tpu.query_compile import CompiledStreamQuery

            d = stream_defs.get(ist.stream_id)
            if d is None:
                raise DeviceCompileError(f"undefined stream '{ist.stream_id}'")
            compiled = CompiledStreamQuery(query, d, batch_capacity=batch,
                                           window_capacity=window_cap)
            if query.output_rate is not None and \
                    compiled.window_kind is not None:
                # host rate limiters count the window's EXPIRED events too
                # (selector → limiter → events_for filter); device kernels
                # emit CURRENT rows only, so the counts would diverge
                raise DeviceCompileError(
                    "output rate limiting on windowed queries takes the "
                    "host path")

            class _StreamRT(_DeviceRTBase):
                def __init__(self):
                    self.compiled = compiled
                    self.builder = BatchBuilder(compiled.schema, batch)
                    # drain steps run on the WORKER thread in async mode —
                    # they must not touch the producer's live builder
                    self._drain_builder = BatchBuilder(compiled.schema,
                                                       batch)
                    # hopping's collect() reads live state between steps:
                    # the driver pins its dispatch window to 1
                    self.pipeline_safe = compiled.window_kind != "hopping"
                    self.state = compiled.init_state()
                    # segment clock high-water: arrival ts, or the
                    # externalTimeBatch attribute column
                    self._tk_pos = (
                        d.attribute_position(compiled.time_key)
                        if compiled.time_key is not None else None)
                    self._last_clk = None

                def send(self, row, timestamp=0):
                    clk = timestamp if self._tk_pos is None \
                        else row[self._tk_pos]
                    if clk is not None:
                        self._last_clk = clk if self._last_clk is None \
                            else max(self._last_clk, clk)
                    self.builder.append(row, timestamp)
                    self._maybe_flush()

                def send_columns(self, cols, ts):
                    """Bulk columnar staging: the chunk slice-copies into
                    the builder (``append_columns``) across as many
                    micro-batches as it spans — flush causes and adaptive
                    thresholds behave exactly as per-event ``send``."""
                    import numpy as np
                    ts = np.asarray(ts, dtype=np.int64)
                    n = int(ts.shape[0])
                    if n == 0:
                        return
                    clk_col = ts if self._tk_pos is None else np.asarray(
                        cols[compiled.time_key].materialize()
                        if hasattr(cols[compiled.time_key], "materialize")
                        else cols[compiled.time_key])
                    try:
                        clk = clk_col.max()
                    except TypeError:    # object column with None values
                        vals = [v for v in clk_col if v is not None]
                        clk = max(vals) if vals else None
                    if clk is not None:
                        self._last_clk = clk if self._last_clk is None \
                            else max(self._last_clk, clk)
                    start = 0
                    while start < n:
                        take = self.builder.append_columns(cols, ts, start)
                        start += take
                        self._maybe_flush()
                        if take == 0 and len(self.builder):
                            # defensive: a full builder _maybe_flush did
                            # not drain (no controller, capacity race)
                            self.flush()

                def finalize(self):
                    """Force-close the open timeBatch bucket at shutdown: a
                    sentinel event two windows past the last segment-clock
                    value closes the terminal bucket the way the host's
                    boundary timer does (advisor r3 — streams that stop
                    sending must not lose their last bucket). For
                    externalTimeBatch the sentinel carries the far-future
                    value in the time ATTRIBUTE (the kernel's clock). The
                    sentinel lands in its own far-future segment and never
                    emits. Sessions need no terminal flush on this path:
                    currents pass through per arrival."""
                    if self.compiled.window_kind != "timeBatch" or \
                            self._last_clk is None:
                        return
                    self.flush()
                    sentinel = self._last_clk + \
                        2 * max(int(self.compiled.window_ms), 1)
                    row = [None] * len(self.compiled.schema.names)
                    if self._tk_pos is not None:
                        row[self._tk_pos] = sentinel
                    # a guarded builder excludes the sentinel from its
                    # host-fallback shadow (it is bookkeeping, not an event)
                    append = getattr(self.builder, "append_sentinel",
                                     self.builder.append)
                    append(row, sentinel)
                    self.flush()

                def collect(self, out):
                    """Egress fence + decode. Hopping drains deferred
                    boundary flushes here with empty steps — the runtime is
                    pipeline-unsafe, so the state read is this step's own."""
                    rows = self.compiled.decode_outputs(out)
                    if self.compiled.window_kind == "hopping":
                        from ..tpu.runtime import drain_hop_boundaries
                        self.state = drain_hop_boundaries(
                            self.compiled, self.state, self._drain_builder,
                            lambda o: rows.extend(
                                self.compiled.decode_outputs(o)))
                    return rows

                def on_drained(self):
                    # counter checks device_get state scalars — deferred to
                    # drain points so they never stall the pipeline
                    self._check_counters()

                def _check_counters(self):
                    # surface bounded-state overflow instead of silently
                    # diverging from the host semantics
                    for key, what in (("window_drops", "alive events evicted "
                                       "(raise @device(window='N'))"),
                                      ("ts_regressions", "out-of-order "
                                       "timestamps clamped"),
                                      ("group_collisions", "group-by keys "
                                       "collided in the dense table (raise "
                                       "@device key capacity)")):
                        c = self.state.get(key)
                        if c is None:
                            continue
                        c = int(c)
                        if c > getattr(self, f"_warned_{key}", 0):
                            log.warning("query '%s': %d %s", name, c, what)
                            setattr(self, f"_warned_{key}", c)

                def snapshot_state(self):
                    from ..tpu.batch import device_state_snapshot
                    return device_state_snapshot(self.state,
                                                 self.compiled.schema)

                def restore_state(self, st):
                    from ..tpu.batch import device_state_restore
                    self.state = device_state_restore(
                        st, self.compiled.schema)

            rt = _StreamRT()
            bridge = DeviceQueryBridge("stream", rt, app_context,
                                       [ist.stream_id], target, name,
                                       async_mode=async_mode,
                                       output_rate=query.output_rate,
                                       pipeline_window=pipeline_window)
            bridge.output_schema = ([s.name for s in compiled.specs],
                                    [s.dtype for s in compiled.specs])
        elif isinstance(ist, StateInputStream):
            from ..tpu.nfa import DeviceNFACompiler, DeviceNFARuntime, MergedBatchBuilder

            compiler = DeviceNFACompiler(query, stream_defs, slots, batch)

            class _NFART(DeviceNFARuntime):
                def __init__(self):
                    self.compiler = compiler
                    self.builder = MergedBatchBuilder(
                        compiler.merged, batch, stream_defs,
                        used_cols=compiler.used_cols)
                    # absent-start seeds arm their clock at the app's start
                    # time (host: seed placed at start() on the playback
                    # clock)
                    self.state = compiler.init_state(
                        app_context.current_time())
                    self.callback = None
                    self.driver = None

            rt = _NFART()
            bridge = DeviceQueryBridge("nfa", rt, app_context,
                                       compiler.compiled.stream_ids, target,
                                       name, async_mode=async_mode,
                                       output_rate=query.output_rate,
                                       pipeline_window=pipeline_window)
            bridge.output_schema = ([n for n, _, _ in compiler.out_specs],
                                    [t for _, _, t in compiler.out_specs])
        elif isinstance(ist, JoinInputStream):
            from ..tpu.join_compile import CompiledJoinQuery
            from ..tpu.nfa import MergedBatchBuilder

            ring = int(ann.get("ring") or 1024)
            joined = int(ann.get("joined") or 2048)
            compiled = CompiledJoinQuery(
                query, dict(stream_defs), batch_capacity=batch,
                ring_capacity=ring, joined_capacity=joined)

            class _JoinRT(_DeviceRTBase):
                def __init__(self):
                    self.compiled = compiled
                    self.builder = MergedBatchBuilder(
                        compiled.merged, batch, dict(stream_defs))
                    self.state = compiled.init_state()
                    self._warned_drops = 0

                def send(self, stream_id, row, timestamp=0):
                    self.builder.append(stream_id, row, timestamp)
                    self._maybe_flush()

                def on_drained(self):
                    # drop counters live in device state: check at drain
                    # points (device_get would stall the pipeline per-step)
                    drops = int(self.state["join_drops"]) + \
                        int(self.state["ring_drops"])
                    if drops > self._warned_drops:
                        log.warning(
                            "query '%s': %d joined rows/ring entries dropped "
                            "(raise @device(joined=/ring=))", name, drops)
                        self._warned_drops = drops

                def snapshot_state(self):
                    from ..tpu.batch import device_state_snapshot
                    return device_state_snapshot(self.state,
                                                 self.compiled.merged)

                def restore_state(self, st):
                    from ..tpu.batch import device_state_restore
                    self.state = device_state_restore(
                        st, self.compiled.merged)

            rt = _JoinRT()
            bridge = DeviceQueryBridge(
                "join", rt, app_context,
                [compiled.left_id, compiled.right_id], target, name,
                async_mode=async_mode, output_rate=query.output_rate,
                pipeline_window=pipeline_window)
            bridge.output_schema = ([n for (n, _, t, _) in compiled.out_specs],
                                    [t for (n, _, t, _) in compiled.out_specs])
        else:
            raise DeviceCompileError(
                "device path covers single-stream, pattern/sequence, and "
                "windowed stream-join inputs")
    except DeviceCompileError as e:
        if strict:
            raise
        log.info("query '%s' falls back to host path: %s", name, e)
        return None

    bridge.batch_capacity = batch       # pad-ratio denominator (observability)
    if app_context.adaptive_cfg is not None:
        # @app:adaptive: flush thresholds track observed rate/latency; the
        # query's own batch capacity caps the adjustable range
        from ..flow.adaptive_batch import AdaptiveBatchController
        cfg = dict(app_context.adaptive_cfg)
        cfg["max_batch"] = min(cfg.get("max_batch", batch), batch)
        cfg["min_batch"] = min(cfg.get("min_batch", 64), cfg["max_batch"])
        rt.batch_controller = AdaptiveBatchController(**cfg)
    # device quarantine: a RUNTIME step failure (compile-time failures fell
    # back above) reroutes the batch through the host interpreter, and
    # repeated failures circuit-break the device path itself
    resilience = getattr(app_context.runtime, "resilience", None)
    if resilience is not None:
        bridge.guard = resilience.guard_device(
            rt, query, name, dict(stream_defs), get_junction, bridge.kind)
        resilience.bind_bridge(bridge.guard, bridge)
    app_context.register_state(f"device-{name}", _BridgeState(bridge))
    return bridge


class _BridgeState:
    """Snapshot adapter: device state pytree is host-fetchable."""

    def __init__(self, bridge: DeviceQueryBridge):
        self.bridge = bridge

    def snapshot_state(self):
        limiter = self.bridge.rate_limiter
        if self.bridge.driver is None:
            self.bridge.flush()
            st = self.bridge.runtime.snapshot_state()
            if limiter is None:
                return st
            return {"rt": st, "limiter": limiter.snapshot_state()}
        # async mode: SiddhiAppRuntime._pre_snapshot already flushed + paused
        # the driver (flushing here would deadlock — we hold root_lock and
        # the worker's delivery phase needs it). Events that raced in between
        # the pre-drain and this lock acquisition sit in the builder / driver
        # queue — checkpoint them as staged batches so the cut is consistent
        # with the host-side state walked under the same lock.
        st = {
            "rt": self.bridge.runtime.snapshot_state(),
            "staged": self.bridge.driver.snapshot_staged(),
            "builder": self.bridge.runtime.builder.snapshot(),
        }
        if limiter is not None:
            st["limiter"] = limiter.snapshot_state()
        return st

    def restore_state(self, state):
        if isinstance(state, dict) and "rt" in state:
            if self.bridge.rate_limiter is not None and "limiter" in state:
                self.bridge.rate_limiter.restore_state(state["limiter"])
            if "staged" not in state:       # sync-mode shape with a limiter
                self.bridge.runtime.restore_state(state["rt"])
                return
            # async-mode snapshot shape — also restorable into a runtime
            # whose async opt-in was removed: staged batches are stepped
            # synchronously instead of re-queued
            self.bridge.runtime.restore_state(state["rt"])
            self.bridge.runtime.builder.restore(state["builder"])
            if self.bridge.driver is not None:
                self.bridge.driver.restore_staged(state["staged"])
            else:
                rt = self.bridge.runtime
                for batch in state["staged"]:
                    rt.deliver(rt.process(batch), batch.get("last_ts"))
            return
        self.bridge.runtime.restore_state(state)
