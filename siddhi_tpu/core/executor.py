"""Expression executors (host interpreter).

Reference: ``io.siddhi.core.executor`` — the per-type executor matrix
(``executor/condition/compare/*`` ~17 classes per operator, ``executor/math/*``,
``executor/function/*``) collapses here into closures with build-time type
propagation. The same AST is separately compiled to jnp programs by
``siddhi_tpu/tpu/expr_compile.py``; this version is the semantic oracle.

An executor is ``fn(frame) -> value`` where ``frame`` resolves attribute references:
  - ``StreamFrame``  — single-stream queries
  - ``StateFrame``   — pattern/sequence queries (alias → bound events)
  - ``JoinFrame``    — two-sided joins
  - ``RowFrame``     — table rows / output events (having / order-by)
"""

from __future__ import annotations

import math
import time
import uuid as _uuid
from typing import Any, Callable, Optional

from ..query_api import (
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    DataType,
    Expression,
    In,
    IsNull,
    MathExpr,
    MathOp,
    Minus,
    Not,
    Or,
    Variable,
)
from .event import StateEvent, StreamEvent


class ExecutorBuildError(Exception):
    pass


# ---------------------------------------------------------------------------
# Frames
# ---------------------------------------------------------------------------

class StreamFrame:
    __slots__ = ("event",)

    def __init__(self, event: StreamEvent):
        self.event = event

    def timestamp(self) -> int:
        return self.event.timestamp


class RowFrame:
    """Positional row (table rows, selector output for having/order-by)."""
    __slots__ = ("data", "ts")

    def __init__(self, data: list, ts: int = 0):
        self.data = data
        self.ts = ts

    def timestamp(self) -> int:
        return self.ts


class StateFrame:
    __slots__ = ("state", "current_alias", "current_event")

    def __init__(self, state: StateEvent, current_alias: Optional[str] = None,
                 current_event: Optional[StreamEvent] = None):
        self.state = state
        self.current_alias = current_alias   # alias being evaluated right now
        self.current_event = current_event   # candidate event (not yet bound)

    def timestamp(self) -> int:
        if self.current_event is not None:
            return self.current_event.timestamp
        return self.state.timestamp or 0


class JoinFrame:
    __slots__ = ("left", "right", "ts")

    def __init__(self, left: Optional[StreamEvent], right: Optional[StreamEvent],
                 ts: int = 0):
        self.left = left
        self.right = right
        self.ts = ts

    def timestamp(self) -> int:
        return self.ts


# ---------------------------------------------------------------------------
# Variable resolution strategies
# ---------------------------------------------------------------------------

class VariableResolver:
    """Build-time resolution of a Variable to a frame accessor."""

    def resolve(self, var: Variable) -> tuple[Callable[[Any], Any], DataType]:
        raise NotImplementedError


class StreamResolver(VariableResolver):
    def __init__(self, definition):
        self.definition = definition

    def resolve(self, var: Variable):
        if var.stream_id is not None and var.stream_id != self.definition.id:
            # alias reference to this same stream is allowed
            pass
        pos = self.definition.attribute_position(var.attribute)
        dtype = self.definition.attributes[pos].type
        return (lambda f: f.event.data[pos]), dtype


class RowResolver(VariableResolver):
    """Resolve against a positional schema [(name, dtype), ...]."""

    def __init__(self, names: list[str], dtypes: list[DataType], table_id: Optional[str] = None):
        self.names = names
        self.dtypes = dtypes
        self.table_id = table_id

    def resolve(self, var: Variable):
        if var.attribute not in self.names:
            raise ExecutorBuildError(
                f"attribute '{var.attribute}' not found in {self.names}")
        pos = self.names.index(var.attribute)
        return (lambda f: f.data[pos]), self.dtypes[pos]


class StateResolver(VariableResolver):
    """Pattern context: ``e1.price``, ``e2[0].price``, bare ``price`` (current)."""

    def __init__(self, alias_defs: dict, default_alias: Optional[str] = None):
        self.alias_defs = alias_defs          # alias -> StreamDefinition
        self.default_alias = default_alias    # alias whose candidate is being tested

    def resolve(self, var: Variable):
        alias = var.stream_id
        if alias is None:
            # bare attribute: candidate event of the current state
            if self.default_alias is None:
                # fall back: unique attribute across alias defs
                owners = [
                    a for a, d in self.alias_defs.items()
                    if var.attribute in d.attribute_names
                ]
                if not owners:
                    raise ExecutorBuildError(f"cannot resolve '{var.attribute}'")
                alias = owners[0]
            else:
                alias = self.default_alias
        if alias not in self.alias_defs:
            raise ExecutorBuildError(f"unknown event reference '{alias}'")
        d = self.alias_defs[alias]
        pos = d.attribute_position(var.attribute)
        dtype = d.attributes[pos].type
        idx = var.stream_index

        def get(f: StateFrame, alias=alias, pos=pos, idx=idx):
            if f.current_alias == alias and f.current_event is not None and idx is None:
                return f.current_event.data[pos]
            ev = f.state.get(alias, idx)
            return None if ev is None else ev.data[pos]

        return get, dtype


class JoinResolver(VariableResolver):
    def __init__(self, left_ref: str, left_def, right_ref: str, right_def):
        self.left_ref = left_ref
        self.left_def = left_def
        self.right_ref = right_ref
        self.right_def = right_def

    def resolve(self, var: Variable):
        sid = var.stream_id
        if sid == self.left_ref:
            side, d = "left", self.left_def
        elif sid == self.right_ref:
            side, d = "right", self.right_def
        elif sid is None:
            in_l = var.attribute in self.left_def.attribute_names
            in_r = var.attribute in self.right_def.attribute_names
            if in_l and in_r:
                raise ExecutorBuildError(
                    f"ambiguous attribute '{var.attribute}' in join")
            if in_l:
                side, d = "left", self.left_def
            elif in_r:
                side, d = "right", self.right_def
            else:
                raise ExecutorBuildError(f"unknown attribute '{var.attribute}'")
        else:
            raise ExecutorBuildError(f"unknown stream reference '{sid}' in join")
        pos = d.attribute_position(var.attribute)
        dtype = d.attributes[pos].type

        if side == "left":
            return (lambda f: None if f.left is None else f.left.data[pos]), dtype
        return (lambda f: None if f.right is None else f.right.data[pos]), dtype


# ---------------------------------------------------------------------------
# Type promotion
# ---------------------------------------------------------------------------

_NUM_ORDER = [DataType.INT, DataType.LONG, DataType.FLOAT, DataType.DOUBLE]


def promote(a: DataType, b: DataType) -> DataType:
    if a in _NUM_ORDER and b in _NUM_ORDER:
        return _NUM_ORDER[max(_NUM_ORDER.index(a), _NUM_ORDER.index(b))]
    if a == b:
        return a
    return DataType.OBJECT


# ---------------------------------------------------------------------------
# Builder
# ---------------------------------------------------------------------------

class ExecutorBuilder:
    def __init__(self, resolver: VariableResolver, context=None,
                 extra_functions: Optional[dict] = None):
        self.resolver = resolver
        self.context = context                    # SiddhiAppContext (tables for `in`)
        self.extra_functions = extra_functions or {}

    def build(self, expr: Expression) -> tuple[Callable[[Any], Any], DataType]:
        if isinstance(expr, Constant):
            v = expr.value
            return (lambda f: v), expr.type
        if type(expr).__name__ == "ParamRef":
            # fleet parameter slot (normalized query ASTs): the scalar
            # interpreter never executes those plans — the tpu passes
            # re-compile predicates with slot support — but structural
            # compilers (PatternCompiler) walk the AST eagerly, so give
            # them a loud stub instead of a build failure
            def _no_scalar(f):
                raise ExecutorBuildError(
                    "fleet ParamRef has no scalar executor")
            return _no_scalar, expr.type
        if isinstance(expr, Variable):
            return self.resolver.resolve(expr)
        if isinstance(expr, And):
            lf, _ = self.build(expr.left)
            rf, _ = self.build(expr.right)
            return (lambda f: bool(lf(f)) and bool(rf(f))), DataType.BOOL
        if isinstance(expr, Or):
            lf, _ = self.build(expr.left)
            rf, _ = self.build(expr.right)
            return (lambda f: bool(lf(f)) or bool(rf(f))), DataType.BOOL
        if isinstance(expr, Not):
            f1, _ = self.build(expr.expr)
            return (lambda f: not bool(f1(f))), DataType.BOOL
        if isinstance(expr, Compare):
            return self._build_compare(expr)
        if isinstance(expr, MathExpr):
            return self._build_math(expr)
        if isinstance(expr, Minus):
            f1, t1 = self.build(expr.expr)
            return (lambda f: None if f1(f) is None else -f1(f)), t1
        if isinstance(expr, IsNull):
            return self._build_is_null(expr)
        if isinstance(expr, In):
            return self._build_in(expr)
        if isinstance(expr, AttributeFunction):
            return self._build_function(expr)
        raise ExecutorBuildError(f"unsupported expression {expr!r}")

    # -- comparisons ---------------------------------------------------------
    _NUMERIC = {DataType.INT, DataType.LONG, DataType.FLOAT, DataType.DOUBLE}

    def _build_compare(self, expr: Compare):
        lf, lt = self.build(expr.left)
        rf, rt = self.build(expr.right)
        op = expr.op
        # incompatible operand types fail at BUILD time (reference
        # StringCompareTestCase/BooleanCompareTestCase expect
        # SiddhiAppCreationException for e.g. double != string); unknown/
        # OBJECT types stay permissive
        if lt is not None and rt is not None and lt != rt:
            groups = (self._NUMERIC, {DataType.STRING}, {DataType.BOOL})
            lg = next((g for g in groups if lt in g), None)
            rg = next((g for g in groups if rt in g), None)
            if lg is not None and rg is not None and lg is not rg:
                raise ExecutorBuildError(
                    f"cannot compare {lt.value} with {rt.value} "
                    f"({expr.op.value})")

        def cmp(f):
            a, b = lf(f), rf(f)
            if a is None or b is None:
                return False
            if op == CompareOp.EQ:
                return a == b
            if op == CompareOp.NEQ:
                return a != b
            if op == CompareOp.LT:
                return a < b
            if op == CompareOp.LE:
                return a <= b
            if op == CompareOp.GT:
                return a > b
            return a >= b

        return cmp, DataType.BOOL

    # -- math ----------------------------------------------------------------
    def _build_math(self, expr: MathExpr):
        lf, lt = self.build(expr.left)
        rf, rt = self.build(expr.right)
        rtype = promote(lt, rt)
        op = expr.op
        int_result = rtype in (DataType.INT, DataType.LONG)

        def calc(f):
            a, b = lf(f), rf(f)
            if a is None or b is None:
                return None
            if op == MathOp.ADD:
                return a + b
            if op == MathOp.SUB:
                return a - b
            if op == MathOp.MUL:
                return a * b
            if op == MathOp.DIV:
                if int_result:
                    if b == 0:
                        return None
                    q = abs(a) // abs(b)           # Java truncation toward zero
                    return q if (a >= 0) == (b >= 0) else -q
                return a / b if b != 0 else (math.inf if a > 0 else -math.inf if a < 0 else math.nan)
            # MOD — Java semantics: result sign follows dividend
            if b == 0:
                return None if int_result else math.nan
            return math.fmod(a, b) if not int_result else int(math.fmod(a, b))

        return calc, rtype

    def _build_is_null(self, expr: IsNull):
        # `e1 is null` may parse as IsNull(Variable('e1')): resolve a bare name
        # that is actually a pattern alias or join side to the stream form
        sid, idx = expr.stream_id, expr.stream_index
        if sid is None and isinstance(expr.expr, Variable) \
                and expr.expr.stream_id is None:
            name = expr.expr.attribute
            if isinstance(self.resolver, StateResolver) and name in self.resolver.alias_defs:
                sid, idx = name, expr.expr.stream_index
            elif isinstance(self.resolver, JoinResolver) and name in (
                    self.resolver.left_ref, self.resolver.right_ref):
                sid, idx = name, None
        if sid is not None:
            if isinstance(self.resolver, JoinResolver):
                is_left = sid == self.resolver.left_ref

                def isnull_side(f, is_left=is_left):
                    return (f.left is None) if is_left else (f.right is None)

                return isnull_side, DataType.BOOL

            def isnull_stream(f, sid=sid, idx=idx):
                if isinstance(f, StateFrame):
                    return f.state.get(sid, idx) is None
                return False

            return isnull_stream, DataType.BOOL
        f1, _ = self.build(expr.expr)
        return (lambda f: f1(f) is None), DataType.BOOL

    def _build_in(self, expr: In):
        f1, _ = self.build(expr.expr)
        source_id = expr.source_id
        ctx = self.context
        if ctx is None:
            raise ExecutorBuildError("'in' requires app context with tables")

        def contains(f):
            table = ctx.get_table(source_id)
            return table.contains_value(f1(f))

        return contains, DataType.BOOL

    # -- functions -----------------------------------------------------------
    def _build_function(self, expr: AttributeFunction):
        name = expr.name
        key = f"{expr.namespace}:{name}" if expr.namespace else name
        args = [self.build(a) for a in expr.args]
        fns = [a[0] for a in args]
        types = [a[1] for a in args]

        # extension / user scalar functions
        if self.context is not None:
            ext = self.context.lookup_scalar_function(expr.namespace, name)
            if ext is not None:
                from .extension import validate_extension_args
                try:
                    validate_extension_args(type(ext), types)
                except TypeError as e:
                    raise ExecutorBuildError(str(e)) from None
                fn, rt = ext.bind(fns, types)
                return fn, rt
        if key in self.extra_functions:
            fn, rt = self.extra_functions[key](fns, types)
            return fn, rt

        builder = _BUILTIN_FUNCTIONS.get(name if expr.namespace is None else key)
        if builder is None:
            raise ExecutorBuildError(f"unknown function '{key}'")
        return builder(fns, types)


# ---------------------------------------------------------------------------
# Built-in scalar functions (reference: core/executor/function/, 20 built-ins)
# ---------------------------------------------------------------------------

def _fn_coalesce(fns, types):
    def run(f):
        for fn in fns:
            v = fn(f)
            if v is not None:
                return v
        return None
    return run, types[0] if types else DataType.OBJECT


_CONVERT_TYPES = {
    "string": DataType.STRING, "int": DataType.INT, "long": DataType.LONG,
    "float": DataType.FLOAT, "double": DataType.DOUBLE, "bool": DataType.BOOL,
}

_PY_CASTS = {
    DataType.STRING: str,
    DataType.INT: int,
    DataType.LONG: int,
    DataType.FLOAT: float,
    DataType.DOUBLE: float,
}


def _fn_convert(fns, types):
    if len(fns) != 2:
        raise ExecutorBuildError("convert(value, 'type') needs 2 args")
    target_fn = fns[1]
    target = _CONVERT_TYPES.get(str(target_fn(None)).lower() if _is_const(fns[1]) else "", None)

    def run(f):
        v = fns[0](f)
        t = target or _CONVERT_TYPES.get(str(fns[1](f)).lower())
        if v is None or t is None:
            return None
        try:
            if t == DataType.BOOL:
                if isinstance(v, str):
                    return v.lower() == "true"
                return bool(v)
            return _PY_CASTS[t](v)
        except (ValueError, TypeError):
            return None

    return run, target or DataType.OBJECT


def _is_const(fn) -> bool:
    try:
        fn(None)
        return True
    except Exception:
        return False


def _fn_cast(fns, types):
    return _fn_convert(fns, types)


def _fn_if_then_else(fns, types):
    if len(fns) != 3:
        raise ExecutorBuildError("ifThenElse(cond, a, b) needs 3 args")
    return (lambda f: fns[1](f) if bool(fns[0](f)) else fns[2](f)), promote(types[1], types[2])


def _fn_uuid(fns, types):
    return (lambda f: str(_uuid.uuid4())), DataType.STRING


def _fn_current_time_millis(fns, types):
    return (lambda f: int(time.time() * 1000)), DataType.LONG


def _fn_event_timestamp(fns, types):
    if fns:
        return fns[0], DataType.LONG
    return (lambda f: f.timestamp()), DataType.LONG


def _fn_maximum(fns, types):
    def run(f):
        vals = [fn(f) for fn in fns]
        vals = [v for v in vals if v is not None]
        return max(vals) if vals else None
    return run, types[0] if types else DataType.OBJECT


def _fn_minimum(fns, types):
    def run(f):
        vals = [fn(f) for fn in fns]
        vals = [v for v in vals if v is not None]
        return min(vals) if vals else None
    return run, types[0] if types else DataType.OBJECT


def _fn_instance_of(dtype: DataType, pytypes):
    def builder(fns, types):
        def run(f):
            v = fns[0](f)
            if dtype == DataType.BOOL:
                return isinstance(v, bool)
            if dtype in (DataType.INT, DataType.LONG):
                return isinstance(v, int) and not isinstance(v, bool)
            return isinstance(v, pytypes)
        return run, DataType.BOOL
    return builder


def _fn_create_set(fns, types):
    def run(f):
        s = set()
        v = fns[0](f)
        if v is not None:
            s.add(v)
        return s
    return run, DataType.OBJECT


def _fn_size_of_set(fns, types):
    return (lambda f: len(fns[0](f)) if fns[0](f) is not None else 0), DataType.INT


def _fn_default(fns, types):
    return (lambda f: fns[0](f) if fns[0](f) is not None else fns[1](f)), types[0]


def _fn_log(fns, types):
    import logging
    logger = logging.getLogger("siddhi_tpu.log")

    def run(f):
        vals = [fn(f) for fn in fns]
        logger.info(" ".join(str(v) for v in vals))
        return True
    return run, DataType.BOOL


_BUILTIN_FUNCTIONS: dict[str, Callable] = {
    "coalesce": _fn_coalesce,
    "convert": _fn_convert,
    "cast": _fn_cast,
    "ifThenElse": _fn_if_then_else,
    "UUID": _fn_uuid,
    "currentTimeMillis": _fn_current_time_millis,
    "eventTimestamp": _fn_event_timestamp,
    "maximum": _fn_maximum,
    "minimum": _fn_minimum,
    "instanceOfString": _fn_instance_of(DataType.STRING, str),
    "instanceOfInteger": _fn_instance_of(DataType.INT, int),
    "instanceOfLong": _fn_instance_of(DataType.LONG, int),
    "instanceOfFloat": _fn_instance_of(DataType.FLOAT, float),
    "instanceOfDouble": _fn_instance_of(DataType.DOUBLE, float),
    "instanceOfBoolean": _fn_instance_of(DataType.BOOL, bool),
    "createSet": _fn_create_set,
    "sizeOfSet": _fn_size_of_set,
    "default": _fn_default,
    "log": _fn_log,
}
