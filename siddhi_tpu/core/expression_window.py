"""Expression windows: retain events while a condition over the buffer holds.

Reference: ``ExpressionWindowProcessor`` / ``ExpressionBatchWindowProcessor`` —
``#window.expression('count() <= 20')``, ``#window.expressionBatch('last.ts -
first.ts < 5000')``. The expression sees:

- bare attributes → the newest (just-arrived) event
- ``first.attr`` / ``last.attr`` → oldest / newest buffered event
- ``count()``, ``sum(x)``, ``avg(x)``, ``min(x)``, ``max(x)`` → over the buffer
- ``eventTimestamp(first)`` / ``eventTimestamp(last)`` → buffer boundary times

Sliding form: on arrival, evict oldest events until the expression holds.
Batch form: when the expression turns false, flush the buffered batch (expiring
the previous batch) and start fresh with the new event.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..query_api import AttributeFunction, DataType, Variable
from ..query_api.definition import StreamDefinition
from .event import EventType, StreamEvent
from .executor import ExecutorBuilder, VariableResolver
from .windows import WindowProcessor


class _BufferFrame:
    __slots__ = ("buffer", "newest")

    def __init__(self, buffer: list[StreamEvent], newest: StreamEvent):
        self.buffer = buffer
        self.newest = newest

    def timestamp(self) -> int:
        return self.newest.timestamp


class _AggCache:
    """Running aggregates over a FIFO buffer, resynced by identity against the
    buffer's endpoints: evictions pop from the front, appends extend the back,
    so each event's value is computed exactly once."""

    def __init__(self):
        from collections import deque
        self.entries = deque()          # (event, value) aligned with buffer
        self.sum = 0                    # int stays int; += float promotes
        self.nn = 0                     # non-null count
        self.minq = deque()             # monotonic (value, event)
        self.maxq = deque()

    def sync(self, buffer: list, valfn) -> None:
        ents = self.entries
        while ents and (not buffer or ents[0][0] is not buffer[0]):
            ev, v = ents.popleft()
            if v is not None:
                self.sum -= v
                self.nn -= 1
                if self.minq and self.minq[0][1] is ev:
                    self.minq.popleft()
                if self.maxq and self.maxq[0][1] is ev:
                    self.maxq.popleft()
        for i in range(len(ents), len(buffer)):
            ev = buffer[i]
            v = valfn(ev)
            ents.append((ev, v))
            if v is not None:
                self.sum += v
                self.nn += 1
                while self.minq and self.minq[-1][0] >= v:
                    self.minq.pop()
                self.minq.append((v, ev))
                while self.maxq and self.maxq[-1][0] <= v:
                    self.maxq.pop()
                self.maxq.append((v, ev))


class _BufferResolver(VariableResolver):
    def __init__(self, definition: StreamDefinition):
        self.definition = definition

    def resolve(self, var: Variable):
        d = self.definition
        if var.stream_id in ("first", "last"):
            pos = d.attribute_position(var.attribute)
            if var.stream_id == "first":
                return (lambda f: f.buffer[0].data[pos] if f.buffer else None), \
                    d.attributes[pos].type
            return (lambda f: f.buffer[-1].data[pos] if f.buffer else None), \
                d.attributes[pos].type
        pos = d.attribute_position(var.attribute)
        return (lambda f: f.newest.data[pos]), d.attributes[pos].type


def _build_buffer_fn(expr, definition: StreamDefinition, app_context) -> Callable:
    """Compile the window expression with buffer-aggregate function support."""
    resolver = _BufferResolver(definition)
    # set after the rewrite pass below; True when some aggregate's argument
    # references first./last./eventTimestamp — those change as the buffer
    # moves, so per-event values can't be cached at append time
    _agg_arg_buffer_dep = [False]

    def agg_builder(kind):
        def build(fns, types):
            # incremental per-window cache: the buffer is FIFO (append at the
            # back, evict from the front), so running sum/count plus monotonic
            # deques give O(1) amortized evaluation instead of re-walking the
            # whole buffer on every check (the reference keeps equivalent
            # incremental state in ExpressionWindowProcessor's per-attribute
            # executors)
            cache = _AggCache()

            def run(f: _BufferFrame):
                if kind == "count":
                    return len(f.buffer)
                if _agg_arg_buffer_dep[0]:
                    vals = [v for v in (fns[0](_BufferFrame(f.buffer, e))
                                        for e in f.buffer) if v is not None]
                    if not vals:
                        return None
                    if kind == "sum":
                        return sum(vals)
                    if kind == "avg":
                        return sum(vals) / len(vals)
                    return min(vals) if kind == "min" else max(vals)
                cache.sync(f.buffer,
                           lambda e: fns[0](_BufferFrame(f.buffer, e)))
                if cache.nn == 0:
                    return None
                if kind == "sum":
                    return cache.sum
                if kind == "avg":
                    return cache.sum / cache.nn
                if kind == "min":
                    return cache.minq[0][0]
                return cache.maxq[0][0]
            return run, DataType.DOUBLE if kind in ("avg",) else (
                types[0] if types else DataType.LONG)
        return build

    extra = {
        "count": agg_builder("count"),
        "sum": agg_builder("sum"),
        "avg": agg_builder("avg"),
        "min": agg_builder("min"),
        "max": agg_builder("max"),
    }

    # rewrite eventTimestamp(first|last) before building
    def rewrite(e):
        if isinstance(e, AttributeFunction) and e.name == "eventTimestamp" \
                and e.args and isinstance(e.args[0], Variable) \
                and e.args[0].attribute in ("first", "last"):
            which = e.args[0].attribute
            return _TimestampOf(which)
        for attr in ("left", "right", "expr"):
            sub = getattr(e, attr, None)
            if sub is not None and hasattr(sub, "__class__") and not isinstance(sub, (int, float, str)):
                new = rewrite(sub)
                if new is not sub:
                    setattr(e, attr, new)
        if isinstance(e, AttributeFunction):
            e.args = [rewrite(a) for a in e.args]
        return e

    expr = rewrite(expr)

    def _buffer_dep(e) -> bool:
        if isinstance(e, _TimestampOf):
            return True
        if isinstance(e, Variable) and e.stream_id in ("first", "last"):
            return True
        if isinstance(e, AttributeFunction) and e.namespace is None \
                and e.name in ("sum", "avg", "min", "max", "count"):
            return True  # nested aggregate: value moves with the buffer
        for attr in ("left", "right", "expr"):
            sub = getattr(e, attr, None)
            if sub is not None and not isinstance(sub, (int, float, str, bool)) \
                    and _buffer_dep(sub):
                return True
        if isinstance(e, AttributeFunction):
            return any(_buffer_dep(a) for a in e.args)
        return False

    def _scan_agg_args(e) -> None:
        if isinstance(e, AttributeFunction) and e.namespace is None \
                and e.name in ("sum", "avg", "min", "max"):
            if any(_buffer_dep(a) for a in e.args):
                _agg_arg_buffer_dep[0] = True
        for attr in ("left", "right", "expr"):
            sub = getattr(e, attr, None)
            if sub is not None and not isinstance(sub, (int, float, str, bool)):
                _scan_agg_args(sub)
        if isinstance(e, AttributeFunction):
            for a in e.args:
                _scan_agg_args(a)

    _scan_agg_args(expr)

    class _Builder(ExecutorBuilder):
        def build(self, e):
            if isinstance(e, _TimestampOf):
                if e.which == "first":
                    return (lambda f: f.buffer[0].timestamp if f.buffer else 0), \
                        DataType.LONG
                return (lambda f: f.buffer[-1].timestamp if f.buffer else 0), \
                    DataType.LONG
            return super().build(e)

    builder = _Builder(resolver, app_context, extra_functions=extra)
    fn, _ = builder.build(expr)
    return fn


class _TimestampOf:
    def __init__(self, which: str):
        self.which = which


class DynamicExpressionWindow(WindowProcessor):
    """Sliding: evict oldest until the expression holds."""

    def __init__(self, expr, definition: StreamDefinition, app_context):
        super().__init__()
        self.fn = _build_buffer_fn(expr, definition, app_context)
        self.buffer: list[StreamEvent] = []

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                continue
            self.buffer.append(ev)
            while self.buffer and not bool(
                    self.fn(_BufferFrame(self.buffer, ev))):
                out.append(self._expired(self.buffer.pop(0), ev.timestamp))
            out.append(ev)
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return list(self.buffer)

    def snapshot_state(self) -> dict:
        return {"buffer": [(e.timestamp, list(e.data)) for e in self.buffer]}

    def restore_state(self, state: dict) -> None:
        self.buffer = [StreamEvent(t, d) for t, d in state["buffer"]]


class DynamicExpressionBatchWindow(WindowProcessor):
    """Batch: flush the collected batch when the expression turns false."""

    def __init__(self, expr, definition: StreamDefinition, app_context):
        super().__init__()
        self.fn = _build_buffer_fn(expr, definition, app_context)
        self.pending: list[StreamEvent] = []
        self.last_batch: list[StreamEvent] = []

    def process(self, events: list[StreamEvent]) -> None:
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type != EventType.CURRENT:
                continue
            trial = self.pending + [ev]
            if self.pending and not bool(self.fn(_BufferFrame(trial, ev))):
                # flush current batch, start a new one with this event
                for old in self.last_batch:
                    out.append(self._expired(old, ev.timestamp))
                out.append(StreamEvent(ev.timestamp, [], EventType.RESET))
                out.extend(self.pending)
                self.last_batch = self.pending
                self.pending = [ev]
            else:
                self.pending.append(ev)
        self.forward(out)

    def find_events(self) -> list[StreamEvent]:
        return list(self.last_batch) + list(self.pending)

    def snapshot_state(self) -> dict:
        return {"pending": [(e.timestamp, list(e.data)) for e in self.pending],
                "last": [(e.timestamp, list(e.data)) for e in self.last_batch]}

    def restore_state(self, state: dict) -> None:
        self.pending = [StreamEvent(t, d) for t, d in state["pending"]]
        self.last_batch = [StreamEvent(t, d) for t, d in state["last"]]
