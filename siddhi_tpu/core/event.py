"""Event model.

Reference: ``io.siddhi.core.event`` — ``ComplexEvent.Type`` (``ComplexEvent.java:48``),
``StreamEvent``, ``StateEvent``, ``Event``. Redesigned: the interpreter uses one small
``StreamEvent`` class (list-of-values payload) and ``StateEvent`` (alias→events map) —
the pooled 3-array layout of the reference is replaced on the TPU path by columnar
SoA batches (``siddhi_tpu/tpu/batch.py``), so the host classes stay simple.
"""

from __future__ import annotations

import enum
from typing import Any, Optional


class EventType(enum.Enum):
    CURRENT = "current"
    EXPIRED = "expired"
    TIMER = "timer"
    RESET = "reset"


class StreamEvent:
    """A single event within the engine.

    ``group_key`` rides along on SELECTOR OUTPUT events of group-by queries
    (reference ``GroupedComplexEvent``): grouped first/last output rate
    limiters batch per key, not per event stream."""

    __slots__ = ("timestamp", "data", "type", "group_key", "flow_seq",
                 "trace")

    def __init__(self, timestamp: int, data: list, type: EventType = EventType.CURRENT):
        self.timestamp = timestamp
        self.data = data
        self.type = type
        self.group_key = None
        # WAL sequence number on flow-controlled ingress events (None
        # otherwise): the junction advances the stream's applied watermark
        # with it at delivery (siddhi_tpu/flow)
        self.flow_seq = None
        # sampled observability Trace riding an @async enqueue — the
        # delivery worker re-activates it (siddhi_tpu/observability);
        # synchronous paths propagate thread-locally and never stamp it
        self.trace = None

    def copy(self) -> "StreamEvent":
        # hot path (every window expiry clones): skip __init__ — field
        # assignment via __new__ is ~2x cheaper than re-running the
        # constructor, and the per-copy semantics (fresh group_key/flow_seq/
        # trace) are explicit here
        c = StreamEvent.__new__(StreamEvent)
        c.timestamp = self.timestamp
        c.data = list(self.data)
        c.type = self.type
        c.group_key = None
        c.flow_seq = None
        c.trace = None
        return c

    def __repr__(self) -> str:
        return f"StreamEvent({self.timestamp}, {self.data}, {self.type.name})"


class Event:
    """Public API event delivered to callbacks (reference ``event/Event.java``)."""

    __slots__ = ("timestamp", "data", "is_expired")

    def __init__(self, timestamp: int, data: list, is_expired: bool = False):
        self.timestamp = timestamp
        self.data = list(data)
        self.is_expired = is_expired

    def __repr__(self) -> str:
        flag = ", expired" if self.is_expired else ""
        return f"Event({self.timestamp}, {self.data}{flag})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Event)
            and self.timestamp == other.timestamp
            and self.data == other.data
            and self.is_expired == other.is_expired
        )


class StateEvent:
    """A partial/complete pattern match: alias → StreamEvent | list[StreamEvent].

    Reference ``event/state/StateEvent.java`` uses a positional StreamEvent[]; here a
    dict keyed by state alias (``e1``…) since the interpreter favors clarity; the TPU
    match tables use positional slots.
    """

    __slots__ = ("events", "first_timestamp", "timestamp", "meta")

    def __init__(self):
        self.events: dict[str, Any] = {}
        self.first_timestamp: Optional[int] = None
        self.timestamp: Optional[int] = None
        self.meta: dict[str, Any] = {}  # per-node scratch (logical flags, counts)

    def bind(self, alias: str, ev: StreamEvent, append: bool = False) -> None:
        if self.first_timestamp is None:
            self.first_timestamp = ev.timestamp
        self.timestamp = ev.timestamp
        if append:
            self.events.setdefault(alias, []).append(ev)
        else:
            self.events[alias] = ev

    def get(self, alias: str, index: Optional[int] = None) -> Optional[StreamEvent]:
        v = self.events.get(alias)
        if v is None:
            return None
        if isinstance(v, list):
            if index is None or index == -1:   # default / LAST
                return v[-1] if v else None
            return v[index] if index < len(v) else None
        return v

    def copy(self) -> "StateEvent":
        c = StateEvent()
        c.events = {
            k: (list(v) if isinstance(v, list) else v) for k, v in self.events.items()
        }
        c.first_timestamp = self.first_timestamp
        c.timestamp = self.timestamp
        c.meta = dict(self.meta)
        return c

    def __repr__(self) -> str:
        return f"StateEvent({self.events})"


class PatternEvent(StreamEvent):
    """Selector-bound event carrying a completed pattern match."""

    __slots__ = ("state_event",)

    def __init__(self, timestamp: int, state_event: StateEvent,
                 type: EventType = EventType.CURRENT):
        super().__init__(timestamp, [], type)
        self.state_event = state_event


class JoinedEvent(StreamEvent):
    """Selector-bound event carrying a joined (left, right) pair."""

    __slots__ = ("left", "right")

    def __init__(self, timestamp: int, left: Optional[StreamEvent],
                 right: Optional[StreamEvent], type: EventType = EventType.CURRENT):
        super().__init__(timestamp, [], type)
        self.left = left
        self.right = right
