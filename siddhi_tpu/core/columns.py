"""Columnar chunk payloads and parsers for the zero-object edge.

The columnar interior (``tpu/host_exec.py``, PR 5) and the fleet lanes
(PR 6) outrun the per-event Python edge: every source payload used to cross
``SourceMapper.map`` → per-event list → ``InputHandler.send``, and every
sink emission re-materialized scalar ``Event`` objects. This module is the
shared vocabulary that closes the gap (Hazelcast Jet's lesson, PAPERS.md
2103.10169 — saturation-grade engines win or lose at the edge):

- :class:`RowsChunk` — the columnar transport payload (one dict of numpy
  columns + an int64 timestamp column), accepted end-to-end by
  ``InputHandler.send_columns``, the in-memory broker, and rows-capable
  sinks;
- :class:`DictColumn` — a dictionary-encoded string column (int32 codes +
  a shared append-only value table) with cached code translation into an
  engine ``StringDictionary``, so strings cross the edge as integers;
- :class:`CsvColumnParser` — raw CSV line bytes → columns, through the
  ``native/ingress.cpp`` C ABI when a toolchain exists (parse,
  dictionary-encode and SoA staging all native) with a pure-Python
  fallback;
- :class:`ColumnsOut` — a query's columnar output chunk (decoded lazily;
  rows materialize only when a consumer genuinely needs per-event shape);
- ``unpack_columns`` — the DCN ``pack_rows`` SoA wire format decoded
  straight into columns (the socket source shares that format, see
  DISTRIBUTED.md).

Zero-object contract: none of the hot functions here construct ``Event`` /
``StreamEvent`` objects (pinned by ``scripts/check_rows_path.py``); rows
materialize only in explicit fallback helpers.
"""

from __future__ import annotations

import struct
import time
from typing import Any, Optional

import numpy as np

from ..query_api.definition import DataType, StreamDefinition

# host-side CSV type chars → numpy host policy (NP_HOST): INT/LONG parse as
# int64, FLOAT/DOUBLE as float64 (full precision — the native path uses the
# wide emit, sp_emit_lane_wide), STRING dictionary-encodes, BOOL is uint8
TYPE_CHARS = {
    DataType.STRING: "s",
    DataType.INT: "l",
    DataType.LONG: "l",
    DataType.FLOAT: "d",
    DataType.DOUBLE: "d",
    DataType.BOOL: "b",
}

_CHAR_NP = {"s": np.int32, "l": np.int64, "d": np.float64, "b": np.bool_}


def type_chars(definition: StreamDefinition) -> str:
    """Per-attribute parse type chars for a stream definition."""
    chars = []
    for a in definition.attributes:
        c = TYPE_CHARS.get(a.type)
        if c is None:
            raise TypeError(
                f"attribute '{a.name}': {a.type.value} columns cannot cross "
                f"the columnar edge (host-only)")
        chars.append(c)
    return "".join(chars)


class DictColumn:
    """Dictionary-encoded string column: int32 ``codes`` into an append-only
    ``values`` table (index 0 = None). ``source`` identifies the table owner
    (e.g. the parser) so translations into engine dictionaries cache there.
    """

    __slots__ = ("codes", "values", "source")

    def __init__(self, codes: np.ndarray, values: list, source: Any = None):
        self.codes = np.asarray(codes, dtype=np.int32)
        self.values = values
        self.source = source if source is not None else self

    def __len__(self) -> int:
        return int(self.codes.shape[0])

    def __getitem__(self, item) -> "DictColumn":
        return DictColumn(self.codes[item], self.values, self.source)

    def materialize(self) -> np.ndarray:
        """→ object array of the decoded values (None for code 0)."""
        table = np.empty(len(self.values), dtype=object)
        table[:] = self.values
        return table[np.clip(self.codes, 0, len(self.values) - 1)]

    def tolist(self) -> list:
        vals = self.values
        return [vals[c] for c in self.codes.tolist()]


def encode_dict_column(col: DictColumn, dictionary) -> np.ndarray:
    """Translate a :class:`DictColumn`'s codes into ``dictionary`` codes via
    a cached per-(source, dictionary) translation table — one ``np.take``
    per chunk, no per-row Python."""
    src = col.source
    cache = getattr(src, "_dict_trans", None)
    if cache is None:
        cache = {}
        try:
            src._dict_trans = cache
        except AttributeError:      # pragma: no cover — frozen source
            pass
    key = id(dictionary)
    gen = getattr(dictionary, "generation", 0)
    got = cache.get(key)
    trans = got[1] if got is not None and got[0] == gen else None
    # a dictionary RESTORE remaps values→codes in place (generation bump):
    # a cached translation would then silently emit the old codes, so a
    # generation mismatch drops the cache wholesale
    nv = len(col.values)
    if trans is None or trans.shape[0] < nv:
        old = 0 if trans is None else trans.shape[0]
        ext = np.empty(nv, dtype=np.int32)
        if old:
            ext[:old] = trans
        for i in range(old, nv):
            ext[i] = dictionary.encode(col.values[i])
        trans = ext
        cache[key] = (gen, trans)
    return trans[np.clip(col.codes, 0, nv - 1)]


def column_length(col) -> int:
    if isinstance(col, DictColumn):
        return len(col)
    if isinstance(col, np.ndarray):
        return int(col.shape[0])
    return len(col)


def column_tolist(col) -> list:
    if isinstance(col, DictColumn):
        return col.tolist()
    if isinstance(col, np.ndarray):
        return col.tolist()
    return list(col)


def columns_to_rows(cols: dict, names: list, n: int) -> list[list]:
    """Materialize per-event row lists from a columns dict — the explicit
    fallback for non-columnar consumers (NOT the hot path)."""
    if n == 0:
        return []
    py = [column_tolist(cols[name]) for name in names]
    return [list(r) for r in zip(*py)]


class RowsChunk:
    """One columnar transport chunk: ``cols`` maps attribute name →
    numpy array / :class:`DictColumn`; ``ts`` is int64 per-row event time
    (None → the engine stamps ingestion time at ``send_columns``)."""

    __slots__ = ("cols", "ts", "count")

    def __init__(self, cols: dict, ts: Optional[np.ndarray] = None,
                 count: Optional[int] = None):
        self.cols = cols
        self.ts = None if ts is None else np.asarray(ts, dtype=np.int64)
        if count is None:
            count = int(self.ts.shape[0]) if self.ts is not None \
                else (column_length(next(iter(cols.values()))) if cols else 0)
        self.count = count

    def __len__(self) -> int:
        return self.count

    def rows(self, names: list) -> list[list]:
        return columns_to_rows(self.cols, names, self.count)

    def __repr__(self) -> str:
        return f"RowsChunk({self.count} rows x {len(self.cols)} cols)"


class ColumnsOut:
    """A query's columnar output chunk: raw plan columns (strings still
    dictionary codes) + the specs/dictionaries that decode them. Decoding
    and row materialization are lazy — the zero-object egress hands
    ``decoded()`` columns to rows-capable sinks and never builds rows."""

    __slots__ = ("ts", "cols", "n", "specs", "dictionaries",
                 "_decoded", "_rows")

    def __init__(self, ts: np.ndarray, cols: dict, n: int, specs: list,
                 dictionaries: dict):
        self.ts = ts
        self.cols = cols
        self.n = n
        self.specs = specs              # [(name, fn, DataType)]
        self.dictionaries = dictionaries
        self._decoded = None
        self._rows = None

    def decoded(self) -> dict:
        """{name: numpy column} with dictionary codes decoded to value
        object arrays — the payload ``StreamJunction.deliver_columns``
        carries to rows-capable receivers."""
        if self._decoded is None:
            out = {}
            table = None
            for dic in self.dictionaries.values():
                table = dic
                break
            for (name, _fn, t) in self.specs:
                v = self.cols[name]
                if t == DataType.STRING and table is not None:
                    vals = np.empty(len(table._values), dtype=object)
                    vals[:] = table._values
                    codes = np.clip(np.asarray(v, np.int64), 0,
                                    len(vals) - 1)
                    out[name] = vals[codes]
                else:
                    out[name] = np.asarray(v)
            self._decoded = out
        return self._decoded

    def rows(self) -> list[list]:
        if self._rows is None:
            from ..tpu.host_exec import decode_columns
            self._rows = decode_columns(self.specs, self.cols,
                                        self.dictionaries)
        return self._rows

    def ts_list(self) -> list:
        return np.asarray(self.ts).tolist()


# ---------------------------------------------------------------------------
# CSV → columns parsers
# ---------------------------------------------------------------------------

def _py_bool(field: bytes) -> bool:
    return field.lower() == b"true" or field == b"1"


class CsvColumnParser:
    """Raw CSV line bytes → :class:`RowsChunk` list.

    Native path (``native/ingress.cpp`` via ctypes): parse, dictionary
    encode and SoA staging run in C++; Python only wraps the emitted numpy
    arrays (wide emit — doubles keep float64 for interpreter parity).
    Pure-Python fallback when no toolchain exists: same column layout, same
    malformed-line accounting, built from per-line splits.

    ``ts_last=True`` reads a trailing int64 event-time field per line
    (the bench corpus / DCN convention); otherwise ``ts`` is None and the
    engine stamps arrival time.
    """

    def __init__(self, definition: StreamDefinition, ts_last: bool = False,
                 capacity: int = 65536):
        self.definition = definition
        self.types = type_chars(definition)
        self.names = definition.attribute_names
        self.ts_last = ts_last
        self.capacity = int(capacity)
        self.rows_out = 0
        self.bytes_in = 0
        self.parse_seconds = 0.0
        self._t_first = None
        self._py_errors = 0
        self._ning = None
        self._values: list = [None]     # native dict mirror (code 0 = None)
        self.ingress = "python"
        try:
            from ..native import NativeIngress, native_available
            if native_available():
                self._ning = NativeIngress(self.types, key_col=-1,
                                           n_lanes=1, capacity=self.capacity)
                self.ingress = "native"
        except Exception:   # noqa: BLE001 — toolchain probe; python fallback
            self._ning = None

    @property
    def parse_errors(self) -> int:
        if self._ning is not None:
            return int(self._ning.parse_errors) + self._py_errors
        return self._py_errors

    @property
    def rows_per_s(self) -> float:
        return self.rows_out / self.parse_seconds if self.parse_seconds \
            else 0.0

    def parse(self, payload: bytes) -> list[RowsChunk]:
        """Whole lines only (the caller frames torn tails); returns the
        parsed chunks (several when a payload overflows one staging
        buffer)."""
        t0 = time.perf_counter()
        self.bytes_in += len(payload)
        if self._ning is not None:
            chunks = self._parse_native(payload)
        else:
            chunks = self._parse_python(payload)
        self.parse_seconds += time.perf_counter() - t0
        for ch in chunks:
            self.rows_out += ch.count
        return chunks

    # -- native ------------------------------------------------------------
    def _sync_values(self) -> None:
        ning = self._ning
        ds = int(ning.dict_size())
        vals = self._values
        while len(vals) < ds:
            vals.append(ning.decode(len(vals)))

    def _parse_native(self, payload: bytes) -> list[RowsChunk]:
        ning = self._ning
        chunks: list[RowsChunk] = []
        pos, total = 0, len(payload)
        while pos < total:
            consumed = ning.ingest_csv(payload, ts_last=self.ts_last,
                                       final=True, offset=pos)
            pos += consumed
            n = int(ning.lane_len(0))
            if n == 0:
                if consumed == 0:
                    break               # nothing staged, nothing consumed
                continue
            b = ning.emit_lane(0, wide=True)
            self._sync_values()
            cols: dict[str, Any] = {}
            for i, (name, t) in enumerate(zip(self.names, self.types)):
                arr = b["cols"][i][:n]
                if t == "s":
                    cols[name] = DictColumn(arr, self._values, source=self)
                else:
                    cols[name] = arr
            chunks.append(RowsChunk(
                cols, b["ts"][:n] if self.ts_last else None, n))
        return chunks

    # -- pure python -------------------------------------------------------
    def _parse_python(self, payload: bytes) -> list[RowsChunk]:
        names, types = self.names, self.types
        ncols = len(types)
        expected = ncols + (1 if self.ts_last else 0)
        raw_cols: list[list] = [[] for _ in range(ncols)]
        tss: list[int] = []
        for line in payload.split(b"\n"):
            if line.endswith(b"\r"):
                line = line[:-1]
            if not line:
                continue
            fields = line.split(b",")
            if len(fields) != expected:
                self._py_errors += 1
                continue
            try:
                vals = []
                for f, t in zip(fields, types):
                    if t == "s":
                        vals.append(f.decode() if f else None)
                    elif not f:
                        vals.append(0 if t != "d" else 0.0)
                    elif t == "d":
                        vals.append(float(f))
                    elif t == "l":
                        vals.append(int(f))
                    else:                   # 'b'
                        vals.append(_py_bool(f))
                ts = int(fields[ncols]) if self.ts_last else 0
            except ValueError:
                self._py_errors += 1
                continue
            for c, v in zip(raw_cols, vals):
                c.append(v)
            tss.append(ts)
        n = len(tss)
        if n == 0:
            return []
        cols: dict[str, Any] = {}
        for name, t, vals in zip(names, types, raw_cols):
            if t == "s":
                arr = np.empty(n, dtype=object)
                arr[:] = vals
                cols[name] = arr
            else:
                cols[name] = np.asarray(vals, dtype=_CHAR_NP[t])
        out = [RowsChunk(cols, np.asarray(tss, np.int64)
                         if self.ts_last else None, n)]
        return out


# ---------------------------------------------------------------------------
# DCN pack_rows wire format → columns (shared with tpu/dcn.py; layout pinned
# by tests/test_edge_rows.py round-trip against dcn.pack_rows/unpack_rows)
# ---------------------------------------------------------------------------

_NUM_DT = {"f": ">f4", "d": ">f8", "i": ">i4", "l": ">i8", "b": ">u1"}


def unpack_columns(payload: bytes) -> tuple[dict, np.ndarray, int, str]:
    """Decode one ``tpu/dcn.py pack_rows`` SoA payload straight into
    positional columns: returns ``({index: column}, ts, n, types)``. Numeric
    columns are zero-copy ``np.frombuffer`` views converted to host dtypes;
    string columns decode through their offset table."""
    n, ncols = struct.unpack_from(">IB", payload, 0)
    off = 5
    types = payload[off:off + ncols].decode("ascii")
    off += ncols
    ts = np.frombuffer(payload, dtype=">i8", count=n, offset=off) \
        .astype(np.int64)
    off += 8 * n
    cols: dict[int, Any] = {}
    for ci, t in enumerate(types):
        nulls = np.frombuffer(payload, dtype=np.uint8, count=n, offset=off) \
            .astype(bool)
        off += n
        if t == "s":
            offs = np.frombuffer(payload, dtype=">u4", count=n + 1,
                                 offset=off).astype(np.int64)
            off += 4 * (n + 1)
            blob = payload[off:off + int(offs[-1])]
            off += int(offs[-1])
            vals = np.empty(n, dtype=object)
            for i in range(n):          # string decode is inherently per-row
                vals[i] = None if nulls[i] \
                    else blob[offs[i]:offs[i + 1]].decode()
            cols[ci] = vals
        else:
            arr = np.frombuffer(payload, dtype=_NUM_DT[t], count=n,
                                offset=off)
            off += arr.dtype.itemsize * n
            host = arr.astype(_CHAR_NP["d" if t in ("f", "d") else
                                       ("l" if t in ("i", "l") else "b")])
            if nulls.any():
                host = host.copy()
                host[nulls] = 0
            cols[ci] = host
    return cols, ts, int(n), types
