"""Named windows: ``define window W (...) <handler>`` shared across queries.

Reference: ``core/window/Window.java`` — internal processor chain, publishes
events per its output event type, exposes ``find()`` for joins.
"""

from __future__ import annotations

from ..query_api.definition import OutputEventType, WindowDefinition
from .event import EventType, StreamEvent
from .processors import SinkProcessor


class NamedWindow:
    def __init__(self, definition: WindowDefinition, processor, app_context):
        self.definition = definition
        self.processor = processor          # a WindowProcessor chain head
        self.app_context = app_context
        self.subscribers: list = []         # junction-receiver-like objects
        processor.set_next(SinkProcessor(self._dispatch))

    def add(self, event: StreamEvent) -> None:
        self.processor.process([event])

    def _dispatch(self, events: list[StreamEvent]) -> None:
        # deliver the flush as ONE chunk, RESET events included: batch-type
        # named windows (lengthBatch/timeBatch/...) rely on downstream
        # selectors seeing chunk boundaries to collapse aggregated rows and
        # reset between batches (CustomJoinWindowTestCase
        # .testMultipleStreamsToWindow pins one row per flush)
        t = self.definition.output_event_type
        out: list[StreamEvent] = []
        for ev in events:
            if ev.type == EventType.CURRENT and t == OutputEventType.EXPIRED_EVENTS:
                continue
            if ev.type == EventType.EXPIRED and t == OutputEventType.CURRENT_EVENTS:
                continue
            if ev.type in (EventType.CURRENT, EventType.EXPIRED,
                           EventType.RESET):
                out.append(StreamEvent(ev.timestamp, list(ev.data), ev.type))
        if not out:
            return
        for s in self.subscribers:
            if hasattr(s, "receive_chunk"):
                s.receive_chunk(list(out))
            else:
                for ev in out:
                    s.receive(ev)

    def subscribe(self, receiver) -> None:
        self.subscribers.append(receiver)

    def find_events(self) -> list[StreamEvent]:
        return self.processor.find_events()
