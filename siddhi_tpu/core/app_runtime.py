"""SiddhiAppRuntime: build + lifecycle for one app.

Reference: ``core/SiddhiAppRuntime.java`` / ``SiddhiAppRuntimeImpl.java`` (start:449,
shutdown:552, persist:686, query:309) and ``util/SiddhiAppRuntimeBuilder`` +
``util/parser/SiddhiAppParser`` (definitions, fault streams :382, queries,
partitions).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..compiler import parse_on_demand_query
from ..query_api import Partition, Query, SiddhiApp, Window
from ..query_api.annotation import find_annotation
from ..query_api.definition import DataType, StreamDefinition
from .context import SiddhiAppContext, SiddhiContext
from .errors import SiddhiAppCreationError
from .event import Event
from .extension import ScriptFunction
from .io import (
    SINK_MAPPERS,
    SINKS,
    SOURCE_MAPPERS,
    SOURCES,
    parse_io_annotations,
)
from .metrics import Level, StatisticsManager
from .named_window import NamedWindow
from .on_demand import OnDemandQueryRuntime
from .partition import PartitionRuntime
from .query_runtime import QueryRuntime, build_query_runtime, make_window_processor
from .scheduler import SystemTicker
from .snapshot import PersistenceManager, SnapshotService
from .stream import (
    InputHandler,
    OnErrorAction,
    QueryCallback,
    StreamCallback,
    StreamJunction,
    _StreamCallbackReceiver,
)
from .table import InMemoryTable
from .trigger import TriggerRuntime, trigger_stream_definition

log = logging.getLogger("siddhi_tpu.app")


class SiddhiAppRuntime:
    def __init__(self, app: SiddhiApp, siddhi_context: SiddhiContext,
                 playback: Optional[bool] = None, start_time: int = 0):
        self.app = app
        app_ann = find_annotation(app.annotations, "app")
        playback_ann = find_annotation(app.annotations, "playback")
        if playback is None:
            playback = playback_ann is not None or (
                app_ann is not None and app_ann.get("playback") == "true")
        # @app:playback(idle.time='...', increment='...') heartbeat: after
        # idle.time of wall silence the playback clock jumps by increment
        # (reference EventTimeBasedMillisTimestampGenerator)
        self._heartbeat_cfg = None
        if playback_ann is not None and playback_ann.get("idle.time"):
            from .aggregation import parse_retention
            idle = parse_retention(playback_ann.get("idle.time"))
            inc = parse_retention(playback_ann.get("increment") or "1 sec")
            self._heartbeat_cfg = (int(idle), int(inc))
        self.name = app.name()
        self.ctx = SiddhiAppContext(siddhi_context, self.name, playback, start_time)
        self.ctx.runtime = self
        self.ctx.statistics_manager = StatisticsManager(self.name)
        # @app(statistics='true'|'detail', statistics.reporter='log',
        # statistics.interval='30') — reference @app statistics wiring
        if app_ann is not None:
            stats = (app_ann.get("statistics") or "").lower()
            if stats in ("true", "basic"):
                self.ctx.statistics_manager.set_level(Level.BASIC)
            elif stats == "detail":
                self.ctx.statistics_manager.set_level(Level.DETAIL)
            reporter = app_ann.get("statistics.reporter")
            interval = app_ann.get("statistics.interval")
            if reporter or interval:
                try:
                    self.ctx.statistics_manager.configure_reporter(
                        reporter, float(interval) if interval else None)
                except ValueError as e:
                    raise SiddhiAppCreationError(str(e)) from None
        self.input_handlers: dict[str, InputHandler] = {}
        self.query_runtimes: dict[str, QueryRuntime] = {}
        self.partition_runtimes: list[PartitionRuntime] = []
        self.trigger_runtimes: list[TriggerRuntime] = []
        self.sources: list = []
        self.sinks: list = []
        self.device_bridges: list = []
        self.host_bridges: list = []    # columnar host fast-path queries
        self.fleet_bridges: list = []   # multi-tenant shared-plan queries
        self._io_handlers: list[tuple[str, str]] = []   # (kind, element id)
        self._started = False
        self._ondemand_cache: dict[str, OnDemandQueryRuntime] = {}

        self.snapshot_service = SnapshotService(self.ctx)
        self.persistence = PersistenceManager(
            self.ctx, self.snapshot_service, siddhi_context.persistence_store)

        # @app:adaptive(...): device micro-batch flush thresholds adapt to
        # observed rate/latency — parsed before _build so device bridges can
        # attach controllers as they compile
        adaptive_ann = find_annotation(app.annotations, "adaptive")
        if adaptive_ann is not None:
            from ..flow.adaptive_batch import parse_adaptive_annotation
            self.ctx.adaptive_cfg = parse_adaptive_annotation(adaptive_ann)
        self.flow = None                # FlowSubsystem when @app:wal/@app:backpressure
        # observability BEFORE _build: the @app:trace tracer must exist on
        # the context while queries, sinks and device bridges compile their
        # instrumentation points
        from ..observability import ObservabilitySubsystem
        self.observability = ObservabilitySubsystem(self)
        # fault-handling layer (sink pipelines, device quarantine, @app:chaos)
        # — built BEFORE _build so sinks wrap and device guards attach as the
        # IO and query surfaces compile
        from ..resilience import ResilienceSubsystem
        self.resilience = ResilienceSubsystem(self)

        self._build()
        # gauges/probes over the finished surfaces (bridges, junctions,
        # sources) — after _build so every element exists
        self.observability.wire()

    # ------------------------------------------------------------------ build
    def _build(self) -> None:
        app, ctx = self.app, self.ctx
        # script functions
        for fd in app.function_definitions.values():
            ctx.script_functions[fd.id] = ScriptFunction(
                fd.id, fd.language, fd.return_type, fd.body)
        # tables
        for td in app.table_definitions.values():
            store_ann = find_annotation(td.annotations, "store")
            if store_ann is not None:
                store_type = store_ann.get("type")
                cls = ctx.siddhi_context.extensions.get(f"store:{store_type}")
                if cls is None:
                    raise SiddhiAppCreationError(
                        f"no store extension '{store_type}' for table '{td.id}'")
                table = cls(td, ctx)
                table.config_reader = ctx.config_reader("store", store_type)
                table.init(td, {e.key: e.value for e in store_ann.elements if e.key})
                rmgr = ctx.siddhi_context.record_table_handler_manager
                if rmgr is not None:
                    th = rmgr.generate_record_table_handler()
                    th.init(self.name, td)
                    rmgr.register_record_table_handler(th.id, th)
                    table.handler = th
                    self._io_handlers.append(("table", th.id))
                cache_ann = store_ann.nested("cache")
                if cache_ann is not None:
                    from .table import CacheTable
                    # the reference requires an explicit size and rejects
                    # unknown cache keys (CacheTable config validation) — a
                    # silent 128/FIFO default would mask config typos
                    known = {"size", "cache.size", "policy", "cache.policy"}
                    bad = [e.key for e in cache_ann.elements
                           if e.key and e.key not in known]
                    if bad:
                        raise SiddhiAppCreationError(
                            f"table '{td.id}': unrecognized @cache key(s) "
                            f"{bad}; known: {sorted(known)}")
                    size_s = cache_ann.get("size") or cache_ann.get("cache.size")
                    if size_s is None:
                        raise SiddhiAppCreationError(
                            f"table '{td.id}': @cache requires a 'size'")
                    try:
                        size = int(size_s)
                    except ValueError:
                        raise SiddhiAppCreationError(
                            f"table '{td.id}': @cache size '{size_s}' is not "
                            f"an integer") from None
                    if size < 1:
                        raise SiddhiAppCreationError(
                            f"table '{td.id}': @cache size must be >= 1, "
                            f"got {size}")
                    try:
                        table = CacheTable(
                            td, ctx, backing=table, max_size=size,
                            policy=(cache_ann.get("cache.policy")
                                    or cache_ann.get("policy") or "FIFO"))
                    except ValueError as e:    # e.g. unknown policy name
                        raise SiddhiAppCreationError(
                            f"table '{td.id}': {e}") from None
                    table.preload()
            else:
                table = InMemoryTable(td, ctx)
            ctx.tables[td.id] = table
        # streams + junctions (+ fault streams)
        for sd in app.stream_definitions.values():
            self._get_junction(sd.id, define=sd)
            # stream-level @async, or app-wide @app:async applying to every
            # defined stream (reference AsyncTestCase.asyncTest2)
            async_ann = find_annotation(sd.annotations, "async") \
                or find_annotation(app.annotations, "async")
            if async_ann is not None:
                # Disruptor-mode analog (StreamJunction.java:279-316):
                # producers enqueue, workers deliver under the engine lock
                self.ctx.stream_junctions[sd.id].enable_async(
                    buffer_size=int(async_ann.get("buffer.size") or 1024),
                    workers=int(async_ann.get("workers") or 1),
                    batch_size_max=int(async_ann.get("batch.size.max") or 64))
            onerror = find_annotation(sd.annotations, "OnError")
            if onerror is not None:
                action = (onerror.get("action") or "log").lower()
                junction = ctx.stream_junctions[sd.id]
                junction.on_error_action = action
                if action == OnErrorAction.STREAM:
                    fault_def = StreamDefinition("!" + sd.id)
                    for a in sd.attributes:
                        fault_def.attribute(a.name, a.type)
                    fault_def.attribute("_error", DataType.OBJECT)
                    fj = self._get_junction("!" + sd.id, define=fault_def)
                    junction.fault_junction = fj
        # named windows
        for wd in app.window_definitions.values():
            handler = wd.window_handler or Window(None, "length", [])
            proc = make_window_processor(handler, wd, ctx, f"window-{wd.id}")
            ctx.named_windows[wd.id] = NamedWindow(wd, proc, ctx)
        # triggers
        for td in app.trigger_definitions.values():
            sd = trigger_stream_definition(td.id)
            j = self._get_junction(td.id, define=sd)
            self.trigger_runtimes.append(TriggerRuntime(td, j, ctx))
        # aggregations
        from .aggregation import AggregationRuntime
        for ad in app.aggregation_definitions.values():
            ctx.aggregations[ad.id] = AggregationRuntime(ad, ctx, self._stream_defs())
        # queries & partitions in definition order
        from .host_bridge import (
            host_batch_config,
            try_build_host_partition,
            try_build_host_query,
        )
        host_cfg = host_batch_config(app.annotations)
        if host_cfg is not None:
            # the retained source travels with the config: process-backed
            # lane pools rebuild identical engines by re-parsing it
            host_cfg["source_text"] = getattr(app, "source_text", None)
        part_count = 0
        # @app:fleet: multi-tenant shared compilation — queries join the
        # engine-wide FleetManager's shape groups (one compiled program per
        # shape, cross-app lane batching); non-normalizing queries fall
        # through to the solo tiers below, per query
        from ..fleet import fleet_config
        try:
            fleet_cfg = fleet_config(app.annotations)
        except ValueError as e:     # malformed slo.class / numeric knob
            raise SiddhiAppCreationError(str(e)) from None
        fleet_mgr = ctx.siddhi_context.fleet() if fleet_cfg is not None \
            else None
        q_count = 0
        for element in app.execution_elements:
            if isinstance(element, Query):
                q_count += 1
                name = element.name() or f"query-{q_count}"
                # @device queries offload to the compiled TPU path when they
                # fit its kernel coverage; otherwise the host path builds below
                from .device_bridge import try_build_device_query
                bridge = try_build_device_query(
                    element, ctx, self._stream_defs(), self._get_junction, name)
                if bridge is not None:
                    self.device_bridges.append(bridge)
                    for sid in bridge.stream_ids:
                        self._get_junction(sid).subscribe(
                            bridge.receiver_for(sid))
                    self._fill_implicit(element, bridge)
                    continue
                # fleet tier: same-shape queries across tenant apps share
                # one compiled columnar program and step as lanes of one
                # batched step (solo tiers below when no fleet shape)
                if fleet_mgr is not None:
                    fbridge = fleet_mgr.enroll_query(
                        element, ctx, self._stream_defs(),
                        self._get_junction, name, fleet_cfg)
                    if fbridge is not None:
                        self.fleet_bridges.append(fbridge)
                        for sid in fbridge.stream_ids:
                            self._get_junction(sid).subscribe(
                                fbridge.receiver_for(sid))
                        self._fill_implicit(element, fbridge)
                        continue
                # columnar host fast path (middle tier): engages per query
                # when the plan lowers on the numpy backend; otherwise the
                # scalar interpreter builds below — per query, not per app
                hbridge = try_build_host_query(
                    element, ctx, self._stream_defs(), self._get_junction,
                    name, host_cfg)
                if hbridge is not None:
                    self.host_bridges.append(hbridge)
                    for sid in hbridge.stream_ids:
                        self._get_junction(sid).subscribe(
                            hbridge.receiver_for(sid))
                    self._fill_implicit(element, hbridge)
                    continue
                rt = build_query_runtime(
                    element, ctx, self._stream_defs(), self._get_junction, name)
                self.query_runtimes[name] = rt
                for sid, receiver in rt.subscriptions:
                    if sid in ctx.named_windows:
                        ctx.named_windows[sid].subscribe(receiver)
                    elif sid in ctx.aggregations:
                        raise SiddhiAppCreationError(
                            "aggregations are queried via joins/on-demand")
                    else:
                        self._get_junction(sid).subscribe(receiver)
                self._fill_implicit(element, rt)
            elif isinstance(element, Partition):
                q_count += 1
                part_count += 1
                name = f"partition-{q_count}"
                if host_cfg is not None:
                    # position among the app's partitions: the lane-pool
                    # child re-parses and indexes to the same block
                    host_cfg["part_index"] = part_count - 1
                if fleet_mgr is not None:
                    fbridges = fleet_mgr.enroll_partition(
                        element, ctx, self._stream_defs(),
                        self._get_junction, name, fleet_cfg)
                    if fbridges is not None:
                        for fb in fbridges:
                            self.fleet_bridges.append(fb)
                            for sid in fb.stream_ids:
                                self._get_junction(sid).subscribe(
                                    fb.receiver_for(sid))
                        continue
                if host_cfg is not None:
                    # lane-partitioned columnar NFA for pattern partitions:
                    # replaces the per-key interpreter cloning when EVERY
                    # query in the block lowers on the numpy backend
                    hbridges = try_build_host_partition(
                        element, ctx, self._stream_defs(),
                        self._get_junction, name, host_cfg)
                    if hbridges is not None:
                        for hb in hbridges:
                            self.host_bridges.append(hb)
                            for sid in hb.stream_ids:
                                self._get_junction(sid).subscribe(
                                    hb.receiver_for(sid))
                        continue
                prt = PartitionRuntime(element, ctx, self._stream_defs(),
                                       lambda sid, inner=False: self._get_junction(sid),
                                       name)
                # pre-fill implicit defs for partition outputs
                prt.subscribe_all(lambda sid, inner=False: self._get_junction(sid))
                self.partition_runtimes.append(prt)
        # sources & sinks from stream annotations
        self._wire_io()
        # durable flow control (@app:wal / @app:backpressure) — after
        # junctions exist and @async dispatchers are configured
        wants_flow = find_annotation(app.annotations, "wal") is not None \
            or find_annotation(app.annotations, "backpressure") is not None
        if wants_flow:
            from ..flow import build_flow
            self.flow = build_flow(self)
        self._wire_gauges()

    def _wire_gauges(self) -> None:
        """Buffered-events + memory gauges (reference BufferedEventsTracker /
        SiddhiMemoryUsageMetric): async queue depths and per-element retained
        size, incl. device pytree HBM bytes."""
        sm = self.ctx.statistics_manager
        for sid, j in self.ctx.stream_junctions.items():
            if j.dispatcher is not None:
                sm.buffered_tracker(
                    f"stream.{sid}", lambda d=j.dispatcher: d.buffered_events)
        for b in self.device_bridges:
            if b.driver is not None:
                sm.buffered_tracker(
                    f"device.{b.query_name}",
                    lambda drv=b.driver: drv.pipeline_depth)
            # device state HBM: nbytes summed over the pytree
            sm.memory_tracker(
                f"device.{b.query_name}",
                lambda rt=b.runtime: rt.state)
        for element_id, holder in self.ctx.state_registry.items():
            if not element_id.startswith("device-"):
                sm.memory_tracker(element_id, lambda h=holder: h)
        # flow-control gauges: wal_bytes / queue_depth / credits / shed_count
        if self.flow is not None:
            for sid, sf in self.flow.streams.items():
                if sf.wal is not None:
                    sm.gauge_tracker(f"flow.{sid}.wal_bytes",
                                     lambda w=sf.wal: w.wal_bytes)
                if sf.gate is not None:
                    sm.gauge_tracker(f"flow.{sid}.queue_depth",
                                     lambda g=sf.gate: g.depth)
                    sm.gauge_tracker(f"flow.{sid}.credits",
                                     lambda g=sf.gate: g.credits)
                sm.gauge_tracker(f"flow.{sid}.shed_count",
                                 lambda s=sf.stats: s.shed)
                sm.gauge_tracker(f"flow.{sid}.dropped_oldest",
                                 lambda s=sf.stats: s.dropped_oldest)
        for b in self.device_bridges:
            ctrl = getattr(b.runtime, "batch_controller", None)
            if ctrl is not None:
                sm.gauge_tracker(f"device.{b.query_name}.batch_size",
                                 lambda c=ctrl: c.current)
        # columnar host fast-path gauges: staged rows, events/batches routed
        # through the vectorized engine (the step-latency histogram registers
        # at bridge construction)
        for b in self.host_bridges:
            sm.buffered_tracker(f"host_batch.{b.query_name}",
                                lambda bb=b: len(bb.runtime.builder))
            sm.gauge_tracker(f"host_batch.{b.query_name}.events",
                             lambda bb=b: bb.events_in)
            sm.gauge_tracker(f"host_batch.{b.query_name}.batches",
                             lambda bb=b: bb.batches)
            ctrl = getattr(b.runtime, "batch_controller", None)
            if ctrl is not None:
                sm.gauge_tracker(f"host_batch.{b.query_name}.batch_size",
                                 lambda c=ctrl: c.current)
        # fleet gauges: staged rows visible per tenant (per-member ev/s,
        # lanes-per-step and shape-cache counters register at enroll time in
        # the FleetManager)
        for b in self.fleet_bridges:
            sm.buffered_tracker(f"fleet.{b.query_name}",
                                lambda bb=b: len(bb.group.stager))
        # resilience gauges: per-receiver fault counts, sink circuits, device
        # quarantine state (sink_retries / sink_dropped register themselves
        # as counters at wrap time)
        # edge-path gauges: transport bytes and parsed rows per source (the
        # rows/s reading is the zero-object ingress evidence surface)
        for src in self.sources:
            sid = getattr(getattr(src, "definition", None), "id", None)
            if sid is None:     # exotic Source subclass skipping init()
                continue
            if hasattr(src, "bytes_in"):
                sm.gauge_tracker(f"stream.{sid}.source_bytes_in",
                                 lambda s=src: s.bytes_in)
            mp = getattr(src, "mapper", None)
            if mp is not None and hasattr(mp, "rows_out"):
                sm.gauge_tracker(f"stream.{sid}.source_rows_out",
                                 lambda m=mp: m.rows_out)
                sm.gauge_tracker(f"stream.{sid}.source_rows_per_s",
                                 lambda m=mp: m.rows_per_s)
                sm.gauge_tracker(f"stream.{sid}.source_parse_errors",
                                 lambda m=mp: m.parse_errors)
        for sid, j in self.ctx.stream_junctions.items():
            sm.gauge_tracker(f"stream.{sid}.receiver_errors",
                             lambda jj=j: jj.receiver_errors)
        for rs in self.resilience.sinks:
            sm.gauge_tracker(
                f"sink.{rs.stream_id}.{rs.ordinal}.circuit_state",
                lambda s=rs: s.breaker.state_code)
        for g in self.resilience.guards:
            sm.gauge_tracker(f"device.{g.query_name}.circuit_state",
                             lambda x=g: x.breaker.state_code)
            sm.gauge_tracker(f"device.{g.query_name}.fallback_events",
                             lambda x=g: x.fallback_events)
        # host-batch step containment (HostStepGuard): circuit + replay
        # evidence per columnar query, torn down with the host_batch.{q}
        # family on shutdown
        for g in self.resilience.host_guards:
            sm.gauge_tracker(f"host_batch.{g.query_name}.circuit_state",
                             lambda x=g: x.breaker.state_code)
            sm.gauge_tracker(f"host_batch.{g.query_name}.fallback_events",
                             lambda x=g: x.fallback_events)
        if self.resilience.chaos is not None:
            for key in self.resilience.chaos.counters:
                sm.gauge_tracker(
                    f"chaos.{key}",
                    lambda c=self.resilience.chaos, k=key: c.counters[k])

    def _stream_defs(self) -> dict:
        defs = dict(self.app.stream_definitions)
        for sid, j in self.ctx.stream_junctions.items():
            defs.setdefault(sid, j.definition)
        return defs

    def _get_junction(self, stream_id: str, inner: bool = False,
                      define: Optional[StreamDefinition] = None) -> StreamJunction:
        j = self.ctx.stream_junctions.get(stream_id)
        if j is None:
            d = define or self.app.stream_definitions.get(stream_id) \
                or StreamDefinition(stream_id)
            j = StreamJunction(d, self.ctx)
            self.ctx.stream_junctions[stream_id] = j
        elif define is not None and not j.definition.attributes:
            j.definition = define
        return j

    def _fill_implicit(self, query: Query, rt) -> None:
        """``rt`` is any runtime exposing ``output_schema`` (host query runtime
        or device bridge)."""
        from ..query_api import InsertIntoStream
        os = query.output_stream
        if isinstance(os, InsertIntoStream):
            j = self.ctx.stream_junctions.get(os.target_id)
            if j is not None and not j.definition.attributes:
                names, types = rt.output_schema
                d = StreamDefinition(os.target_id)
                for n, t in zip(names, types):
                    d.attribute(n, t)
                j.definition = d

    def _with_config(self, obj, namespace: str, name: str):
        # reference hands a ConfigReader into every extension init
        obj.config_reader = self.ctx.config_reader(namespace, name)
        return obj

    def _wire_io(self) -> None:
        ctx = self.ctx
        for sd in self.app.stream_definitions.values():
            sources, sinks = parse_io_annotations(sd)
            for s in sources:
                cls = SOURCES.get(s["type"]) or \
                    ctx.siddhi_context.extensions.get(f"source:{s['type']}")
                if cls is None:
                    raise SiddhiAppCreationError(f"unknown source type '{s['type']}'")
                mapper_cls = SOURCE_MAPPERS.get(s["map"]) or \
                    ctx.siddhi_context.extensions.get(f"sourceMapper:{s['map']}")
                if mapper_cls is None:
                    raise SiddhiAppCreationError(
                        f"unknown source mapper type '{s['map']}'")
                mapper = self._with_config(mapper_cls(), "sourceMapper", s["map"])
                mapper.init(sd, {**s["options"], **s.get("map_options", {})})
                src = self._with_config(cls(), "source", s["type"])
                handler = self._make_source_handler(sd.id, mapper, s["type"])
                src.init(sd, s["options"], mapper, handler)
                try:
                    src.retry_delays()    # malformed retry.delays fails the
                    # BUILD, not the first connect attempt at start
                except ValueError as e:
                    raise SiddhiAppCreationError(
                        f"source on stream '{sd.id}': bad retry.delays "
                        f"({e})") from None
                # connect retries abort promptly once shutdown starts
                src.shutdown_signal = self.resilience.shutdown_signal
                self.resilience.wrap_source_connect(src, sd.id)
                self.sources.append(src)
            for s in sinks:
                cls = SINKS.get(s["type"]) or \
                    ctx.siddhi_context.extensions.get(f"sink:{s['type']}")
                if cls is None:
                    raise SiddhiAppCreationError(f"unknown sink type '{s['type']}'")
                mapper_cls = SINK_MAPPERS.get(s["map"]) or \
                    ctx.siddhi_context.extensions.get(f"sinkMapper:{s['map']}")
                if mapper_cls is None:
                    raise SiddhiAppCreationError(
                        f"unknown sink mapper type '{s['map']}'")
                dist = s.get("distribution")
                if dist and dist["destinations"]:
                    from .io import (
                        BroadcastStrategy,
                        DistributedSink,
                        PartitionedStrategy,
                        RoundRobinStrategy,
                    )
                    subs = []
                    for dest_opts in dist["destinations"]:
                        mapper = self._with_config(
                            mapper_cls(), "sinkMapper", s["map"])
                        mapper.init(sd, {**s["options"], **s.get("map_options", {})})
                        sub = self._with_config(cls(), "sink", s["type"])
                        merged = {**s["options"], **dest_opts}
                        sub.init(sd, merged, mapper)
                        # per-destination pipeline: one endpoint failing must
                        # not take down its siblings
                        subs.append(self.resilience.wrap_sink(sub, sd, merged))
                    n = len(subs)
                    strat_name = (dist["strategy"] or "roundRobin").lower()
                    if strat_name == "partitioned":
                        key = dist.get("partitionKey")
                        if key is None:
                            raise SiddhiAppCreationError(
                                "partitioned @distribution needs partitionKey")
                        strat = PartitionedStrategy(
                            n, sd.attribute_position(key))
                    elif strat_name == "broadcast":
                        strat = BroadcastStrategy(n)
                    else:
                        strat = RoundRobinStrategy(n)
                    sink = DistributedSink(subs, strat)
                else:
                    mapper = self._with_config(
                        mapper_cls(), "sinkMapper", s["map"])
                    mapper.init(sd, {**s["options"], **s.get("map_options", {})})
                    sink = self._with_config(cls(), "sink", s["type"])
                    sink.init(sd, s["options"], mapper)
                    # the publish pipeline (on.error policy + circuit
                    # breaker) wraps every wired sink
                    sink = self.resilience.wrap_sink(sink, sd, s["options"])
                self.sinks.append(sink)
                smgr = ctx.siddhi_context.sink_handler_manager
                if smgr is not None:
                    sh = smgr.generate_sink_handler()
                    sh.init(self.name, sd, sink.on_event,
                            element_id=self.ctx.element_id(
                                f"{self.name}-{sd.id}-{type(sh).__name__}"))
                    smgr.register_sink_handler(sh.id, sh)
                    self._io_handlers.append(("sink", sh.id))
                    cb = StreamCallback(lambda events, h=sh: [
                        h.handle(e) for e in events])
                    self.add_callback(sd.id, cb)
                else:
                    # direct sink subscription: rows-capable sinks (mapper
                    # map_rows + sink publish_rows) accept whole columnar
                    # chunks — the zero-object egress; everything else
                    # keeps the per-event Event path
                    from .io import RowsSinkReceiver, SinkReceiver
                    recv = RowsSinkReceiver(sink) \
                        if getattr(sink, "rows_capable", False) \
                        else SinkReceiver(sink)
                    self._get_junction(sd.id).subscribe(recv)

    def _make_source_handler(self, stream_id: str, mapper, source_type: str):
        mgr = self.ctx.siddhi_context.source_handler_manager
        sh = None
        if mgr is not None:
            sh = mgr.generate_source_handler(source_type)
            sh.init(self.name, self.app.stream_definitions[stream_id],
                    element_id=self.ctx.element_id(
                        f"{self.name}-{stream_id}-{type(sh).__name__}"))
            mgr.register_source_handler(sh.id, sh)
            self._io_handlers.append(("source", sh.id))

        sm = self.ctx.statistics_manager
        parse_tracker = sm.latency_tracker(
            f"source.{stream_id}.ingress_parse") if sm is not None else None
        map_rows = getattr(mapper, "map_rows", None)

        def handler(payload):
            from .columns import RowsChunk
            ih = self.input_handler(stream_id)
            if isinstance(payload, RowsChunk):
                if sh is None:
                    # a columnar chunk forwards whole through the bulk
                    # ingress instead of exploding into per-event sends
                    # (in-memory broker rows path, socket rows frames)
                    ih.send_columns(payload.cols, payload.ts, payload.count)
                    return
                # interception installed: the SourceHandler contract is
                # per event — degrade the chunk to rows so a RowsChunk
                # payload still flows instead of crashing the mapper
                names = self.app.stream_definitions[stream_id] \
                    .attribute_names
                tss = payload.ts
                for i, row in enumerate(payload.rows(names)):
                    sh.send_event(
                        Event(int(tss[i]), row) if tss is not None
                        else row, ih)
                return
            if sh is None:
                if callable(map_rows) and isinstance(
                        payload, (bytes, bytearray, memoryview)):
                    t0 = time.perf_counter()
                    chunks = map_rows(payload)
                    dt = time.perf_counter() - t0
                    for ch in chunks:
                        if parse_tracker is not None and ch.count:
                            parse_tracker.record_seconds(
                                dt / max(len(chunks), 1), ch.count)
                        ih.send_columns(ch.cols, ch.ts, ch.count)
                    return
            for row in mapper.map(payload):
                if sh is not None:
                    sh.send_event(row, ih)
                else:
                    ih.send(row)
        # @app:chaos source faults reject the payload before ingress
        return self.resilience.wrap_source_handler(stream_id, handler)

    # -------------------------------------------------------------- public API
    def input_handler(self, stream_id: str) -> InputHandler:
        ih = self.input_handlers.get(stream_id)
        if ih is None:
            if stream_id not in self.ctx.stream_junctions:
                raise KeyError(f"stream '{stream_id}' is not defined")
            ih = InputHandler(stream_id, self.ctx.stream_junctions[stream_id], self.ctx)
            if self.flow is not None:
                self.flow.attach(ih)
            self.input_handlers[stream_id] = ih
        return ih

    # reference-style alias
    getInputHandler = input_handler

    def add_callback(self, stream_id: str, callback: StreamCallback) -> None:
        if stream_id not in self.ctx.stream_junctions:
            raise KeyError(f"stream '{stream_id}' is not defined")
        self.ctx.stream_junctions[stream_id].subscribe(
            _StreamCallbackReceiver(callback))

    def add_rows_callback(self, stream_id: str, fn) -> None:
        """Columns-capable subscription: ``fn(cols, ts, n)`` receives whole
        columnar chunks (zero per-event objects end to end when every other
        subscriber of the stream is also columns-capable)."""
        from .stream import RowsCallback
        j = self.ctx.stream_junctions.get(stream_id)
        if j is None:
            raise KeyError(f"stream '{stream_id}' is not defined")
        cb = RowsCallback(fn)
        cb.names = j.definition.attribute_names
        j.subscribe(cb)

    def remove_callback(self, callback: StreamCallback) -> None:
        """Detach a previously added stream callback (reference
        ``SiddhiAppRuntime.removeCallback``)."""
        for j in self.ctx.stream_junctions.values():
            for r in list(j.receivers):
                if isinstance(r, _StreamCallbackReceiver) \
                        and r.callback is callback:
                    j.unsubscribe(r)

    def remove_query_callback(self, callback: QueryCallback) -> None:
        for rt in self.query_runtimes.values():
            cbs = rt.callback_adapter.callbacks
            if callback in cbs:
                cbs.remove(callback)
        for bridge in (self.device_bridges + self.host_bridges
                       + self.fleet_bridges):
            cbs = getattr(bridge, "query_callbacks", [])
            if callback in cbs:
                cbs.remove(callback)

    def add_query_callback(self, query_name: str, callback: QueryCallback) -> None:
        rt = self.query_runtimes.get(query_name)
        if rt is not None:
            rt.add_callback(callback)
            return
        for bridge in (self.device_bridges + self.host_bridges
                       + self.fleet_bridges):
            if bridge.query_name == query_name:
                bridge.query_callbacks.append(callback)
                return
        for prt in self.partition_runtimes:
            for q in prt.partition_ast.queries:
                if q.name() == query_name:
                    prt.add_query_callback(query_name, callback)
                    return
        raise KeyError(f"no query named '{query_name}'")

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self.resilience.on_start()
        for j in self.ctx.stream_junctions.values():
            if j.dispatcher is not None:
                j.dispatcher.start()
        for rt in self.query_runtimes.values():
            rt.start()
        for tr in self.trigger_runtimes:
            tr.start()
        if not getattr(self, "_defer_sources", False):
            for src in self.sources:
                src.connect_with_retry()
        self.observability.on_start()
        self.ctx.statistics_manager.start_reporting()
        if not self.ctx.timestamp_generator.playback:
            self.ctx.ticker = SystemTicker(self.ctx.scheduler)
            self.ctx.ticker.start()
        elif self._heartbeat_cfg is not None:
            from .scheduler import PlaybackHeartbeat
            self._heartbeat = PlaybackHeartbeat(self.ctx,
                                                *self._heartbeat_cfg)
            self._heartbeat.start()

    def shutdown(self) -> None:
        # signal first: WAIT backoffs and connect retries abort promptly
        # instead of riding out their delays
        self.resilience.on_shutdown()
        self.drain_async()           # deliver queued async events
        for b in self.device_bridges:
            b.finalize()             # drain + close open device segments
        for b in self.host_bridges:
            b.finalize()             # drain columnar host micro-batches
        for b in self.fleet_bridges:
            b.finalize()             # drain the shared fleet groups
        for j in self.ctx.stream_junctions.values():
            if j.dispatcher is not None:
                j.dispatcher.stop()
        for b in self.device_bridges:
            if b.driver is not None:
                b.driver.stop()
        for agg in self.ctx.aggregations.values():
            if getattr(agg, "persist_stores", None):
                agg.flush_persisted()    # drain write-behind rollups
        for src in self.sources:
            src.disconnect()
        for sink in self.sinks:
            sink.disconnect()
        sc = self.ctx.siddhi_context
        for kind, hid in self._io_handlers:
            mgr = {"source": sc.source_handler_manager,
                   "sink": sc.sink_handler_manager,
                   "table": sc.record_table_handler_manager}[kind]
            if mgr is not None:
                getattr(mgr, f"unregister_{'record_table' if kind == 'table' else kind}_handler")(hid)
        if self.flow is not None:
            self.flow.close()
        # leave the fleet: this tenant's lanes detach from their shape
        # groups (shared plans stay cached for the next tenant), and its
        # metric families tear down through unregister() — a stopped tenant
        # app must not leak dead gauges into the engine-wide exposition
        sm = self.ctx.statistics_manager
        if self.fleet_bridges:
            self.ctx.siddhi_context.fleet().release_app(self.name)
            sm.unregister("fleet.")
            sm.unregister("slo.")   # the autopilot's compliance gauges ride
            # the tenant's lifecycle exactly like the fleet.* families
            self.fleet_bridges = []
        for b in self.host_bridges:
            sm.unregister(f"host_batch.{b.query_name}")
        self.observability.on_shutdown()
        self.ctx.statistics_manager.stop_reporting()
        if self.ctx.ticker is not None:
            self.ctx.ticker.stop()
        if getattr(self, "_heartbeat", None) is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        self._started = False

    def drain_async(self) -> None:
        """Quiesce async junction dispatchers (ThreadBarrier analog). Must be
        called WITHOUT holding root_lock."""
        for j in self.ctx.stream_junctions.values():
            if j.dispatcher is not None:
                j.dispatcher.quiesce()

    # -- time (playback) ------------------------------------------------------
    def advance_time(self, ts: int) -> None:
        """Advance the playback clock (fires due timers) without an event."""
        self.flush_device()
        self.flush_host()
        self.ctx.advance_time(ts)

    def flush_device(self) -> None:
        """Drain pending micro-batches of @device-offloaded queries."""
        for b in self.device_bridges:
            b.flush()

    def flush_host(self) -> None:
        """Drain pending micro-batches of columnar host fast-path and fleet
        queries (a fleet flush drains the whole shape group — staged rows of
        co-tenant apps resolve with it)."""
        for b in self.host_bridges:
            b.flush()
        for b in self.fleet_bridges:
            b.flush()

    # -- snapshots ------------------------------------------------------------
    def _pre_snapshot(self) -> None:
        """Quiesce async machinery so state walks see a stable engine (the
        reference locks ThreadBarrier). Runs WITHOUT root_lock."""
        self.drain_async()
        self.flush_host()       # columnar bridges are synchronous: a plain
        # drain leaves no staged row for the state walk to miss
        for b in self.device_bridges:
            if b.driver is not None:
                b.driver.flush_sync()
                b.driver.pause()

    def _post_snapshot(self) -> None:
        for b in self.device_bridges:
            if b.driver is not None:
                b.driver.resume()

    def snapshot(self) -> bytes:
        self._pre_snapshot()
        try:
            return self.snapshot_service.full_snapshot()
        finally:
            self._post_snapshot()

    def restore(self, blob: bytes) -> None:
        # quiesce + pause async machinery: a device worker step in flight
        # would otherwise overwrite the freshly restored device state
        self._pre_snapshot()
        try:
            self.snapshot_service.restore(blob)
            self.persistence.invalidate_chain()
        finally:
            self._post_snapshot()

    def persist(self) -> str:
        self._pre_snapshot()
        try:
            revision = self.persistence.persist()
        finally:
            self._post_snapshot()
        if self.flow is not None:
            # the checkpoint is durable: WAL segments below its watermark
            # are acked and can be dropped
            self.flow.on_persisted()
        return revision

    def restore_revision(self, revision: str) -> None:
        self._pre_snapshot()
        try:
            self.persistence.restore_revision(revision)
        finally:
            self._post_snapshot()

    def restore_last_revision(self) -> Optional[str]:
        self._pre_snapshot()
        try:
            return self.persistence.restore_last_revision()
        finally:
            self._post_snapshot()

    def clear_all_revisions(self) -> None:
        self.persistence.clear_all_revisions()

    # -- error-store replay ---------------------------------------------------
    def replay_errors(self, stream_name: Optional[str] = None,
                      min_id: Optional[int] = None,
                      max_id: Optional[int] = None) -> dict:
        """Re-inject this app's stored failed events (occurrence-aware:
        'before' entries re-enter through the stream's ``InputHandler``,
        'sink' entries re-publish through the sink pipeline only). Returns
        ``{"replayed", "failed", "skipped"}``."""
        store = self.ctx.siddhi_context.error_store
        if store is None:
            raise ValueError("no error store configured")
        return store.replay(self, stream_name, min_id, max_id)

    # -- on-demand queries ----------------------------------------------------
    def query(self, text: str) -> list[Event]:
        rt = self._ondemand_cache.get(text)
        if rt is None:
            odq = parse_on_demand_query(text)
            rt = OnDemandQueryRuntime(odq, self.ctx)
            if len(self._ondemand_cache) > 100:
                self._ondemand_cache.clear()
            self._ondemand_cache[text] = rt
        return rt.execute()

    # -- debugger -------------------------------------------------------------
    def debug(self):
        """Start debugging: returns the SiddhiDebugger (reference
        ``SiddhiAppRuntime.debug():666``)."""
        from .debugger import SiddhiDebugger
        if getattr(self.ctx, "debugger", None) is None:
            self.ctx.debugger = SiddhiDebugger(self.ctx)
        self.start()
        return self.ctx.debugger

    # -- stats / errors -------------------------------------------------------
    # -- introspection (reference SiddhiAppRuntime getter surface) ----------
    @property
    def stream_definition_map(self) -> dict:
        # declared + inferred (output streams materialize junctions with
        # their inferred definitions — the reference's map includes both)
        return self._stream_defs()

    @property
    def table_definition_map(self) -> dict:
        return dict(self.app.table_definitions)

    @property
    def window_definition_map(self) -> dict:
        return dict(self.app.window_definitions)

    @property
    def aggregation_definition_map(self) -> dict:
        return dict(self.app.aggregation_definitions)

    @property
    def query_names(self) -> set:
        names = set(self.query_runtimes)
        names.update(b.query_name for b in self.device_bridges)
        names.update(b.query_name for b in self.host_bridges)
        names.update(b.query_name for b in self.fleet_bridges)
        return names

    @property
    def tables(self) -> list:
        return list(self.ctx.tables.values())

    @property
    def windows(self) -> list:
        return list(self.ctx.named_windows.values())

    @property
    def triggers(self) -> list:
        return list(self.trigger_runtimes)

    def table_input_handler(self, table_id: str):
        """Direct table ingress (reference ``getTableInputHandler``)."""
        table = self.ctx.tables.get(table_id)
        if table is None:
            raise KeyError(f"table '{table_id}' is not defined")
        return _TableInputHandler(table, self.ctx)

    def on_demand_query_output_attributes(self, text: str) -> list:
        """(name, DataType) pairs the on-demand query would emit (reference
        ``getOnDemandQueryOutputAttributes``)."""
        from .executor import ExecutorBuilder, RowResolver
        odq = parse_on_demand_query(text)
        sid = odq.input_store_id
        ctx = self.ctx
        if sid in ctx.tables:
            d = ctx.tables[sid].definition
        elif sid in ctx.named_windows:
            d = ctx.named_windows[sid].definition
        elif sid in ctx.aggregations:
            d = ctx.aggregations[sid].output_definition
        else:
            raise KeyError(f"store '{sid}' is not defined")
        names = d.attribute_names
        types = [d.attribute_type(n) for n in names]
        attrs = list(odq.selector.attributes)
        if odq.selector.select_all or not attrs:
            return list(zip(names, types))
        builder = ExecutorBuilder(RowResolver(names, types), ctx)
        out = []
        for oa in attrs:
            fn, t = builder.build(oa.expr)
            name = oa.name or getattr(oa.expr, "attribute", None) or "value"
            out.append((name, t))
        return out

    def set_purging_enabled(self, enabled: bool) -> None:
        """Toggle incremental-aggregation purging engine-wide (reference
        ``setPurgingEnabled``)."""
        for agg in self.ctx.aggregations.values():
            was = agg.purge_enabled
            agg.purge_enabled = enabled
            if enabled and not was and agg.purge_interval:
                agg._arm_purge()

    def start_without_sources(self) -> None:
        """Start everything but the transports (reference
        ``startWithoutSources`` — sources attach later via
        :meth:`start_sources`)."""
        self._defer_sources = True
        try:
            self.start()
        finally:
            self._defer_sources = False

    def start_sources(self) -> None:
        for src in self.sources:
            src.connect_with_retry()

    def set_statistics_level(self, level: Level) -> None:
        self.ctx.statistics_manager.set_level(level)

    def set_exception_listener(self, listener) -> None:
        self.ctx.exception_listener = listener


class _TableInputHandler:
    """Direct table ingress (reference ``TableInputHandler``): rows go into
    the table without a feeding stream/query."""

    def __init__(self, table, app_context):
        self.table = table
        self.app_context = app_context

    def send(self, rows, timestamp=None) -> None:
        # a bare row may be a list OR a tuple (mirrors InputHandler payloads)
        if rows and not isinstance(rows[0], (list, tuple)):
            rows = [rows]
        ts = timestamp if timestamp is not None \
            else self.app_context.current_time()
        with self.app_context.root_lock:
            self.table.add([list(r) for r in rows], ts)
