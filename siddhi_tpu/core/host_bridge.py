"""Columnar host fast-path: per-query vectorized micro-batch execution.

The middle execution tier between the compiled device path (``@device`` →
``core/device_bridge.py``) and the scalar interpreter: queries whose plans
fully lower on the numpy backend (``tpu/host_exec.py``) execute over SoA
micro-batches — dictionary-encoded columns, vectorized filters/aggregates/
NFA stages — instead of one ``StreamEvent`` at a time. Queries that do not
lower keep the scalar interpreter, **per query, not per app**.

Engagement:
- ``@app:host_batch(batch='8192', lanes='16')`` enables the fast path for
  every eligible query (and ``partition with`` pattern block) in the app;
- a query-level ``@host_batch`` annotation opts in a single query
  (``strict='true'`` raises instead of falling back);
- ``SIDDHI_HOST_BATCH=1`` in the environment is the app-level switch for
  benchmarking without editing app text;
- the resilience layer builds these bridges programmatically as the
  DeviceGuard quarantine/shadow-replay engine (``build_host_fallback``), so
  degraded mode is no longer interpreter-speed.

Batching semantics (same contract as the device bridge): per-event sends
stage until the flush threshold; CHUNKED deliveries (``InputHandler.send``
with an ``Event`` list, ``send_rows``, @async dispatcher batches, WAL
replay) are each processed as one micro-batch and flushed at chunk end, so
chunk ingress sees outputs synchronously. ``SiddhiAppRuntime.flush_host()``
(also called on playback watermark advancement and shutdown) drains
partial batches. Outputs re-enter the engine as CURRENT events carrying
their PER-ROW timestamps (the match/arrival event time — unlike the device
bridge's batch-timestamp stamping, so downstream event-time windows keep
exact semantics).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

import numpy as np

from ..query_api import (
    InsertIntoStream,
    OutputEventsFor,
    Query,
    SingleInputStream,
    StateInputStream,
    Variable,
)
from ..query_api.annotation import find_annotation
from ..flow.adaptive_batch import AdaptiveFlushMixin
from .event import Event, EventType, StreamEvent

log = logging.getLogger("siddhi_tpu.host_batch")

_DEF_BATCH = 8192
_DEF_LANES = 16


def host_batch_config(app_annotations) -> Optional[dict]:
    """App-level opt-in (annotation or SIDDHI_HOST_BATCH=1) → config dict."""
    ann = find_annotation(app_annotations, "host_batch")
    if ann is None and os.environ.get("SIDDHI_HOST_BATCH", "") != "1":
        return None
    cfg = {"batch": _DEF_BATCH, "lanes": _DEF_LANES,
           "workers": int(os.environ.get("SIDDHI_HOST_WORKERS", "1")),
           "workers_mode": os.environ.get("SIDDHI_HOST_WORKERS_MODE",
                                          "thread")}
    if ann is not None:
        if ann.get("enable") and ann.get("enable").lower() == "false":
            return None
        if ann.get("batch"):
            cfg["batch"] = int(ann.get("batch"))
        if ann.get("lanes"):
            cfg["lanes"] = int(ann.get("lanes"))
        if ann.get("workers"):
            # parallel columnar host tier: shard the partitioned-NFA lane
            # space across N worker threads (exact per-lane parity kept)
            cfg["workers"] = int(ann.get("workers"))
        if ann.get("workers.mode"):
            # 'process' backs the shards with a procmesh lane pool (one
            # child process per shard — own GIL); byte-identical outputs
            cfg["workers_mode"] = ann.get("workers.mode")
    if cfg["workers_mode"] not in ("thread", "process"):
        raise ValueError(
            f"host_batch workers.mode '{cfg['workers_mode']}' is not "
            "thread|process")
    if os.environ.get("SIDDHI_PROCMESH_CHILD") == "1":
        # already inside a procmesh child: no recursive process pools
        cfg["workers_mode"] = "thread"
    return cfg


class _HostRTBase(AdaptiveFlushMixin):
    """Stage → step → deliver dispatch shared by the host runtimes.

    ``process(batch) -> (ts_list, rows)`` is implemented per engine; rows
    carry per-row event timestamps end to end."""

    callback = None
    driver = None               # host path is synchronous (no device queue)

    def add_callback(self, fn):
        self.callback = fn

    def deliver(self, out):
        fn = self.callback
        if fn is not None and out is not None and getattr(out, "n", 0):
            fn(out)

    def flush(self):
        if len(self.builder) == 0:
            return
        b = self.builder.emit()
        b["_cause"] = self._take_cause()
        self.deliver(self._timed_process(b))

    def finalize(self):
        self.flush()


class HostQueryBridge:
    """Junction subscriber feeding a columnar host runtime; outputs re-enter
    the engine through the query's output junction with per-row timestamps."""

    def __init__(self, kind: str, runtime, app_context, stream_ids: list[str],
                 output_junction, query_name: str):
        self.kind = kind              # 'host_stream' | 'host_nfa' | 'host_partition'
        self.runtime = runtime
        self.app_context = app_context
        self.stream_ids = stream_ids
        self.output_junction = output_junction
        self.query_name = query_name
        self.query_callbacks: list = []
        self.events_in = 0
        self.batches = 0
        runtime.add_callback(self._on_out)
        sm = app_context.statistics_manager
        self._step_tracker = (
            sm.latency_tracker(f"host_batch.{query_name}.step")
            if sm is not None else None)
        self._wrap_metrics()

    def _wrap_metrics(self):
        inner = self.runtime.process
        bridge = self

        def process(batch):
            t0 = time.perf_counter()
            try:
                return inner(batch)
            finally:
                bridge.batches += 1
                n = batch.get("count", 0)
                bridge.events_in += n
                tr = bridge._step_tracker
                if tr is not None:
                    tr.record_seconds(time.perf_counter() - t0)

        self.runtime.process = process

    # -- junction receivers ---------------------------------------------------
    def receiver_for(self, stream_id: str):
        bridge = self
        rt = self.runtime

        class _R:
            def receive(self, event: StreamEvent) -> None:
                if event.type is not EventType.CURRENT:
                    return
                rt.builder.append(stream_id, event.data, event.timestamp)
                rt._maybe_flush()

            def receive_chunk(self, events: list) -> None:
                # a delivered chunk IS a micro-batch: stage in bulk, flush at
                # chunk end so chunked ingress observes outputs synchronously
                if any(e.type is not EventType.CURRENT for e in events):
                    events = [e for e in events
                              if e.type is EventType.CURRENT]
                    if not events:
                        return
                rt.builder.append_events(stream_id, events)
                rt.flush()

            def receive_rows(self, rows: list, timestamps) -> None:
                # zero-wrap delivery (StreamJunction.deliver_rows): raw rows
                # straight into the SoA stager, one step per chunk
                rt.builder.append_rows(stream_id, rows, timestamps)
                rt.flush()

            def receive_columns(self, cols: dict, ts, n: int) -> None:
                # zero-object delivery (StreamJunction.deliver_columns):
                # the whole columnar chunk stages as-is — no per-row
                # Python anywhere between transport bytes and the step
                rt.builder.append_columns(stream_id, cols, ts)
                rt.flush()

        return _R()

    def flush(self, cause: str = "drain") -> None:
        if len(self.runtime.builder):
            self.runtime._count_flush(cause)
        self.runtime.flush()

    def finalize(self) -> None:
        self.flush(cause="final")
        self.runtime.finalize()

    # -- output ---------------------------------------------------------------
    def _on_out(self, out) -> None:
        """``out`` is a :class:`~siddhi_tpu.core.columns.ColumnsOut`: the
        zero-object egress hands decoded columns straight to a
        columns-capable output junction (rows-capable sinks); everything
        else falls back to per-event materialization."""
        if out is None or not out.n:
            return
        oj = self.output_junction
        if not self.query_callbacks:
            if oj is None:
                return
            if oj.columns_capable():
                self._deliver_columns_out(out, oj)
                return
        self._deliver_events_out(out, oj)

    def _deliver_columns_out(self, out, oj) -> None:
        # zero-object egress: dictionary codes decode to value columns (one
        # vectorized take per string column), no Event/StreamEvent builds
        oj.deliver_columns(out.decoded(), np.asarray(out.ts, dtype=np.int64),
                           out.n)

    def _deliver_events_out(self, out, oj) -> None:
        ts_list, rows = out.ts_list(), out.rows()
        events = [StreamEvent(ts, row, EventType.CURRENT)
                  for ts, row in zip(ts_list, rows)]
        if not events:
            return
        if self.query_callbacks:
            evs = [Event(e.timestamp, e.data) for e in events]
            for cb in self.query_callbacks:
                cb.receive(events[-1].timestamp, evs, None)
        if oj is not None:
            oj.send_events(events)

    def report(self) -> dict:
        return {"query": self.query_name, "engine": "columnar",
                "kind": self.kind, "events": self.events_in,
                "batches": self.batches}


class _HostBridgeState:
    """Snapshot adapter (registered in the app state registry)."""

    def __init__(self, bridge: HostQueryBridge):
        self.bridge = bridge

    def snapshot_state(self):
        self.bridge.flush()
        return self.bridge.runtime.snapshot_state()

    def restore_state(self, state):
        self.bridge.runtime.restore_state(state)


# ---------------------------------------------------------------------------
# runtimes
# ---------------------------------------------------------------------------

def _audit_query_surface(query: Query, app_context, get_junction):
    """Shared lowering gate (mirrors the device bridge's full-surface audit):
    anything the columnar engine does not model must raise → scalar path."""
    from ..tpu.expr_compile import DeviceCompileError

    sel = query.selector
    if sel is not None and (sel.order_by or sel.limit is not None
                            or sel.offset is not None):
        raise DeviceCompileError(
            "order by / limit / offset keep the scalar interpreter")
    if query.output_rate is not None:
        raise DeviceCompileError(
            "output rate limiting keeps the scalar interpreter")
    if not isinstance(query.output_stream, InsertIntoStream):
        raise DeviceCompileError(
            "host fast path handles insert-into-stream outputs only")
    if query.output_stream.events_for != OutputEventsFor.CURRENT_EVENTS:
        raise DeviceCompileError(
            "expired/all-events outputs keep the scalar interpreter")
    if query.output_stream.is_fault_stream or \
            query.output_stream.is_inner_stream:
        raise DeviceCompileError(
            "fault/inner-stream outputs keep the scalar interpreter")
    from .device_bridge import _input_single_streams
    for s in _input_single_streams(query.input_stream):
        if s.is_fault_stream or s.is_inner_stream:
            raise DeviceCompileError(
                "fault/inner input streams keep the scalar interpreter")
    tid = query.output_stream.target_id
    if tid in app_context.tables or tid in app_context.named_windows:
        raise DeviceCompileError(
            f"host fast path cannot target table/window '{tid}'")
    return get_junction(tid, query.output_stream.is_inner_stream)


class _HostStreamRT(_HostRTBase):
    def __init__(self, compiled, hq, capacity: int):
        from ..tpu.host_exec import HostRowStager
        self.compiled = compiled
        self.hq = hq
        self.builder = HostRowStager(compiled.schema, None, capacity)
        self.state = hq.init_state()

    def process(self, b):
        from .columns import ColumnsOut
        self.state, res = self.hq.step(self.state, b["cols"], b["ts"])
        return ColumnsOut(res["ts"], res["out"], int(res["ts"].shape[0]),
                          self.hq.out_specs, self.compiled.schema.dictionaries)

    @staticmethod
    def _copy_state(v):
        import numpy as np
        if isinstance(v, np.ndarray):
            return v.copy()
        if isinstance(v, dict):
            return {k: _HostStreamRT._copy_state(x) for k, x in v.items()}
        return v

    def snapshot_state(self):
        return {"hq": self._copy_state(self.state),
                "dict": self.compiled.schema.snapshot_dictionaries()}

    def restore_state(self, st):
        self.compiled.schema.restore_dictionaries(st.get("dict", {}))
        self.state = self._copy_state(st["hq"])


class _HostNFART(_HostRTBase):
    def __init__(self, compiler, engine, stream_defs, capacity: int):
        from ..tpu.host_exec import HostRowStager
        self.compiler = compiler
        self.engine = engine
        self.builder = HostRowStager(compiler.merged, stream_defs, capacity,
                                     used_cols=compiler.used_cols)
        self.state = engine.init_state()

    def process(self, b):
        from .columns import ColumnsOut
        self.state, outs = self.engine.step(
            self.state, b["cols"], b["tag"], b["ts"])
        if not outs or outs["j"].size == 0:
            return None
        return ColumnsOut(outs["ts"], outs, int(outs["j"].size),
                          self.engine.out_specs,
                          self.compiler.merged.dictionaries)

    def snapshot_state(self):
        return self.engine.snapshot_state(self.state)

    def restore_state(self, st):
        self.state = self.engine.restore_state(st)


class _HostPartitionRT(_HostRTBase):
    def __init__(self, prt, stream_defs, capacity: int):
        from ..tpu.host_exec import HostRowStager
        self.prt = prt
        self.builder = HostRowStager(prt.compiler.merged, stream_defs,
                                     capacity,
                                     used_cols=prt.compiler.used_cols)

    def process(self, b):
        from .columns import ColumnsOut
        j, outs = self.prt.process(b)
        if not outs:
            return None
        return ColumnsOut(outs["ts"], outs, int(j.size),
                          self.prt.engine.out_specs,
                          self.prt.compiler.merged.dictionaries)

    def finalize(self):
        self.flush()
        self.prt.close()            # release the workers thread pool

    def snapshot_state(self):
        return self.prt.snapshot_state()

    def restore_state(self, st):
        self.prt.restore_state(st)


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------

def _app_plan_key(query: Query, stream_defs: dict, kind: str):
    """Shape-and-constants key for the per-APP plan cache: two queries in
    one app that lower to the SAME program (identical shape AND identical
    constants/overrides on the same streams) share one compiled plan —
    state, stagers and junction wiring stay per query. Cross-app sharing is
    the fleet's job (per-tenant parameter slots); within one app the
    constants must match exactly, so the plan needs no slots."""
    try:
        from ..fleet.shape import normalize_query
        nq = normalize_query(query, stream_defs)
    except Exception:       # noqa: BLE001 — no shape → no dedupe, solo build
        return None
    if nq.kind != kind:
        return None
    try:
        return (nq.shape_key, tuple(nq.param_values),
                tuple(sorted(nq.overrides.items())), tuple(nq.stream_ids))
    except TypeError:       # unhashable constant — skip dedupe
        return None


def _app_plan_cache(app_context) -> dict:
    c = getattr(app_context, "_host_plan_cache", None)
    if c is None:
        c = app_context._host_plan_cache = {}
    return c


def _guard_host_bridge(bridge, query, app_context, stream_defs,
                       get_junction) -> None:
    """Containment for the columnar step (resilience/fleet_guard.py
    HostStepGuard): a failing micro-batch replays through the scalar
    interpreter and repeated failures quarantine the columnar path —
    the host-tier analog of the DeviceGuard wrap."""
    resilience = getattr(getattr(app_context, "runtime", None),
                         "resilience", None)
    if resilience is not None:
        resilience.guard_host(bridge, query, stream_defs, get_junction)


def try_build_host_query(query: Query, app_context, stream_defs: dict,
                         get_junction, name: str, cfg: Optional[dict],
                         guard: bool = True) -> Optional[HostQueryBridge]:
    """Columnar host bridge for one top-level query, or None → scalar path.

    Tried AFTER the device path (``@device`` wins when both apply): an
    app-level config (``cfg``) or a query-level ``@host_batch`` annotation
    opts in; ``strict='true'`` raises the lowering error instead of falling
    back."""
    from ..tpu.expr_compile import DeviceCompileError

    ann = find_annotation(query.annotations, "host_batch")
    if ann is None and cfg is None:
        return None
    strict = ann is not None and (ann.get("strict") or "").lower() == "true"
    batch = int((ann.get("batch") if ann is not None and ann.get("batch")
                 else (cfg or {}).get("batch", _DEF_BATCH)))
    try:
        target = _audit_query_surface(query, app_context, get_junction)
        ist = query.input_stream
        if isinstance(ist, SingleInputStream):
            from ..tpu.host_exec import HostStreamQuery
            from ..tpu.query_compile import CompiledStreamQuery
            d = stream_defs.get(ist.stream_id)
            if d is None:
                raise DeviceCompileError(
                    f"undefined stream '{ist.stream_id}'")
            pkey = _app_plan_key(query, stream_defs, "stream")
            cache = _app_plan_cache(app_context)
            shared = cache.get(pkey) if pkey is not None else None
            if shared is None:
                compiled = CompiledStreamQuery(query, d, backend="numpy")
                hq = HostStreamQuery(compiled)
                if pkey is not None:
                    cache[pkey] = (compiled, hq)
            else:
                compiled, hq = shared
            rt = _HostStreamRT(compiled, hq, batch)
            bridge = HostQueryBridge("host_stream", rt, app_context,
                                     [ist.stream_id], target, name)
            bridge.output_schema = ([s.name for s in compiled.specs],
                                    [s.dtype for s in compiled.specs])
        elif isinstance(ist, StateInputStream):
            from ..tpu.host_exec import HostBlockNFA
            from ..tpu.nfa import DeviceNFACompiler
            pkey = _app_plan_key(query, stream_defs, "nfa")
            cache = _app_plan_cache(app_context)
            shared = cache.get(pkey) if pkey is not None else None
            if shared is None:
                compiler = DeviceNFACompiler(query, stream_defs,
                                             backend="numpy")
                engine = HostBlockNFA(compiler)
                if pkey is not None:
                    cache[pkey] = (compiler, engine)
            else:
                compiler, engine = shared
            rt = _HostNFART(compiler, engine, stream_defs, batch)
            bridge = HostQueryBridge("host_nfa", rt, app_context,
                                     compiler.compiled.stream_ids, target,
                                     name)
            bridge.output_schema = ([n for n, _, _ in compiler.out_specs],
                                    [t for _, _, t in compiler.out_specs])
        else:
            raise DeviceCompileError(
                "joins keep the scalar interpreter on the host fast path")
    except DeviceCompileError as e:
        if strict:
            raise
        log.info("query '%s' keeps the scalar interpreter: %s", name, e)
        return None
    _attach_adaptive(rt, app_context, batch)
    app_context.register_state(f"host-{name}", _HostBridgeState(bridge))
    if guard:
        _guard_host_bridge(bridge, query, app_context, stream_defs,
                           get_junction)
    return bridge


def try_build_host_partition(partition_ast, app_context, stream_defs: dict,
                             get_junction, name: str,
                             cfg: dict) -> Optional[list[HostQueryBridge]]:
    """Columnar bridges for a ``partition with (key of Stream)`` block whose
    queries are ALL blocked-NFA-eligible patterns; None → the per-key
    interpreter ``PartitionRuntime``. All-or-nothing per partition: inner
    streams and mixed engines inside one partition would need cross-engine
    state the fallback contract does not cover."""
    from ..tpu.expr_compile import DeviceCompileError
    from ..tpu.host_exec import HostPartitionedNFA

    try:
        if len(partition_ast.partition_types) != 1:
            raise DeviceCompileError(
                "multi-stream partitions keep the per-key interpreter")
        pt = partition_ast.partition_types[0]
        if getattr(pt, "value_expr", None) is None or \
                not isinstance(pt.value_expr, Variable) or \
                pt.value_expr.stream_index is not None:
            raise DeviceCompileError(
                "range/expression partitions keep the per-key interpreter")
        key_attr = pt.value_expr.attribute
        bridges = []
        for i, q in enumerate(partition_ast.queries):
            qname = q.name() or f"{name}-query-{i}"
            target = _audit_query_surface(q, app_context, get_junction)
            ist = q.input_stream
            if not isinstance(ist, StateInputStream):
                raise DeviceCompileError(
                    "non-pattern partition queries keep the per-key "
                    "interpreter")
            source = None
            if cfg.get("source_text") is not None \
                    and cfg.get("part_index") is not None:
                # identity a lane-pool child needs to rebuild this exact
                # engine: re-parse the SAME text, pick the SAME query
                source = {"app_text": cfg["source_text"],
                          "part_index": cfg["part_index"],
                          "query_index": i,
                          "key_attr": key_attr}
            prt = HostPartitionedNFA(q, stream_defs, key_attr,
                                     num_partitions=cfg.get(
                                         "lanes", _DEF_LANES),
                                     workers=cfg.get("workers", 1),
                                     workers_mode=cfg.get("workers_mode",
                                                          "thread"),
                                     source=source)
            rt = _HostPartitionRT(prt, stream_defs,
                                  cfg.get("batch", _DEF_BATCH))
            bridge = HostQueryBridge(
                "host_partition", rt, app_context,
                prt.compiler.compiled.stream_ids, target, qname)
            bridge.output_schema = (
                [n for n, _, _ in prt.compiler.out_specs],
                [t for _, _, t in prt.compiler.out_specs])
            if target is not None and not target.definition.attributes:
                from ..query_api.definition import StreamDefinition
                d = StreamDefinition(q.output_stream.target_id)
                for n, t in zip(*bridge.output_schema):
                    d.attribute(n, t)
                target.definition = d
            bridges.append(bridge)
    except DeviceCompileError as e:
        log.info("partition '%s' keeps the per-key interpreter: %s", name, e)
        return None
    for bridge, q in zip(bridges, partition_ast.queries):
        _attach_adaptive(bridge.runtime, app_context, cfg.get("batch",
                                                              _DEF_BATCH))
        app_context.register_state(f"host-{bridge.query_name}",
                                   _HostBridgeState(bridge))
        _guard_host_bridge(bridge, q, app_context, stream_defs,
                           get_junction)
    return bridges


def _attach_adaptive(rt, app_context, batch: int) -> None:
    """@app:adaptive: the flow layer's AIMD controller picks the flush
    threshold for the columnar micro-batches too (same controller the
    device bridges use)."""
    if app_context.adaptive_cfg is None:
        return
    from ..flow.adaptive_batch import AdaptiveBatchController
    cfg = dict(app_context.adaptive_cfg)
    cfg["max_batch"] = min(cfg.get("max_batch", batch), batch)
    cfg["min_batch"] = min(cfg.get("min_batch", 64), cfg["max_batch"])
    rt.batch_controller = AdaptiveBatchController(**cfg)


# ---------------------------------------------------------------------------
# resilience fallback (DeviceGuard quarantine / shadow replay)
# ---------------------------------------------------------------------------

class HostFallbackRuntime:
    """QueryRuntime-shaped wrapper the DeviceGuard replays shadows into:
    exposes ``subscriptions`` receivers that stage rows columnar; the guard
    calls ``flush()`` after each replayed batch so outputs surface
    immediately. Falls out of ``build_host_fallback`` only when the query
    lowers — otherwise the guard keeps the scalar interpreter runtime."""

    def __init__(self, bridge: HostQueryBridge):
        self.bridge = bridge
        self.subscriptions = [(sid, bridge.receiver_for(sid))
                              for sid in bridge.stream_ids]
        self.callback_adapter = bridge      # .query_callbacks shared below

    def start(self) -> None:
        pass

    def flush(self) -> None:
        self.bridge.flush(cause="fallback")


def build_host_fallback(query: Query, app_context, stream_defs: dict,
                        get_junction, name: str) -> Optional[HostFallbackRuntime]:
    # guard=False: this bridge IS a guard's fallback engine (DeviceGuard
    # quarantine) — wrapping it in a HostStepGuard would nest containment
    bridge = try_build_host_query(query, app_context, stream_defs,
                                  get_junction, name,
                                  {"batch": _DEF_BATCH}, guard=False)
    if bridge is None:
        return None
    return HostFallbackRuntime(bridge)
