"""Engine / app / query contexts.

Reference: ``core/config/SiddhiContext.java``, ``SiddhiAppContext.java``,
``SiddhiQueryContext.java``. Holds the clock, scheduler, shared services, extension
registry, and the state registry used by snapshotting. The reference's ThreadLocal
partition flow keys become an explicit ``partition_key`` pushed/popped around
partitioned execution (single-threaded deterministic interpreter).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from .scheduler import Scheduler, SystemTicker, TimestampGenerator


class SiddhiContext:
    """Engine-level shared context (one per SiddhiManager)."""

    def __init__(self):
        self.extensions: dict[str, Any] = {}        # "ns:name" -> class
        self.persistence_store = None
        self.config_manager = None
        self.attributes: dict[str, Any] = {}
        # handler interception SPIs (reference SiddhiContext source/sink/
        # record-table handler manager slots)
        self.source_handler_manager = None
        self.sink_handler_manager = None
        self.record_table_handler_manager = None
        # multi-tenant shared compilation (siddhi_tpu/fleet/): one manager
        # per engine so @app:fleet apps share plans and lane-batch cross-app
        self.fleet_manager = None

    def fleet(self):
        """The engine's FleetManager, created on first use."""
        if self.fleet_manager is None:
            from ..fleet import FleetManager
            self.fleet_manager = FleetManager()
        return self.fleet_manager


class SiddhiAppContext:
    def __init__(self, siddhi_context: SiddhiContext, name: str,
                 playback: bool = False, start_time: int = 0):
        self.siddhi_context = siddhi_context
        self.name = name
        self.playback = playback
        self.timestamp_generator = TimestampGenerator(playback, start_time)
        self.scheduler = Scheduler(self.timestamp_generator)
        self.ticker: Optional[SystemTicker] = None
        self.root_lock = threading.RLock()          # whole-app barrier (snapshot)

        # stateful services (populated by the runtime builder)
        self.tables: dict[str, Any] = {}
        self.named_windows: dict[str, Any] = {}
        self.aggregations: dict[str, Any] = {}
        self.stream_junctions: dict[str, Any] = {}
        self.script_functions: dict[str, Any] = {}

        # snapshotting: element_id -> object with snapshot_state()/restore_state()
        self.state_registry: dict[str, Any] = {}
        self._element_counter = 0

        self.adaptive_cfg: Optional[dict] = None    # @app:adaptive(...) kwargs
        self.exception_listener: Optional[Callable[[Exception], None]] = None
        self.debugger = None
        self.runtime = None                         # back-ref set by SiddhiAppRuntime
        self.statistics_manager = None
        self.tracer = None          # PipelineTracer when @app:trace (hot
        # paths gate on one attribute, like flow/debugger)
        self.flight = None          # FlightRecorder (always set for built
        # apps; None only on bare contexts) — control-plane transition ring

    # -- ids -----------------------------------------------------------------
    def element_id(self, prefix: str) -> str:
        self._element_counter += 1
        return f"{prefix}-{self._element_counter}"

    def register_state(self, element_id: str, holder: Any) -> str:
        self.state_registry[element_id] = holder
        return element_id

    # -- time ----------------------------------------------------------------
    def current_time(self) -> int:
        return self.timestamp_generator.current_time()

    def advance_time(self, ts: int) -> None:
        """Advance the playback clock and fire due timers (watermark semantics)."""
        if self.timestamp_generator.playback:
            self.timestamp_generator.advance(ts)
        self.scheduler.fire_until(self.timestamp_generator.current_time())

    # -- config --------------------------------------------------------------
    def config_reader(self, namespace: str, name: str):
        """Per-extension ConfigReader (reference injects one into every init)."""
        from .config import ConfigReader
        cm = self.siddhi_context.config_manager
        if cm is None:
            return ConfigReader({})
        return cm.generate_config_reader(namespace, name)

    # -- lookups -------------------------------------------------------------
    def get_table(self, table_id: str):
        t = self.tables.get(table_id)
        if t is None:
            raise KeyError(f"no table '{table_id}' defined")
        return t

    def lookup_scalar_function(self, namespace: Optional[str], name: str):
        key = f"{namespace}:{name}" if namespace else name
        if key in self.script_functions:
            return self.script_functions[key]
        cls = self.siddhi_context.extensions.get(key)
        if cls is not None and getattr(cls, "extension_kind", None) == "function":
            return cls()
        return None
