"""Config system: ConfigManager SPI + in-memory and YAML implementations.

Reference: ``core/util/config/`` — ``ConfigManager.java`` (SPI),
``ConfigReader.java`` (per-extension scoped reads, injected into every
extension ``init``), ``InMemoryConfigManager.java``, ``YAMLConfigManager.java:40``
(+ ``model/RootConfiguration``). YAML shape (both accepted):

    properties:
      partitionById: "true"
    extensions:
      - extension:
          namespace: source
          name: http
          properties:
            default.port: "9763"

or a flat map ``source.http.default.port: "9763"`` under ``properties``.
"""

from __future__ import annotations

from typing import Any, Optional


def _scalar_str(v: Any) -> str:
    """YAML-style strings: bare ``true``/``false``/``null``, not Python reprs —
    every manager yields the same value types for the same config."""
    if isinstance(v, str):
        return v
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    return str(v)


class ConfigReader:
    """Scoped view of config for one extension: keys under ``<ns>.<name>.``.

    Handed to sources/sinks/stores/mappers as ``self.config_reader`` before
    ``init`` runs (reference injects it as an ``init`` argument).
    """

    def __init__(self, configs: Optional[dict] = None):
        self._configs = dict(configs or {})

    def read_config(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._configs.get(key, default)

    def get_all_configs(self) -> dict:
        return dict(self._configs)

    # reference-style alias
    readConfig = read_config


class ConfigManager:
    """SPI (reference ``ConfigManager.java``)."""

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        return ConfigReader({})

    def extract_system_configs(self, name: str) -> dict:
        return {}

    def extract_property(self, name: str) -> Optional[str]:
        return None


class InMemoryConfigManager(ConfigManager):
    """Reference ``InMemoryConfigManager.java`` — maps handed in directly.

    ``configs`` keys are fully qualified ``<namespace>.<name>.<key>``;
    ``system_configs`` maps a system name to its properties dict.
    """

    def __init__(self, configs: Optional[dict] = None,
                 system_configs: Optional[dict] = None):
        self.configs = {str(k): _scalar_str(v) for k, v in (configs or {}).items()}
        self.system_configs = dict(system_configs or {})

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        prefix = f"{namespace}.{name}."
        return ConfigReader({
            k[len(prefix):]: v for k, v in self.configs.items()
            if k.startswith(prefix)
        })

    def extract_system_configs(self, name: str) -> dict:
        return dict(self.system_configs.get(name, {}))

    def extract_property(self, name: str) -> Optional[str]:
        return self.configs.get(name)


class YAMLConfigManager(InMemoryConfigManager):
    """Reference ``YAMLConfigManager.java:40`` — YAML text/file → config maps."""

    def __init__(self, yaml_content: Optional[str] = None,
                 path: Optional[str] = None):
        if (yaml_content is None) == (path is None):
            raise ValueError("provide exactly one of yaml_content / path")
        try:
            import yaml
        except ImportError as e:                      # pragma: no cover
            raise RuntimeError("pyyaml is required for YAMLConfigManager") from e
        if path is not None:
            with open(path, "r", encoding="utf-8") as f:
                root = yaml.safe_load(f) or {}
        else:
            root = yaml.safe_load(yaml_content) or {}
        if not isinstance(root, dict):
            raise ValueError("root of config YAML must be a mapping")

        configs: dict[str, Any] = {}
        for k, v in (root.get("properties") or {}).items():
            configs[str(k)] = _scalar_str(v)
        for item in root.get("extensions") or []:
            ext = item.get("extension") if isinstance(item, dict) else None
            if not isinstance(ext, dict):
                raise ValueError(f"malformed extensions entry: {item!r}")
            ns, name = ext.get("namespace", ""), ext.get("name", "")
            for pk, pv in (ext.get("properties") or {}).items():
                configs[f"{ns}.{name}.{pk}" if ns else f"{name}.{pk}"] = \
                    _scalar_str(pv)
        system_configs = {
            str(k): dict(v) for k, v in (root.get("refs") or {}).items()
            if isinstance(v, dict)
        }
        super().__init__(configs, system_configs)
