"""Statistics: throughput / latency / memory / buffered-events trackers with
runtime on/off levels and pluggable reporters.

Reference: ``core/util/statistics/`` SPI (``ThroughputTracker``,
``LatencyTracker``, ``MemoryUsageTracker``, ``BufferedEventsTracker``,
``StatisticsManager``) + ``metrics/`` Dropwizard impl
(``SiddhiStatisticsManager.java:35``, ``Level.java`` OFF/BASIC/DETAIL,
``memory/SiddhiMemoryUsageMetric.java`` — an object-graph walker; here
``sys.getsizeof``-based with a pytree fast path for device state, where the
honest figure is the HBM bytes of the arrays).

Latency trackers are log-bucketed histograms
(:mod:`siddhi_tpu.observability.histogram`) — p50/p90/p99/p99.9, not just
the average — taken/closed with explicit tokens so concurrent or
re-entrant measurements on one tracker can't mis-pair
(``t = tracker.start(); ...; tracker.stop(t)``). The reference-style
``mark_in``/``mark_out`` single-slot shim is gone (PR 10): it dropped
overlapping measurements by design and every caller now uses tokens.

Reporters: ``@app(statistics='true')`` enables BASIC; @app elements
``statistics.reporter`` ('log' | 'console' | registered name) and
``statistics.interval`` (seconds) configure periodic emission — the analog
of the reference's Dropwizard reporter wiring. Machine scraping goes
through :mod:`siddhi_tpu.observability.prometheus` instead
(``GET /siddhi-apps/{name}/metrics``).
"""

from __future__ import annotations

import enum
import logging
import sys
import threading
import time
from typing import Callable, Optional

from ..observability.histogram import LogHistogram

log = logging.getLogger("siddhi_tpu.metrics")


class Level(enum.Enum):
    OFF = 0
    BASIC = 1
    DETAIL = 2


class ThroughputTracker:
    def __init__(self, name: str):
        self.name = name
        self.count = 0

    def event_in(self, n: int = 1) -> None:
        self.count += n


class LatencyTracker:
    """Latency distribution over one site (histogram-backed).

    Token API: ``t = tracker.start(); ...; tracker.stop(t)`` — tokens are
    plain ``perf_counter_ns`` values, so overlapping measurements from any
    number of threads pair correctly."""

    def __init__(self, name: str):
        self.name = name
        self.hist = LogHistogram()

    def start(self) -> int:
        return time.perf_counter_ns()

    def stop(self, token: int) -> int:
        """Close a measurement opened by :meth:`start`; returns the ns."""
        dt_ns = time.perf_counter_ns() - token
        self.hist.record(dt_ns / 1e9)
        return dt_ns

    def record_seconds(self, seconds: float, n: int = 1,
                       exemplar=None) -> None:
        """Record an externally-timed sample (device step durations);
        ``n`` event-weights batch segments, ``exemplar`` stamps a sampled
        trace id onto the bucket for OpenMetrics exemplar exposition."""
        self.hist.record(seconds, n, exemplar=exemplar)

    # -- readouts --------------------------------------------------------------
    @property
    def count(self) -> int:
        return self.hist.count

    @property
    def total_ns(self) -> int:
        return int(self.hist.sum * 1e9)

    @property
    def avg_ms(self) -> float:
        c = self.hist.count
        return (self.hist.sum / c) * 1e3 if c else 0.0

    def percentiles_ms(self) -> dict:
        s = self.hist.snapshot()
        return {"count": s["count"], "avg_ms": s["avg"] * 1e3,
                "p50_ms": s["p50"] * 1e3, "p90_ms": s["p90"] * 1e3,
                "p99_ms": s["p99"] * 1e3, "p999_ms": s["p999"] * 1e3,
                "max_ms": s["max"] * 1e3}


class _GaugeErrorMixin:
    """A dead gauge reads 0 — but COUNTED and logged once, never silently
    (a zero that is really a failure must be distinguishable)."""

    on_error: Optional[Callable[[], None]] = None
    _error_logged = False

    def _gauge_failed(self, e: Exception):
        if self.on_error is not None:
            self.on_error()
        if not self._error_logged:
            self._error_logged = True
            log.warning("gauge '%s' failed (reads 0 from now on a failure): "
                        "%s", self.name, e)
        return 0


class BufferedEventsTracker(_GaugeErrorMixin):
    """Gauge over a queue-depth callable (reference
    ``BufferedEventsTracker.java`` / ``StreamJunction.getBufferedEvents:359``
    — async junction ring occupancy)."""

    def __init__(self, name: str, depth_fn: Callable[[], int],
                 on_error: Optional[Callable[[], None]] = None):
        self.name = name
        self._depth_fn = depth_fn
        self.on_error = on_error

    @property
    def buffered(self) -> int:
        try:
            return int(self._depth_fn())
        except Exception as e:  # noqa: BLE001 — counted dead-gauge read
            return self._gauge_failed(e)


# shared back-references every element holds — following them would charge
# the whole application graph to each element's gauge (and re-count it per
# element)
_SHARED_ATTRS = frozenset({
    "app_context", "siddhi_context", "ctx", "runtime", "scheduler",
    "next", "callback", "callbacks", "query_callbacks",
})


def _deep_size(obj, seen: set, depth: int = 0) -> int:
    """Retained-size estimate (reference SiddhiMemoryUsageMetric walks the
    object graph). Device arrays report their on-device byte size."""
    if depth > 6 or id(obj) in seen:
        return 0
    seen.add(id(obj))
    nbytes = getattr(obj, "nbytes", None)
    if nbytes is not None and isinstance(nbytes, int):
        return nbytes                          # numpy / jax array: HBM bytes
    size = sys.getsizeof(obj, 0)
    if isinstance(obj, dict):
        for k, v in obj.items():
            size += _deep_size(k, seen, depth + 1)
            size += _deep_size(v, seen, depth + 1)
    elif isinstance(obj, (list, tuple, set, frozenset)):
        for v in obj:
            size += _deep_size(v, seen, depth + 1)
    elif hasattr(obj, "__dict__"):
        pruned = {k: v for k, v in obj.__dict__.items()
                  if k not in _SHARED_ATTRS}
        size += _deep_size(pruned, seen, depth + 1)
    return size


class MemoryUsageTracker(_GaugeErrorMixin):
    """Gauge over a state-holder (reference
    ``memory/SiddhiMemoryUsageMetric.java``'s object-graph walker)."""

    def __init__(self, name: str, target_fn: Callable[[], object],
                 on_error: Optional[Callable[[], None]] = None):
        self.name = name
        self._target_fn = target_fn
        self.on_error = on_error

    @property
    def bytes(self) -> int:
        try:
            return _deep_size(self._target_fn(), set())
        except Exception as e:  # noqa: BLE001 — counted dead-gauge read
            return self._gauge_failed(e)


class CounterTracker:
    """Monotonic counter (vs the sampled :class:`GaugeTracker`) — the
    resilience layer's ``sink_retries`` / ``sink_dropped`` / chaos fault
    counts, incremented at the failure site and reported alongside gauges."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0

    def inc(self, n: int = 1) -> None:
        self.count += n


class GaugeTracker(_GaugeErrorMixin):
    """Generic numeric gauge over a callable — the flow subsystem's
    wal_bytes / queue_depth / credits / shed_count / batch_size readouts
    (counterpart of the reference's Dropwizard ``Gauge`` registrations)."""

    def __init__(self, name: str, value_fn: Callable[[], float],
                 on_error: Optional[Callable[[], None]] = None):
        self.name = name
        self._value_fn = value_fn
        self.on_error = on_error

    @property
    def value(self):
        try:
            return self._value_fn()
        except Exception as e:  # noqa: BLE001 — counted dead-gauge read
            return self._gauge_failed(e)


class Reporter:
    """Reporter SPI: receives the report dict every interval."""

    def report(self, data: dict) -> None:
        raise NotImplementedError


class LogReporter(Reporter):
    def report(self, data: dict) -> None:
        log.info("statistics %s: %s", data.get("app"), data)


class ConsoleReporter(Reporter):
    def report(self, data: dict) -> None:
        print(f"[statistics] {data}")


REPORTERS: dict[str, type] = {"log": LogReporter, "console": ConsoleReporter}


class StatisticsManager:
    def __init__(self, app_name: str):
        self.app_name = app_name
        self.level = Level.OFF
        self.throughput: dict[str, ThroughputTracker] = {}
        self.latency: dict[str, LatencyTracker] = {}
        self.buffered: dict[str, BufferedEventsTracker] = {}
        self.memory: dict[str, MemoryUsageTracker] = {}
        self.gauges: dict[str, GaugeTracker] = {}
        self.counters: dict[str, CounterTracker] = {}
        self.reporter: Optional[Reporter] = None
        self.report_interval_s: float = 60.0
        self._timer: Optional[threading.Timer] = None
        self._reporting = False
        self._generation = 0        # invalidates stale tick re-arms
        self._lock = threading.Lock()
        # failed gauge reads land here (and log once per gauge) so a dead
        # gauge is distinguishable from a true zero
        self.gauge_errors = CounterTracker("app.gauge_errors")
        self.counters["app.gauge_errors"] = self.gauge_errors

    # registration runs at deploy time while the reporter timer may already
    # be iterating — every mutation of the tracker dicts takes the lock,
    # and report()/exposition snapshot under it
    def throughput_tracker(self, name: str) -> ThroughputTracker:
        with self._lock:
            return self.throughput.setdefault(name, ThroughputTracker(name))

    def latency_tracker(self, name: str) -> LatencyTracker:
        with self._lock:
            return self.latency.setdefault(name, LatencyTracker(name))

    def buffered_tracker(self, name: str, depth_fn) -> BufferedEventsTracker:
        with self._lock:
            return self.buffered.setdefault(
                name, BufferedEventsTracker(name, depth_fn,
                                            self.gauge_errors.inc))

    def memory_tracker(self, name: str, target_fn) -> MemoryUsageTracker:
        with self._lock:
            return self.memory.setdefault(
                name, MemoryUsageTracker(name, target_fn,
                                         self.gauge_errors.inc))

    def gauge_tracker(self, name: str, value_fn) -> GaugeTracker:
        with self._lock:
            return self.gauges.setdefault(
                name, GaugeTracker(name, value_fn, self.gauge_errors.inc))

    def counter_tracker(self, name: str) -> CounterTracker:
        with self._lock:
            return self.counters.setdefault(name, CounterTracker(name))

    def unregister(self, prefix: str) -> int:
        """Remove every tracker whose registration key starts with
        ``prefix`` (a component tearing down — e.g. a DCN worker closing or
        a released lane group — must not leave dead gauges behind to read 0
        forever); returns the number removed."""
        removed = 0
        with self._lock:
            for d in (self.throughput, self.latency, self.buffered,
                      self.memory, self.gauges, self.counters):
                for k in [k for k in d if k.startswith(prefix)]:
                    del d[k]
                    removed += 1
        return removed

    def snapshot_trackers(self) -> dict:
        """Point-in-time shallow copies of every tracker dict — iterate
        these, not the live dicts, so deploy-time registration can't mutate
        mid-walk (values are evaluated OUTSIDE the lock: memory walkers and
        gauges may be slow or re-entrant)."""
        with self._lock:
            return {
                "throughput": dict(self.throughput),
                "latency": dict(self.latency),
                "buffered": dict(self.buffered),
                "memory": dict(self.memory),
                "gauges": dict(self.gauges),
                "counters": dict(self.counters),
            }

    def set_level(self, level: Level) -> None:
        self.level = level

    # -- reporter wiring ------------------------------------------------------
    def configure_reporter(self, name: Optional[str],
                           interval_s: Optional[float] = None) -> None:
        if name:
            cls = REPORTERS.get(name.lower())
            if cls is None:
                raise ValueError(
                    f"unknown statistics reporter '{name}' "
                    f"(known: {sorted(REPORTERS)})")
            self.reporter = cls()
        if interval_s is not None:
            self.report_interval_s = float(interval_s)

    def start_reporting(self) -> None:
        if self.reporter is None:
            return
        with self._lock:
            if self._timer is not None:     # chain already armed — checked
                return                      # under the lock: two concurrent
            # starts must not arm two chains
            self._reporting = True
            self._generation += 1
            gen = self._generation

        def tick():
            if self.level != Level.OFF and self.reporter is not None:
                try:
                    self.reporter.report(self.report())
                except Exception:       # noqa: BLE001
                    log.exception("statistics reporter failed")
            with self._lock:
                # a stop racing an in-flight tick would otherwise cancel the
                # already-fired timer while this re-arm keeps the chain
                # alive; the generation check keeps a stale tick from
                # re-arming alongside a chain started AFTER that stop
                if not self._reporting or self._generation != gen:
                    return
                self._timer = threading.Timer(self.report_interval_s, tick)
                self._timer.daemon = True
                self._timer.start()

        with self._lock:
            if not self._reporting or self._generation != gen:
                return                      # stopped before the first arm
            self._timer = threading.Timer(self.report_interval_s, tick)
            self._timer.daemon = True
            self._timer.start()

    def stop_reporting(self) -> None:
        with self._lock:
            self._reporting = False
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    def report(self) -> dict:
        snap = self.snapshot_trackers()
        data = {
            "app": self.app_name,
            "level": self.level.name,
            "throughput": {k: v.count for k, v in snap["throughput"].items()},
            "latency_avg_ms": {k: v.avg_ms
                               for k, v in snap["latency"].items()},
            "buffered_events": {k: v.buffered
                                for k, v in snap["buffered"].items()},
        }
        if snap["latency"]:
            data["latency"] = {k: v.percentiles_ms()
                               for k, v in snap["latency"].items()}
        if snap["gauges"]:
            data["gauges"] = {k: v.value for k, v in snap["gauges"].items()}
        counters = {k: v.count for k, v in snap["counters"].items()
                    if v.count or k != "app.gauge_errors"}
        if counters:
            data["counters"] = counters
        if self.level == Level.DETAIL:
            data["memory_bytes"] = {k: v.bytes
                                    for k, v in snap["memory"].items()}
        return data
