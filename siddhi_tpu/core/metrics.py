"""Statistics: throughput / latency trackers with runtime on/off levels.

Reference: ``core/util/statistics/`` SPI + ``metrics/`` Dropwizard impl
(``SiddhiStatisticsManager.java``, ``Level.java`` OFF/BASIC/DETAIL).
"""

from __future__ import annotations

import enum
import time
from typing import Optional


class Level(enum.Enum):
    OFF = 0
    BASIC = 1
    DETAIL = 2


class ThroughputTracker:
    def __init__(self, name: str):
        self.name = name
        self.count = 0

    def event_in(self, n: int = 1) -> None:
        self.count += n


class LatencyTracker:
    def __init__(self, name: str):
        self.name = name
        self.total_ns = 0
        self.count = 0
        self._start: Optional[int] = None

    def mark_in(self) -> None:
        self._start = time.perf_counter_ns()

    def mark_out(self) -> None:
        if self._start is not None:
            self.total_ns += time.perf_counter_ns() - self._start
            self.count += 1
            self._start = None

    @property
    def avg_ms(self) -> float:
        return (self.total_ns / self.count) / 1e6 if self.count else 0.0


class StatisticsManager:
    def __init__(self, app_name: str):
        self.app_name = app_name
        self.level = Level.OFF
        self.throughput: dict[str, ThroughputTracker] = {}
        self.latency: dict[str, LatencyTracker] = {}

    def throughput_tracker(self, name: str) -> ThroughputTracker:
        return self.throughput.setdefault(name, ThroughputTracker(name))

    def latency_tracker(self, name: str) -> LatencyTracker:
        return self.latency.setdefault(name, LatencyTracker(name))

    def set_level(self, level: Level) -> None:
        self.level = level

    def report(self) -> dict:
        return {
            "app": self.app_name,
            "level": self.level.name,
            "throughput": {k: v.count for k, v in self.throughput.items()},
            "latency_avg_ms": {k: v.avg_ms for k, v in self.latency.items()},
        }
