"""Statistics: throughput / latency / memory / buffered-events trackers with
runtime on/off levels and pluggable reporters.

Reference: ``core/util/statistics/`` SPI (``ThroughputTracker``,
``LatencyTracker``, ``MemoryUsageTracker``, ``BufferedEventsTracker``,
``StatisticsManager``) + ``metrics/`` Dropwizard impl
(``SiddhiStatisticsManager.java:35``, ``Level.java`` OFF/BASIC/DETAIL,
``memory/SiddhiMemoryUsageMetric.java`` — an object-graph walker; here
``sys.getsizeof``-based with a pytree fast path for device state, where the
honest figure is the HBM bytes of the arrays).

Reporters: ``@app(statistics='true')`` enables BASIC; @app elements
``statistics.reporter`` ('log' | 'console' | registered name) and
``statistics.interval`` (seconds) configure periodic emission — the analog
of the reference's Dropwizard reporter wiring.
"""

from __future__ import annotations

import enum
import logging
import sys
import threading
import time
from typing import Callable, Optional

log = logging.getLogger("siddhi_tpu.metrics")


class Level(enum.Enum):
    OFF = 0
    BASIC = 1
    DETAIL = 2


class ThroughputTracker:
    def __init__(self, name: str):
        self.name = name
        self.count = 0

    def event_in(self, n: int = 1) -> None:
        self.count += n


class LatencyTracker:
    def __init__(self, name: str):
        self.name = name
        self.total_ns = 0
        self.count = 0
        self._start: Optional[int] = None

    def mark_in(self) -> None:
        self._start = time.perf_counter_ns()

    def mark_out(self) -> None:
        if self._start is not None:
            self.total_ns += time.perf_counter_ns() - self._start
            self.count += 1
            self._start = None

    @property
    def avg_ms(self) -> float:
        return (self.total_ns / self.count) / 1e6 if self.count else 0.0


class BufferedEventsTracker:
    """Gauge over a queue-depth callable (reference
    ``BufferedEventsTracker.java`` / ``StreamJunction.getBufferedEvents:359``
    — async junction ring occupancy)."""

    def __init__(self, name: str, depth_fn: Callable[[], int]):
        self.name = name
        self._depth_fn = depth_fn

    @property
    def buffered(self) -> int:
        try:
            return int(self._depth_fn())
        except Exception:       # noqa: BLE001 — a dead gauge reads 0
            return 0


# shared back-references every element holds — following them would charge
# the whole application graph to each element's gauge (and re-count it per
# element)
_SHARED_ATTRS = frozenset({
    "app_context", "siddhi_context", "ctx", "runtime", "scheduler",
    "next", "callback", "callbacks", "query_callbacks",
})


def _deep_size(obj, seen: set, depth: int = 0) -> int:
    """Retained-size estimate (reference SiddhiMemoryUsageMetric walks the
    object graph). Device arrays report their on-device byte size."""
    if depth > 6 or id(obj) in seen:
        return 0
    seen.add(id(obj))
    nbytes = getattr(obj, "nbytes", None)
    if nbytes is not None and isinstance(nbytes, int):
        return nbytes                          # numpy / jax array: HBM bytes
    size = sys.getsizeof(obj, 0)
    if isinstance(obj, dict):
        for k, v in obj.items():
            size += _deep_size(k, seen, depth + 1)
            size += _deep_size(v, seen, depth + 1)
    elif isinstance(obj, (list, tuple, set, frozenset)):
        for v in obj:
            size += _deep_size(v, seen, depth + 1)
    elif hasattr(obj, "__dict__"):
        pruned = {k: v for k, v in obj.__dict__.items()
                  if k not in _SHARED_ATTRS}
        size += _deep_size(pruned, seen, depth + 1)
    return size


class MemoryUsageTracker:
    """Gauge over a state-holder (reference
    ``memory/SiddhiMemoryUsageMetric.java``'s object-graph walker)."""

    def __init__(self, name: str, target_fn: Callable[[], object]):
        self.name = name
        self._target_fn = target_fn

    @property
    def bytes(self) -> int:
        try:
            return _deep_size(self._target_fn(), set())
        except Exception:       # noqa: BLE001
            return 0


class CounterTracker:
    """Monotonic counter (vs the sampled :class:`GaugeTracker`) — the
    resilience layer's ``sink_retries`` / ``sink_dropped`` / chaos fault
    counts, incremented at the failure site and reported alongside gauges."""

    def __init__(self, name: str):
        self.name = name
        self.count = 0

    def inc(self, n: int = 1) -> None:
        self.count += n


class GaugeTracker:
    """Generic numeric gauge over a callable — the flow subsystem's
    wal_bytes / queue_depth / credits / shed_count / batch_size readouts
    (counterpart of the reference's Dropwizard ``Gauge`` registrations)."""

    def __init__(self, name: str, value_fn: Callable[[], float]):
        self.name = name
        self._value_fn = value_fn

    @property
    def value(self):
        try:
            return self._value_fn()
        except Exception:       # noqa: BLE001 — a dead gauge reads 0
            return 0


class Reporter:
    """Reporter SPI: receives the report dict every interval."""

    def report(self, data: dict) -> None:
        raise NotImplementedError


class LogReporter(Reporter):
    def report(self, data: dict) -> None:
        log.info("statistics %s: %s", data.get("app"), data)


class ConsoleReporter(Reporter):
    def report(self, data: dict) -> None:
        print(f"[statistics] {data}")


REPORTERS: dict[str, type] = {"log": LogReporter, "console": ConsoleReporter}


class StatisticsManager:
    def __init__(self, app_name: str):
        self.app_name = app_name
        self.level = Level.OFF
        self.throughput: dict[str, ThroughputTracker] = {}
        self.latency: dict[str, LatencyTracker] = {}
        self.buffered: dict[str, BufferedEventsTracker] = {}
        self.memory: dict[str, MemoryUsageTracker] = {}
        self.gauges: dict[str, GaugeTracker] = {}
        self.counters: dict[str, CounterTracker] = {}
        self.reporter: Optional[Reporter] = None
        self.report_interval_s: float = 60.0
        self._timer: Optional[threading.Timer] = None
        self._reporting = False
        self._lock = threading.Lock()

    def throughput_tracker(self, name: str) -> ThroughputTracker:
        return self.throughput.setdefault(name, ThroughputTracker(name))

    def latency_tracker(self, name: str) -> LatencyTracker:
        return self.latency.setdefault(name, LatencyTracker(name))

    def buffered_tracker(self, name: str, depth_fn) -> BufferedEventsTracker:
        return self.buffered.setdefault(
            name, BufferedEventsTracker(name, depth_fn))

    def memory_tracker(self, name: str, target_fn) -> MemoryUsageTracker:
        return self.memory.setdefault(
            name, MemoryUsageTracker(name, target_fn))

    def gauge_tracker(self, name: str, value_fn) -> GaugeTracker:
        return self.gauges.setdefault(name, GaugeTracker(name, value_fn))

    def counter_tracker(self, name: str) -> CounterTracker:
        return self.counters.setdefault(name, CounterTracker(name))

    def set_level(self, level: Level) -> None:
        self.level = level

    # -- reporter wiring ------------------------------------------------------
    def configure_reporter(self, name: Optional[str],
                           interval_s: Optional[float] = None) -> None:
        if name:
            cls = REPORTERS.get(name.lower())
            if cls is None:
                raise ValueError(
                    f"unknown statistics reporter '{name}' "
                    f"(known: {sorted(REPORTERS)})")
            self.reporter = cls()
        if interval_s is not None:
            self.report_interval_s = float(interval_s)

    def start_reporting(self) -> None:
        if self.reporter is None or self._timer is not None:
            return
        self._reporting = True

        def tick():
            if self.level != Level.OFF and self.reporter is not None:
                try:
                    self.reporter.report(self.report())
                except Exception:       # noqa: BLE001
                    log.exception("statistics reporter failed")
            with self._lock:
                # a stop racing an in-flight tick would otherwise cancel the
                # already-fired timer while this re-arm keeps the chain alive
                if not self._reporting:
                    return
                self._timer = threading.Timer(self.report_interval_s, tick)
                self._timer.daemon = True
                self._timer.start()

        with self._lock:
            self._timer = threading.Timer(self.report_interval_s, tick)
            self._timer.daemon = True
            self._timer.start()

    def stop_reporting(self) -> None:
        with self._lock:
            self._reporting = False
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    def report(self) -> dict:
        data = {
            "app": self.app_name,
            "level": self.level.name,
            "throughput": {k: v.count for k, v in self.throughput.items()},
            "latency_avg_ms": {k: v.avg_ms for k, v in self.latency.items()},
            "buffered_events": {k: v.buffered
                                for k, v in self.buffered.items()},
        }
        if self.gauges:
            data["gauges"] = {k: v.value for k, v in self.gauges.items()}
        if self.counters:
            data["counters"] = {k: v.count for k, v in self.counters.items()}
        if self.level == Level.DETAIL:
            data["memory_bytes"] = {k: v.bytes
                                    for k, v in self.memory.items()}
        return data
