"""Triggers: inject events into a trigger stream at start / periodic / cron times.

Reference: ``core/trigger/`` — ``StartTrigger``, ``PeriodicTrigger``, ``CronTrigger``
(quartz replaced by ``core/cron.py``). A trigger stream has the single attribute
``triggered_time long``.
"""

from __future__ import annotations

from typing import Optional

from ..query_api.definition import DataType, StreamDefinition, TriggerDefinition
from .cron import CronSchedule
from .event import EventType, StreamEvent


def trigger_stream_definition(trigger_id: str) -> StreamDefinition:
    d = StreamDefinition(trigger_id)
    d.attribute("triggered_time", DataType.LONG)
    return d


class TriggerRuntime:
    def __init__(self, definition: TriggerDefinition, junction, app_context):
        self.definition = definition
        self.junction = junction
        self.app_context = app_context
        self.cron: Optional[CronSchedule] = (
            CronSchedule(definition.at_cron) if definition.at_cron else None
        )

    def start(self) -> None:
        now = self.app_context.current_time()
        if self.definition.at_start:
            self._fire(now)
        elif self.definition.at_every_ms is not None:
            self.app_context.scheduler.notify_at(
                now + self.definition.at_every_ms, self._on_periodic)
        elif self.cron is not None:
            nxt = self.cron.next_fire_after(now)
            if nxt is not None:
                self.app_context.scheduler.notify_at(nxt, self._on_cron)

    def _fire(self, ts: int) -> None:
        self.junction.send_event(StreamEvent(ts, [ts], EventType.CURRENT))

    def _on_periodic(self, ts: int) -> None:
        self._fire(ts)
        self.app_context.scheduler.notify_at(
            ts + self.definition.at_every_ms, self._on_periodic)

    def _on_cron(self, ts: int) -> None:
        self._fire(ts)
        nxt = self.cron.next_fire_after(ts)
        if nxt is not None:
            self.app_context.scheduler.notify_at(nxt, self._on_cron)
