"""Minimal quartz-style cron evaluator.

The reference uses the Quartz library for ``CronTrigger``/``CronWindowProcessor``;
here a small evaluator supports the common subset: 6 or 7 fields
(sec min hour day-of-month month day-of-week [year]) with ``*``, ``?``, ``*/n``,
``a-b``, and comma lists. Fire-time search is done in UTC.
"""

from __future__ import annotations

import calendar
import datetime as _dt
from typing import Optional


class CronParseError(ValueError):
    pass


_FIELD_RANGES = [(0, 59), (0, 59), (0, 23), (1, 31), (1, 12), (0, 7)]


def _parse_field(spec: str, lo: int, hi: int) -> Optional[set[int]]:
    """None = any (``*``/``?``)."""
    if spec in ("*", "?"):
        return None
    out: set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if part in ("*", ""):
                part = f"{lo}-{hi}"
        if "-" in part:
            a, b = part.split("-", 1)
            out.update(range(int(a), int(b) + 1, step))
        else:
            v = int(part)
            if step > 1:
                out.update(range(v, hi + 1, step))
            else:
                out.add(v)
    for v in out:
        if not (lo <= v <= hi + (1 if hi == 7 else 0)):
            raise CronParseError(f"cron field value {v} out of range [{lo},{hi}]")
    return out


class CronSchedule:
    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) == 5:               # classic cron: prepend seconds=0
            fields = ["0"] + fields
        if len(fields) not in (6, 7):
            raise CronParseError(f"cron expression needs 5-7 fields: {expr!r}")
        self.expr = expr
        (self.sec, self.minute, self.hour,
         self.dom, self.month, self.dow) = [
            _parse_field(f, lo, hi)
            for f, (lo, hi) in zip(fields[:6], _FIELD_RANGES)
        ]
        if self.dow is not None and 7 in self.dow:   # quartz: 7 == Sunday == 0
            self.dow = (self.dow - {7}) | {0}
        self.year = None
        if len(fields) == 7 and fields[6] not in ("*", "?"):
            self.year = {int(y) for y in fields[6].split(",")}

    def matches(self, dt: _dt.datetime) -> bool:
        dow = (dt.weekday() + 1) % 7       # python Mon=0 → cron Sun=0
        return (
            (self.sec is None or dt.second in self.sec)
            and (self.minute is None or dt.minute in self.minute)
            and (self.hour is None or dt.hour in self.hour)
            and (self.dom is None or dt.day in self.dom)
            and (self.month is None or dt.month in self.month)
            and (self.dow is None or dow in self.dow)
            and (self.year is None or dt.year in self.year)
        )

    def next_fire_after(self, epoch_ms: int, horizon_days: int = 366 * 2) -> Optional[int]:
        """Next fire time strictly after ``epoch_ms`` (returns epoch ms, UTC)."""
        dt = _dt.datetime.fromtimestamp(epoch_ms / 1000.0, tz=_dt.timezone.utc)
        dt = dt.replace(microsecond=0) + _dt.timedelta(seconds=1)
        end = dt + _dt.timedelta(days=horizon_days)
        while dt < end:
            if self.month is not None and dt.month not in self.month:
                nm = dt.month % 12 + 1
                ny = dt.year + (1 if nm == 1 else 0)
                dt = dt.replace(year=ny, month=nm, day=1, hour=0, minute=0, second=0)
                continue
            if (self.dom is not None and dt.day not in self.dom) or (
                self.dow is not None and (dt.weekday() + 1) % 7 not in self.dow
            ):
                dt = (dt + _dt.timedelta(days=1)).replace(hour=0, minute=0, second=0)
                continue
            if self.hour is not None and dt.hour not in self.hour:
                dt = (dt + _dt.timedelta(hours=1)).replace(minute=0, second=0)
                continue
            if self.minute is not None and dt.minute not in self.minute:
                dt = (dt + _dt.timedelta(minutes=1)).replace(second=0)
                continue
            if self.sec is not None and dt.second not in self.sec:
                dt = dt + _dt.timedelta(seconds=1)
                continue
            return int(dt.timestamp() * 1000)
        return None
