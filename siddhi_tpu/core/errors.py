"""Exception types + replayable error store.

Reference: ``core/exception/`` (23 typed exceptions) and
``util/error/handler/store/ErrorStore.java`` — failed events persisted for
replay. Entries are occurrence-aware: ``'before'`` marks a stream-processing
failure (replay re-injects through the stream's ``InputHandler``), ``'sink'``
marks an egress failure (replay goes back through the stream's resilient
sink pipeline only, so downstream queries never see a duplicate).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any, Iterable, Optional

log = logging.getLogger("siddhi_tpu.errors")


class SiddhiAppCreationError(Exception):
    pass


class SiddhiAppRuntimeError(Exception):
    pass


class DefinitionNotExistError(SiddhiAppCreationError):
    pass


class QueryableRecordTableError(SiddhiAppRuntimeError):
    pass


class CannotRestoreStateError(SiddhiAppRuntimeError):
    pass


@dataclass
class ErrorEntry:
    id: int
    timestamp: int                  # save time (ms)
    app_name: str
    stream_name: str
    event_data: Any
    error: str
    occurrence: str = "before"      # 'before' (stream) | 'sink' (egress)
    event_timestamp: int = 0        # the failed event's own timestamp
    sink_ordinal: int = -1          # which of the stream's sinks failed
    # (-1 = not a sink failure / unknown: replay targets every sink)


class ErrorStore:
    """In-memory error store (reference ``ErrorStore`` abstract,
    saveEntry:160) with occurrence-aware, id-ranged replay.

    Mutations are lock-protected: delivery threads ``save`` while the
    service thread replays/discards. ``replay`` never holds the lock while
    re-injecting (delivery may re-enter ``save``)."""

    def __init__(self, capacity: int = 10000):
        self.capacity = capacity
        self.entries: list[ErrorEntry] = []
        self._next_id = 1
        self._lock = threading.RLock()

    def save(self, app_name: str, stream_name: str, event, error: Exception,
             occurrence: str = "before", sink_ordinal: int = -1) -> ErrorEntry:
        with self._lock:
            entry = ErrorEntry(
                id=self._next_id,
                timestamp=int(time.time() * 1000),
                app_name=app_name,
                stream_name=stream_name,
                event_data=list(getattr(event, "data", []) or []),
                error=repr(error),
                occurrence=occurrence,
                event_timestamp=int(getattr(event, "timestamp", 0) or 0),
                sink_ordinal=sink_ordinal,
            )
            self._next_id += 1
            self.entries.append(entry)
            if len(self.entries) > self.capacity:
                self.entries.pop(0)
            return entry

    def load(self, app_name: str, stream_name: Optional[str] = None,
             min_id: Optional[int] = None,
             max_id: Optional[int] = None) -> list[ErrorEntry]:
        with self._lock:
            return [
                e for e in self.entries
                if e.app_name == app_name
                and (stream_name is None or e.stream_name == stream_name)
                and (min_id is None or e.id >= min_id)
                and (max_id is None or e.id <= max_id)
            ]

    def discard(self, entry_id: int) -> None:
        self.discard_many([entry_id])

    def discard_many(self, entry_ids: Iterable[int]) -> None:
        ids = set(entry_ids)
        with self._lock:
            self.entries = [e for e in self.entries if e.id not in ids]

    # -- replay ---------------------------------------------------------------
    def replay(self, runtime, stream_name: Optional[str] = None,
               min_id: Optional[int] = None,
               max_id: Optional[int] = None) -> dict:
        """Re-inject stored entries for ``runtime``'s app.

        ``occurrence='before'`` entries go through the stream's
        ``InputHandler`` (the full delivery chain runs again — a failure that
        persists re-stores the event under a new id). ``occurrence='sink'``
        entries re-publish through the stream's resilient sink pipeline(s)
        only. Returns ``{"replayed", "failed", "skipped"}`` counts; replayed
        entries are discarded."""
        report = {"replayed": 0, "failed": 0, "skipped": 0}
        replayed_ids = []
        for entry in self.load(runtime.name, stream_name, min_id, max_id):
            try:
                if entry.occurrence == "sink":
                    outcome = self._replay_sink(runtime, entry)
                    if outcome is None:
                        report["skipped"] += 1
                        continue
                    if outcome == "dropped":
                        # publish failed and the pipeline dropped it: keep
                        # the entry — discarding would lose the event while
                        # the report claims success
                        report["failed"] += 1
                        continue
                    if outcome == "stored":
                        # the pipeline re-stored it under a NEW id: discard
                        # this (superseded) entry but report the failure so
                        # a replay-until-clean loop can converge
                        replayed_ids.append(entry.id)
                        report["failed"] += 1
                        continue
                    # 'published' / 'fault' (explicitly routed): success
                else:
                    ih = runtime.input_handler(entry.stream_name)
                    flow = getattr(ih, "flow", None)
                    if flow is not None:
                        # replay bypasses the admission gate + WAL exactly
                        # like WAL recovery does (StreamFlow.replaying): a
                        # lossy overload policy silently shedding the
                        # re-injected event would discard it from the store
                        # while reporting success
                        prev = flow.replaying
                        flow.replaying = True
                        try:
                            ih.send(list(entry.event_data),
                                    timestamp=entry.event_timestamp or None)
                        finally:
                            flow.replaying = prev
                    else:
                        ih.send(list(entry.event_data),
                                timestamp=entry.event_timestamp or None)
            except Exception as e:  # noqa: BLE001 — a failed replay keeps
                # its entry; the caller inspects the report and retries
                log.warning("replay of error entry %d (%s/%s) failed: %s",
                            entry.id, entry.app_name, entry.stream_name, e)
                report["failed"] += 1
                continue
            replayed_ids.append(entry.id)
            report["replayed"] += 1
        # one batch discard: FileErrorStore compacts its file once, not per
        # entry (replaying N entries must not rewrite the file N times);
        # a no-op replay must not touch the file at all
        if replayed_ids:
            self.discard_many(replayed_ids)
        return report

    @staticmethod
    def _replay_sink(runtime, entry: ErrorEntry) -> Optional[str]:
        """Re-publish one sink entry; returns the pipeline outcome (per
        call, so concurrent live traffic can't skew the verdict) or None
        when no matching sink exists (skip)."""
        resilience = getattr(runtime, "resilience", None)
        if resilience is None:
            return None
        # target ONLY the sink that failed — siblings already published this
        # event; a -1 ordinal (legacy entry) falls back to every sink
        sinks = [s for s in resilience.sinks_for(entry.stream_name)
                 if entry.sink_ordinal < 0 or s.ordinal == entry.sink_ordinal]
        if not sinks:
            return None
        from .event import Event
        ev = Event(entry.event_timestamp, list(entry.event_data))
        worst = "published"
        rank = {"published": 0, "fault": 1, "stored": 2, "dropped": 3}
        for s in sinks:
            outcome = s.on_event(ev) or "published"
            if rank.get(outcome, 3) > rank[worst]:
                worst = outcome
        return worst


class FileErrorStore(ErrorStore):
    """JSON-lines file-backed store: entries survive restarts.

    Install engine-wide via ``SiddhiManager.set_error_store(
    FileErrorStore(path))``. Saves append one line; discards compact the
    file. Event data must be wire-representable — values that don't survive
    ``json.dumps`` are stored via ``repr`` and come back as strings."""

    def __init__(self, path: str, capacity: int = 10000):
        super().__init__(capacity)
        self.path = path
        self._file_lines = 0        # lines on disk (entries + stale lines)
        self._fh = None             # persistent append handle (WAL pattern)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._load_file()

    def _load_file(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    self.entries.append(ErrorEntry(**json.loads(line)))
                except (ValueError, TypeError) as e:
                    log.warning("error store %s: skipping corrupt line (%s)",
                                self.path, e)
        if self.entries:
            self._next_id = max(e.id for e in self.entries) + 1
        self._file_lines = len(self.entries)
        if len(self.entries) > self.capacity:
            # capacity applies to the FILE too: keep the newest entries
            self.entries = self.entries[-self.capacity:]
            self._rewrite()

    def save(self, app_name: str, stream_name: str, event, error: Exception,
             occurrence: str = "before", sink_ordinal: int = -1) -> ErrorEntry:
        with self._lock:
            entry = super().save(app_name, stream_name, event, error,
                                 occurrence, sink_ordinal)
            # append always (O(1) on the delivery thread, persistent handle
            # — the WAL pattern); in-memory evictions leave stale lines
            # behind, compacted once the file doubles past capacity —
            # amortized, never per-save
            if self._fh is None:
                self._fh = open(self.path, "a", encoding="utf-8")
            self._fh.write(json.dumps(asdict(entry), default=repr) + "\n")
            self._fh.flush()
            self._file_lines += 1
            if self._file_lines > 2 * self.capacity:
                self._rewrite()
            return entry

    def discard_many(self, entry_ids) -> None:
        ids = set(entry_ids)
        if not ids:
            return
        with self._lock:
            super().discard_many(ids)
            self._rewrite()

    def _rewrite(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for e in self.entries:
                    f.write(json.dumps(asdict(e), default=repr) + "\n")
            os.replace(tmp, self.path)
            self._file_lines = len(self.entries)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
