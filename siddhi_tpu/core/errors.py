"""Exception types + error store.

Reference: ``core/exception/`` (23 typed exceptions) and
``util/error/handler/store/ErrorStore.java`` — failed events persisted for replay.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional


class SiddhiAppCreationError(Exception):
    pass


class SiddhiAppRuntimeError(Exception):
    pass


class DefinitionNotExistError(SiddhiAppCreationError):
    pass


class QueryableRecordTableError(SiddhiAppRuntimeError):
    pass


class CannotRestoreStateError(SiddhiAppRuntimeError):
    pass


@dataclass
class ErrorEntry:
    id: int
    timestamp: int
    app_name: str
    stream_name: str
    event_data: Any
    error: str
    occurrence: str = "before"


class ErrorStore:
    """In-memory error store (reference ``ErrorStore`` abstract, saveEntry:160)."""

    def __init__(self, capacity: int = 10000):
        self.capacity = capacity
        self.entries: list[ErrorEntry] = []
        self._next_id = 1

    def save(self, app_name: str, stream_name: str, event, error: Exception) -> None:
        entry = ErrorEntry(
            id=self._next_id,
            timestamp=int(time.time() * 1000),
            app_name=app_name,
            stream_name=stream_name,
            event_data=list(getattr(event, "data", []) or []),
            error=repr(error),
        )
        self._next_id += 1
        self.entries.append(entry)
        if len(self.entries) > self.capacity:
            self.entries.pop(0)

    def load(self, app_name: str, stream_name: Optional[str] = None) -> list[ErrorEntry]:
        return [
            e for e in self.entries
            if e.app_name == app_name and (stream_name is None or e.stream_name == stream_name)
        ]

    def discard(self, entry_id: int) -> None:
        self.entries = [e for e in self.entries if e.id != entry_id]
