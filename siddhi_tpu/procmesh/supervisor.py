"""procmesh supervisor: spawns host workers, heartbeats them, restarts
crashed children, and replays the fabric's recovery path against REAL
SIGKILLed processes.

Each worker is one OS process (``python -m siddhi_tpu.procmesh.worker``)
handshaking its control port over stdout. Liveness detection runs two
signals through the existing resilience machinery:

- ``Popen.poll()`` — the process exited: unambiguous hard evidence, the
  peer detector :meth:`~siddhi_tpu.resilience.dcn_guard.PeerHealth.trip`
  path (no waiting out a failure threshold);
- heartbeat pings over the control socket — a hung-but-running child
  accumulates failures through the same ``PeerHealth``/CircuitBreaker
  ladder the DCN guard uses for peers (healthy → suspect → down).

Restarts pace through :class:`~siddhi_tpu.resilience.circuit.
RestartBackoff` (exponential, windowed give-up budget — a crash loop
becomes a recorded ``decision:give_up``, never a respawn storm). Every
supervisor decision lands on the flight recorder BEFORE the actuation
(``scripts/check_guard_coverage.py`` pins restart/give-up the same way it
pins the rebalancer), and heartbeat replies carry the workers' SLO
``mesh_replace`` escalations back to the fabric — the cross-host rung
works across process boundaries.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from ..observability.flight_recorder import FlightRecorder
from ..observability.histogram import LogHistogram
from ..resilience.circuit import RestartBackoff
from ..resilience.dcn_guard import PeerHealth
from .host import ProcMeshHost, WorkerClient
from .protocol import (
    READY_TIMEOUT_S,
    WorkerDown,
    WorkerOpError,
    child_env,
    connect,
    read_runfile,
    request,
)

log = logging.getLogger("siddhi_tpu.procmesh")


class WorkerSpawnError(RuntimeError):
    """A child process failed to reach its PROCMESH_READY handshake."""


class SupervisorConfig:
    """Supervisor knobs (kwargs-style; everything has a default)."""

    def __init__(self, heartbeat_interval_s: float = 0.5,
                 failure_threshold: int = 2,
                 down_cooldown_s: float = 0.5,
                 ready_timeout_s: float = READY_TIMEOUT_S,
                 restart_base_s: float = 0.25,
                 restart_max_s: float = 8.0,
                 restart_window_s: float = 60.0,
                 restart_max: int = 5,
                 auto_restart: bool = True,
                 env: Optional[dict] = None,
                 run_dir: Optional[str] = None,
                 io_timeout_s: Optional[float] = None,
                 connect_timeout_s: Optional[float] = None,
                 hedge_fraction: Optional[float] = 0.45,
                 wedge_threshold: int = 3,
                 degrade_factor: float = 4.0,
                 degrade_floor_s: float = 0.05,
                 degrade_min_samples: int = 16):
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.failure_threshold = int(failure_threshold)
        self.down_cooldown_s = float(down_cooldown_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.restart_base_s = float(restart_base_s)
        self.restart_max_s = float(restart_max_s)
        self.restart_window_s = float(restart_window_s)
        self.restart_max = int(restart_max)
        self.auto_restart = bool(auto_restart)
        self.env = dict(env or {})
        # workers persist runfiles here at handshake; a restarted
        # supervisor scans them to re-adopt live shards (parent recovery)
        self.run_dir = run_dir
        # gray-failure surface (ISSUE 19): base control-op deadline
        # (None = SIDDHI_PROCMESH_IO_TIMEOUT_S env or the module default),
        # the hedge fraction for idempotent ops (None disables hedging),
        # and the latency-evidence ladder knobs — wedge_threshold
        # consecutive substantive-op timeouts while heartbeats succeed ⇒
        # *wedged*; a windowed op p99 above degrade_factor × the fleet
        # median (and above degrade_floor_s, with degrade_min_samples in
        # the window) ⇒ *degraded*. degrade_factor <= 0 disables the rung.
        self.io_timeout_s = io_timeout_s
        self.connect_timeout_s = connect_timeout_s
        self.hedge_fraction = hedge_fraction
        self.wedge_threshold = int(wedge_threshold)
        self.degrade_factor = float(degrade_factor)
        self.degrade_floor_s = float(degrade_floor_s)
        self.degrade_min_samples = int(degrade_min_samples)


class ProcWorkerHandle:
    """Supervisor-side state of one child: the process, its live control
    port, the peer-health detector, and the restart budget."""

    def __init__(self, index: int, cfg: SupervisorConfig):
        self.index = index
        self.cfg = cfg
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.pid: Optional[int] = None
        self.nonce: Optional[str] = None
        # re-adopted across a parent restart: not our Popen child — liveness
        # and kills go through os.kill on the runfile pid instead
        self.adopted = False
        self.restarts = 0
        self.kills = 0
        self.gave_up = False
        self.health = PeerHealth(cfg.failure_threshold,
                                 cfg.down_cooldown_s)
        self.backoff = RestartBackoff(cfg.restart_base_s, cfg.restart_max_s,
                                      cfg.restart_window_s, cfg.restart_max)
        self.client = WorkerClient(lambda: self.port,
                                   io_timeout_s=cfg.io_timeout_s,
                                   connect_timeout_s=cfg.connect_timeout_s,
                                   hedge_fraction=cfg.hedge_fraction,
                                   observer=self.note_op)
        # latency EVIDENCE (ISSUE 19): every control op the fabric sends
        # through this handle's client lands in a per-op LogHistogram;
        # heartbeat RTTs get their own (a 1.9s heartbeat is no longer the
        # same evidence as a 1ms one). op_timeouts counts CONSECUTIVE
        # substantive-op failures — the wedge detector's input.
        self.hb_hist = LogHistogram()
        self.op_hist: dict = {}
        self.op_lat = LogHistogram()    # all non-ping ops merged
        self.lat_chk = None             # windowed-p99 cursor (degrade rung)
        self.op_timeouts = 0
        self.flight_cursor = 0          # child flight-ring tail (since_ns)
        # estimated wall-clock LEAD of the child over this process
        # (child_unix_ns - parent_unix_ns), from the ready hello and
        # refined by ping RTT midpoints — the federation layer uses it to
        # causally order merged flight timelines and stitched trace spans
        self.clock_offset_ns = 0

    def note_op(self, op: str, seconds: float, ok: bool) -> None:
        """WorkerClient observer: one record per user-level call, with the
        final outcome. A failed op still records the budget it burned —
        a timed-out op IS tail-latency evidence."""
        if op == "ping":
            return                  # heartbeats have their own histogram
        hist = self.op_hist.get(op)
        if hist is None:
            hist = self.op_hist[op] = LogHistogram()
        hist.record(seconds)
        self.op_lat.record(seconds)
        self.op_timeouts = 0 if ok else self.op_timeouts + 1

    @property
    def alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        if self.adopted and self.pid:
            try:
                os.kill(self.pid, 0)
                return True
            except OSError:
                return False
        return False

    def kill(self) -> None:
        """REAL SIGKILL — the chaos sites the in-process fabric simulates
        become an actual dead process here."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.kills += 1
        elif self.adopted and self.pid:
            try:
                os.kill(self.pid, signal.SIGKILL)
                self.kills += 1
            except OSError:
                pass                    # already gone
        self.port = None
        self.client.drop()
        self.health.trip()

    def reap(self, timeout: float = 5.0) -> None:
        if self.proc is not None:
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=timeout)
        elif self.adopted and self.pid:
            # not our child: init reaps the orphan — poll until it is gone
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                try:
                    os.kill(self.pid, 0)
                except OSError:
                    return
                time.sleep(0.05)


class ProcMeshSupervisor:
    """Spawns and shepherds one worker process per mesh host."""

    def __init__(self, num_workers: int,
                 config: Optional[SupervisorConfig] = None,
                 flight: Optional[FlightRecorder] = None,
                 playback: bool = True,
                 journal=None,
                 worker_state: Optional[dict] = None):
        self.cfg = config or SupervisorConfig()
        self.flight = flight or FlightRecorder(app_name="procmesh")
        self.playback = playback
        # durable control plane (parent recovery): restart/give-up
        # decisions journal BEFORE they actuate, so a restarted parent
        # re-seeds each worker's give-up budget instead of resetting it
        self.journal = journal
        self.handles = {i: ProcWorkerHandle(i, self.cfg)
                        for i in range(num_workers)}
        # fabric wiring: death/recovery callbacks + the SLO escalation
        # relay (heartbeat replies carry worker-side mesh_replace asks)
        self.on_failed: Optional[Callable[[int], None]] = None
        self.on_restarted: Optional[Callable[[int], None]] = None
        self.on_gave_up: Optional[Callable[[int], None]] = None
        self.on_escalation: Optional[Callable[[dict], None]] = None
        # gray-failure actuator wiring (ISSUE 19): the fabric drains a
        # degraded worker's tenants away / re-admits a recovered one
        self.on_degraded: Optional[Callable[[int], None]] = None
        self.on_undegraded: Optional[Callable[[int], None]] = None
        self._sm = None
        self._stop = threading.Event()
        self._monitor = None
        self._lock = threading.RLock()
        for h in self.handles.values():
            st = (worker_state or {}).get(h.index) \
                or (worker_state or {}).get(str(h.index))
            if st:
                h.restarts = int(st.get("restarts", 0))
                h.backoff.seed_attempt_ages(st.get("attempt_ages_s", ()))
                if st.get("gave_up"):
                    h.gave_up = True
        # adopt-or-spawn: a live shard from a previous parent incarnation
        # (runfile pid+nonce verified over its control socket) is re-adopted
        # in place; everything else forks fresh. Fork everything first, then
        # collect handshakes (boot cost is import-dominated; overlapping
        # hides it).
        spawned = []
        for h in self.handles.values():
            if h.gave_up:
                continue                # the budget died with the old parent
            if self.cfg.run_dir and self._adopt(h):
                continue
            self._spawn(h)
            spawned.append(h)
        for h in spawned:
            self._await_ready(h)

    # -- spawning ------------------------------------------------------------
    def _spawn(self, h: ProcWorkerHandle) -> None:
        env = child_env()
        env["SIDDHI_PROCMESH_CHILD"] = "1"      # no recursive pools
        env.update(self.cfg.env)
        cmd = [sys.executable, "-m", "siddhi_tpu.procmesh.worker",
               "--index", str(h.index),
               "--playback", "1" if self.playback else "0"]
        if self.cfg.run_dir:
            cmd += ["--rundir", self.cfg.run_dir]
        h.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=None, env=env)
        h.adopted = False
        h.pid = h.proc.pid
        h.port = None

    def _adopt(self, h: ProcWorkerHandle) -> bool:
        """Try to re-adopt a live worker left behind by a dead parent: dial
        the runfile's port and verify the shard's identity (pid AND boot
        nonce — a reused port or pid cannot spoof it). No restore, no
        respawn: the shard keeps its engine state and outbox."""
        rf = read_runfile(self.cfg.run_dir, h.index)
        if rf is None:
            return False
        try:
            sock = connect(int(rf["port"]))
            try:
                rh, _ = request(sock, "ping")
            finally:
                sock.close()
        except (WorkerDown, WorkerOpError, OSError):
            return False
        if (rh.get("pid") != rf.get("pid")
                or rh.get("nonce") != rf.get("nonce")
                or rh.get("index") != h.index):
            return False
        h.proc = None
        h.clock_offset_ns = 0           # refreshed below over the client
        h.adopted = True
        h.port = int(rf["port"])
        h.pid = int(rf["pid"])
        h.nonce = rf.get("nonce")
        h.health.record_success()
        self._refresh_clock(h)          # re-adoption refreshes the offset
        self.flight.record("procmesh", "worker_readopt",
                           site=f"worker:{h.index}",
                           detail={"pid": h.pid, "port": h.port,
                                   "clock_offset_ns": h.clock_offset_ns})
        return True

    def _refresh_clock(self, h: ProcWorkerHandle) -> None:
        """RTT-midpoint clock-offset estimate over one ping: the child's
        reply stamp minus the midpoint of our send/receive wall-clocks.
        Loopback RTTs are sub-millisecond, so the estimate's error bar is
        RTT/2 — documented in DISTRIBUTED.md as the causal-ordering
        caveat. Best-effort: a failed ping keeps the previous estimate."""
        try:
            t0 = time.time_ns()
            rh, _ = h.client.call("ping", timeout=5.0)
            t1 = time.time_ns()
        except WorkerDown:
            return
        child_ns = rh.get("unix_ns")
        if child_ns is not None:
            h.clock_offset_ns = int(child_ns) - (t0 + t1) // 2

    def _await_ready(self, h: ProcWorkerHandle) -> None:
        import json as _json
        line_box: list = []

        def read_line():
            line_box.append(h.proc.stdout.readline())

        t = threading.Thread(target=read_line, daemon=True)
        t.start()
        t.join(self.cfg.ready_timeout_s)
        line = line_box[0].decode() if line_box else ""
        if not line.startswith("PROCMESH_READY"):
            rc = h.proc.poll()
            h.kill()
            raise WorkerSpawnError(
                f"worker {h.index} never reached READY "
                f"(rc={rc}, line={line!r})")
        hello = _json.loads(line.split(None, 1)[1])
        h.port = int(hello["port"])
        h.pid = int(hello["pid"])
        h.nonce = hello.get("nonce")
        if hello.get("unix_ns") is not None:
            # coarse handshake estimate (biased by the stdout read delay);
            # the RTT-midpoint refresh below tightens it
            h.clock_offset_ns = int(hello["unix_ns"]) - time.time_ns()
        h.health.record_success()
        self._refresh_clock(h)

    # -- fabric host construction -------------------------------------------
    def host(self, index: int, capacity: int,
             device: Optional[int] = None) -> ProcMeshHost:
        return ProcMeshHost(self.handles[index], capacity, device=device,
                            playback=self.playback)

    # -- liveness / restart --------------------------------------------------
    def start_monitor(self) -> None:
        if self._monitor is not None:
            return
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="procmesh-supervisor",
            daemon=True)
        self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            for h in list(self.handles.values()):
                if self._stop.is_set():
                    return
                if h.gave_up:
                    continue
                try:
                    self._check(h)
                except Exception:   # noqa: BLE001 — one worker's turmoil
                    # must never take the monitor down
                    log.exception("procmesh: monitor check of worker %d "
                                  "failed", h.index)
            try:
                self._evaluate_degrade()
            except Exception:       # noqa: BLE001
                log.exception("procmesh: degrade evaluation failed")
            self._stop.wait(self.cfg.heartbeat_interval_s)

    def _check(self, h: ProcWorkerHandle) -> None:
        if not h.alive:
            self._on_death(h, cause="exit")
            return
        if not h.health.allow_probe():
            return
        try:
            t0 = time.time_ns()
            rh, _ = h.client.call("ping", timeout=self.cfg.down_cooldown_s
                                  + self.cfg.heartbeat_interval_s)
            t1 = time.time_ns()
        except WorkerDown:
            h.health.record_failure()
            if h.health.state == "down":
                self._on_death(h, cause="heartbeat")
            return
        h.health.record_success()
        rtt_s = (t1 - t0) / 1e9
        h.hb_hist.record(rtt_s)         # RTT is health EVIDENCE, not a bool
        if self._sm is not None:
            self._sm.latency_tracker(
                f"procmesh.w{h.index}.heartbeat").record_seconds(rtt_s)
        if rh.get("unix_ns") is not None:
            # every heartbeat refreshes the RTT-midpoint offset estimate
            h.clock_offset_ns = int(rh["unix_ns"]) - (t0 + t1) // 2
        if rh.get("uptime_s", 0) > self.cfg.restart_window_s:
            h.backoff.note_stable()     # a stable child earns its budget back
        for decision in rh.get("escalations", ()):
            if self.on_escalation is not None:
                self.on_escalation(decision)
        if (h.op_timeouts >= self.cfg.wedge_threshold
                and not h.health.wedged):
            # the gray signature: THIS heartbeat just succeeded while
            # substantive ops keep timing out — the worker is wedged
            self._on_wedged(h)

    def _on_wedged(self, h: ProcWorkerHandle) -> None:
        """Classify a heartbeat-OK-but-ops-timing-out worker as *wedged*
        and treat it as down (kill + backoff-paced restart). EVIDENCE
        FIRST: the classification, with the op-latency tails that earned
        it, is on the ring before the worker is condemned."""
        with self._lock:
            if h.gave_up or h.health.wedged:
                return
            self.flight.record(
                "procmesh", "decision:worker_wedged",
                site=f"worker:{h.index}",
                detail={"op_timeouts": h.op_timeouts,
                        "heartbeat_p99_s": h.hb_hist.percentile(0.99),
                        "op_p99_s": {op: hs.percentile(0.99)
                                     for op, hs in h.op_hist.items()}})
            h.health.mark_wedged()
        self._on_death(h, cause="wedged")

    def _evaluate_degrade(self) -> None:
        """Fleet-relative tail-outlier detection: each sweep closes one
        window over every worker's merged op histogram; a worker whose
        windowed p99 exceeds ``degrade_factor`` × the median of its PEERS'
        p99s (above an absolute floor) goes *degraded* and the fabric
        drains it. Recovery (half the trip threshold — hysteresis) clears
        the rung and re-admits the worker for placement."""
        cfg = self.cfg
        if cfg.degrade_factor <= 0:
            return
        wins = {}
        for h in self.handles.values():
            if h.gave_up or h.health.wedged or not h.alive:
                continue
            chk, h.lat_chk = h.lat_chk, h.op_lat.checkpoint()
            if chk is None:
                continue
            win = h.op_lat.since(chk)
            if win["count"] >= cfg.degrade_min_samples:
                wins[h.index] = win
        for idx, win in wins.items():
            others = sorted(w["p99"] for j, w in wins.items() if j != idx)
            if not others:
                continue            # fleet-relative needs a fleet
            med = others[len(others) // 2]
            trip = max(cfg.degrade_floor_s, cfg.degrade_factor * med)
            h = self.handles[idx]
            if win["p99"] > trip and not h.health.degraded:
                with self._lock:
                    if h.health.degraded:
                        continue
                    self.flight.record(
                        "procmesh", "decision:worker_degraded",
                        site=f"worker:{idx}",
                        detail={"p99_s": win["p99"],
                                "peer_median_p99_s": med,
                                "window_count": win["count"],
                                "factor": cfg.degrade_factor})
                    h.health.mark_degraded()
                if self.on_degraded is not None:
                    self.on_degraded(idx)
            elif h.health.degraded and win["p99"] <= trip / 2.0:
                with self._lock:
                    self.flight.record(
                        "procmesh", "worker_undegraded",
                        site=f"worker:{idx}",
                        detail={"p99_s": win["p99"],
                                "peer_median_p99_s": med})
                    h.health.clear_degraded()
                if self.on_undegraded is not None:
                    self.on_undegraded(idx)

    def _on_death(self, h: ProcWorkerHandle, cause: str) -> None:
        with self._lock:
            if h.gave_up:
                return
            # EVIDENCE FIRST: the failure is on the ring before any
            # teardown or restart moves state
            self.flight.record(
                "procmesh", "worker_down", site=f"worker:{h.index}",
                detail={"cause": cause, "pid": h.pid,
                        "rc": h.proc.poll() if h.proc else None})
            h.health.trip()
            h.port = None
            h.client.drop()
            if self.on_failed is not None:
                self.on_failed(h.index)
            if self.cfg.auto_restart:
                self.restart(h.index)

    def restart(self, index: int) -> bool:
        """Backoff-paced restart of one worker. The decision (with its
        delay and budget evidence) hits the ring BEFORE the spawn; a
        spent budget records ``decision:give_up`` instead and the worker
        stays down for an operator."""
        h = self.handles[index]
        with self._lock:
            delay = h.backoff.next_delay()
            if delay is None:
                self.flight.record(
                    "procmesh", "decision:give_up",
                    site=f"worker:{index}",
                    detail={"restarts": h.restarts,
                            **h.backoff.report()})
                self._journal("worker_gave_up", worker=index,
                              restarts=h.restarts)
                h.gave_up = True
                if self._sm is not None:
                    # a permanently-down worker's families go with it —
                    # no zombie gauges behind a give-up
                    self._sm.unregister(f"procmesh.w{index}.")
                if self.on_gave_up is not None:
                    self.on_gave_up(index)
                return False
            self.flight.record(
                "procmesh", "decision:restart_worker",
                site=f"worker:{index}",
                detail={"delay_s": delay, "restarts": h.restarts,
                        **h.backoff.report()})
            # journal the consumed attempt BEFORE the spawn: a parent
            # crash mid-restart must not refund the give-up budget
            self._journal("worker_restart", worker=index,
                          attempt_ages_s=h.backoff.attempt_ages_s())
            if delay:
                self._stop.wait(delay)
            h.kill()                    # no half-dead twins
            h.reap()
            # the respawn starts with a clean gray slate: the evidence
            # that condemned the old incarnation must not condemn the new
            h.health.clear_wedged()
            h.health.clear_degraded()
            h.op_timeouts = 0
            self._spawn(h)
            try:
                self._await_ready(h)
            except WorkerSpawnError:
                log.warning("procmesh: worker %d respawn failed", index)
                return self.restart(index)      # burn budget, maybe give up
            h.restarts += 1
            h.client.drop()
            if self.on_restarted is not None:
                self.on_restarted(index)
            return True

    def _journal(self, kind: str, **fields) -> None:
        if self.journal is not None:
            self.journal.append(kind, **fields)

    def worker_state(self) -> dict:
        """Journal-checkpoint form of the fleet's restart ledger."""
        return {h.index: {"restarts": h.restarts, "gave_up": h.gave_up,
                          "attempt_ages_s": h.backoff.attempt_ages_s()}
                for h in self.handles.values()}

    def kill_worker(self, index: int) -> Optional[int]:
        """Operator/chaos SIGKILL (recorded): returns the killed pid. The
        monitor (or an explicit :meth:`restart`) drives recovery."""
        h = self.handles[index]
        pid = h.pid
        self.flight.record("procmesh", "decision:kill_worker",
                           site=f"worker:{index}", detail={"pid": pid})
        h.kill()
        return pid

    # -- observability -------------------------------------------------------
    def register_metrics(self, sm) -> None:
        """``procmesh.w{i}.*`` + ``procmesh.self.*`` families; worker
        stop/give-up and supervisor shutdown unregister their prefixes
        (tests/test_metrics.py pins the teardown)."""
        self._sm = sm
        for h in self.handles.values():
            i = h.index
            sm.gauge_tracker(f"procmesh.w{i}.alive",
                             lambda h=h: 1 if h.alive else 0)
            sm.gauge_tracker(f"procmesh.w{i}.pid",
                             lambda h=h: h.pid or 0)
            sm.gauge_tracker(f"procmesh.w{i}.restarts_total",
                             lambda h=h: h.restarts)
            sm.gauge_tracker(f"procmesh.w{i}.kills_total",
                             lambda h=h: h.kills)
            sm.gauge_tracker(f"procmesh.w{i}.peer_state_code",
                             lambda h=h: h.health.state_code)
            sm.gauge_tracker(f"procmesh.w{i}.downtime_s",
                             lambda h=h: h.health.downtime_s())
            sm.gauge_tracker(f"procmesh.w{i}.last_downtime_s",
                             lambda h=h: h.health.last_downtime_s)
            sm.gauge_tracker(f"procmesh.w{i}.clock_offset_ns",
                             lambda h=h: h.clock_offset_ns)
            sm.gauge_tracker(f"procmesh.w{i}.op_timeouts",
                             lambda h=h: h.op_timeouts)
            sm.gauge_tracker(f"procmesh.w{i}.wedges_total",
                             lambda h=h: h.health.wedge_count)
            sm.gauge_tracker(f"procmesh.w{i}.degrades_total",
                             lambda h=h: h.health.degrade_count)
            sm.gauge_tracker(f"procmesh.w{i}.hedge_attempts_total",
                             lambda h=h: h.client.hedge_attempts)
            sm.gauge_tracker(f"procmesh.w{i}.hedge_wins_total",
                             lambda h=h: h.client.hedge_wins)
            # heartbeat RTT as a real histogram family —
            # siddhi_tpu_procmesh_heartbeat_seconds{worker="w{i}"};
            # _check records into it on every successful ping
            sm.latency_tracker(f"procmesh.w{i}.heartbeat")
        sm.gauge_tracker("procmesh.self.workers",
                         lambda: sum(1 for h in self.handles.values()
                                     if h.alive))
        sm.gauge_tracker("procmesh.self.restarts_total",
                         lambda: sum(h.restarts
                                     for h in self.handles.values()))
        sm.gauge_tracker("procmesh.self.gave_up",
                         lambda: sum(1 for h in self.handles.values()
                                     if h.gave_up))

    def report(self) -> dict:
        return {"workers": {
            h.index: {"alive": h.alive, "pid": h.pid, "port": h.port,
                      "restarts": h.restarts, "kills": h.kills,
                      "gave_up": h.gave_up, "adopted": h.adopted,
                      "op_timeouts": h.op_timeouts,
                      "heartbeat": h.hb_hist.snapshot(),
                      "op_p99_s": {op: hs.percentile(0.99)
                                   for op, hs in h.op_hist.items()},
                      "hedge_attempts": h.client.hedge_attempts,
                      "hedge_wins": h.client.hedge_wins,
                      **h.health.report()}
            for h in self.handles.values()}}

    # -- teardown ------------------------------------------------------------
    def shutdown(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        for h in self.handles.values():
            try:
                h.client.call("stop", timeout=2.0)
            except WorkerDown:
                pass
            h.client.drop()
        for h in self.handles.values():
            if h.proc is not None and h.proc.poll() is None:
                h.proc.terminate()
            elif h.adopted:
                # give the stop op a moment to land (the shard removes its
                # runfile on a clean exit) before escalating to SIGKILL
                deadline = time.monotonic() + 2.0
                while h.alive and time.monotonic() < deadline:
                    time.sleep(0.05)
                if h.alive:
                    h.kill()
        for h in self.handles.values():
            h.reap()
        if self._sm is not None:
            self._sm.unregister("procmesh.")
            self._sm = None
