"""Process-backed lane pool for the columnar host tier.

``@app:host_batch(workers=N, workers.mode='process')`` swaps
``HostPartitionedNFA``'s thread pool for N child PROCESSES, each owning a
contiguous shard of the lane space — the partitioned-NFA analog of the
mesh's process hosts, sidestepping the GIL for the scalar tails numpy
does not release it for.

Byte-parity contract (pinned against ``workers.mode='thread'`` and the
sequential loop by ``tests/test_procmesh.py``):

- children rebuild an IDENTICAL engine by re-parsing the SAME retained
  app source (``SiddhiApp.source_text``) — compile-order determinism
  keeps dictionary CONSTANT codes in agreement across processes;
- DATA codes are parent-minted (the stager's dictionaries); children
  only ever compare codes for equality, never decode, so one consistent
  encoding side is enough;
- the parent ships each shard its slice of the lane-sorted batch; the
  child returns match columns with SHARD-RELATIVE row indices and the
  parent maps them through ``order[row_lo + j]`` — then merges in
  shard→lane order and applies the same stable by-event sort as the
  thread path.

The wire is the procmesh control protocol (:mod:`.protocol` frames) with
pickled numpy bodies — parent and child are the same build of the same
tree, the one situation where pickle across a socket is sound.
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import subprocess
import sys
import threading
from typing import Optional

import numpy as np

from .protocol import (
    F_ERR,
    F_REQ,
    F_RES,
    READY_TIMEOUT_S,
    WorkerOpError,
    child_env,
    connect,
    io_timeout_s,
    recv_frame,
    send_frame,
)

_ACCEPT_POLL_S = 0.5
_STEP_TIMEOUT_S = 120.0


class LanePoolError(RuntimeError):
    """A lane child died or misbehaved mid-step: the batch outcome is
    unknowable, so the pool surfaces a hard error (the host-path guard
    quarantines the bridge exactly like any other engine fault)."""


# ---------------------------------------------------------------------------
# parent side
# ---------------------------------------------------------------------------

class _LaneChild:
    """One spawned shard: process handle + its persistent control socket."""

    def __init__(self, worker_index: int, lane_lo: int, lane_hi: int):
        self.worker_index = worker_index
        self.lane_lo = lane_lo
        self.lane_hi = lane_hi
        self.proc: Optional[subprocess.Popen] = None
        self.sock: Optional[socket.socket] = None

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ProcessLanePool:
    """N lane-shard children stepped in lockstep by the parent NFA.

    ``step`` overlaps the shards: every child's request frame goes out
    before any reply is read — one outstanding request per socket, so
    plain send-all/recv-all is the whole scheduler."""

    def __init__(self, source: dict, P: int, workers: int,
                 snaps: list, env: Optional[dict] = None):
        self.source = dict(source)
        self.P = int(P)
        self.workers = max(1, min(int(workers), self.P))
        cuts = [self.P * w // self.workers
                for w in range(self.workers + 1)]
        self.children = [_LaneChild(w, cuts[w], cuts[w + 1])
                         for w in range(self.workers)]
        self._cuts = cuts
        self._env = dict(env or {})
        self._lock = threading.Lock()
        try:
            for ch in self.children:
                self._spawn(ch, snaps[ch.lane_lo:ch.lane_hi])
        except Exception:
            self.close()
            raise

    # -- lifecycle -----------------------------------------------------------
    def _spawn(self, ch: _LaneChild, shard_snaps: list) -> None:
        env = child_env()
        env["SIDDHI_PROCMESH_CHILD"] = "1"   # no recursive pools
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self._env)
        ch.proc = subprocess.Popen(
            # -c, not -m: the package __init__ already imports this module,
            # and runpy would warn about the double execution
            [sys.executable, "-c",
             "from siddhi_tpu.procmesh.lanepool import main; main()"],
            stdout=subprocess.PIPE, stderr=None, env=env, text=True)
        port = self._await_ready(ch)
        ch.sock = connect(port)
        self._rpc(ch, "init", body=pickle.dumps({
            **self.source,
            "P": self.P,
            "lane_lo": ch.lane_lo,
            "lane_hi": ch.lane_hi,
            "snaps": shard_snaps,
        }), timeout=READY_TIMEOUT_S)

    def _await_ready(self, ch: _LaneChild) -> int:
        box: dict = {}

        def read():
            line = ch.proc.stdout.readline()
            if line.startswith("PROCMESH_READY "):
                box.update(json.loads(line.split(" ", 1)[1]))

        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(READY_TIMEOUT_S)
        if "port" not in box:
            try:
                ch.proc.kill()
            except OSError:
                pass
            raise LanePoolError(
                f"lane child {ch.worker_index} did not become ready")
        return int(box["port"])

    def close(self) -> None:
        for ch in self.children:
            if ch.sock is not None:
                try:
                    send_frame(ch.sock, F_REQ, {"op": "stop"})
                except OSError:
                    pass
                try:
                    ch.sock.close()
                except OSError:
                    pass
                ch.sock = None
            if ch.proc is not None:
                try:
                    ch.proc.terminate()
                    ch.proc.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    try:
                        ch.proc.kill()
                    except OSError:
                        pass

    # -- rpc ------------------------------------------------------------------
    def _rpc(self, ch: _LaneChild, op: str, header: Optional[dict] = None,
             body: bytes = b"", timeout: Optional[float] = None):
        if timeout is None:
            timeout = io_timeout_s()    # env-tunable, resolved per op
        h = dict(header or {})
        h["op"] = op
        try:
            send_frame(ch.sock, F_REQ, h, body)
            kind, rh, rbody = recv_frame(ch.sock, timeout=timeout)
        except (OSError, ValueError, ConnectionError) as e:
            raise LanePoolError(
                f"lane child {ch.worker_index} died mid-'{op}': {e}") from e
        if kind == F_ERR:
            raise LanePoolError(
                f"lane child {ch.worker_index} '{op}' failed: "
                f"{rh.get('error')}")
        return rh, rbody

    # -- the pool surface the NFA steps against --------------------------------
    def step(self, bounds: np.ndarray, cols_sorted: dict,
             ts_sorted: np.ndarray, order: np.ndarray) -> list:
        """One lane-sorted batch through every shard; returns the merged
        ``outs`` list in shard→lane order with GLOBAL ``j`` (pre-sort
        event positions) — the thread path's ``_run_lanes`` contract."""
        with self._lock:
            plans = []
            for ch in self.children:
                row_lo = int(bounds[ch.lane_lo])
                row_hi = int(bounds[ch.lane_hi])
                rel = (np.asarray(bounds[ch.lane_lo:ch.lane_hi + 1],
                                  dtype=np.int64) - row_lo)
                if row_lo == row_hi:
                    plans.append((ch, row_lo, None))
                    continue
                body = pickle.dumps({
                    "bounds": rel,
                    "cols": {k: v[row_lo:row_hi]
                             for k, v in cols_sorted.items()},
                    "ts": ts_sorted[row_lo:row_hi],
                })
                try:
                    send_frame(ch.sock, F_REQ, {"op": "step"}, body)
                except (OSError, ConnectionError) as e:
                    raise LanePoolError(
                        f"lane child {ch.worker_index} died on send: "
                        f"{e}") from e
                plans.append((ch, row_lo, True))
            outs = []
            for ch, row_lo, sent in plans:
                if sent is None:
                    continue
                try:
                    kind, rh, rbody = recv_frame(
                        ch.sock, timeout=_STEP_TIMEOUT_S)
                except (OSError, ValueError, ConnectionError) as e:
                    raise LanePoolError(
                        f"lane child {ch.worker_index} died mid-step: "
                        f"{e}") from e
                if kind == F_ERR:
                    raise LanePoolError(
                        f"lane child {ch.worker_index} step failed: "
                        f"{rh.get('error')}")
                for m in pickle.loads(rbody):
                    m["j"] = order[row_lo + m["j"]]
                    outs.append(m)
            return outs

    def snapshot_lanes(self) -> list:
        """Full-P lane snapshot list assembled from the shard owners."""
        with self._lock:
            lanes: list = []
            for ch in self.children:
                _, rbody = self._rpc(ch, "snap")
                lanes.extend(pickle.loads(rbody))
            return lanes

    def restore_lanes(self, lane_snaps: list) -> None:
        with self._lock:
            for ch in self.children:
                self._rpc(ch, "restore", body=pickle.dumps(
                    lane_snaps[ch.lane_lo:ch.lane_hi]))

    def match_count(self) -> int:
        with self._lock:
            total = 0
            for ch in self.children:
                rh, _ = self._rpc(ch, "stats")
                total += int(rh.get("matches", 0))
            return total

    def report(self) -> dict:
        return {
            "workers": self.workers,
            "cuts": list(self._cuts),
            "alive": sum(1 for ch in self.children if ch.alive),
            "pids": [ch.proc.pid if ch.proc else None
                     for ch in self.children],
        }


# ---------------------------------------------------------------------------
# child side
# ---------------------------------------------------------------------------

class _LaneShardServer:
    """One lane shard: rebuilds the engine from the retained app source,
    owns lane states ``[lane_lo, lane_hi)``, answers step/snap/restore."""

    def __init__(self):
        self.prt = None
        self.lane_lo = 0
        self.lane_hi = 0

    def op_init(self, h: dict, body: bytes):
        cfg = pickle.loads(body)
        from ..compiler import parse
        from ..tpu.host_exec import HostPartitionedNFA
        app = parse(cfg["app_text"])
        part = app.partitions[cfg["part_index"]]
        q = part.queries[cfg["query_index"]]
        # same text → same parse → same compile order → same constant codes
        self.prt = HostPartitionedNFA(
            q, dict(app.stream_definitions), cfg["key_attr"],
            num_partitions=cfg["P"], workers=1)
        self.lane_lo = int(cfg["lane_lo"])
        self.lane_hi = int(cfg["lane_hi"])
        for lane, snap in zip(range(self.lane_lo, self.lane_hi),
                              cfg.get("snaps") or ()):
            self.prt.lane_states[lane] = self.prt.engine.restore_state(snap)
        return {"lanes": [self.lane_lo, self.lane_hi]}, b""

    def op_step(self, h: dict, body: bytes):
        req = pickle.loads(body)
        bounds, cols, ts = req["bounds"], req["cols"], req["ts"]
        outs = []
        for li, lane in enumerate(range(self.lane_lo, self.lane_hi)):
            lo, hi = int(bounds[li]), int(bounds[li + 1])
            if lo == hi:
                continue
            lcols = {k: v[lo:hi] for k, v in cols.items()}
            self.prt.lane_states[lane], m = self.prt.engine.step(
                self.prt.lane_states[lane], lcols, None, ts[lo:hi])
            if m and m["j"].size:
                m = dict(m)
                m["j"] = m["j"] + lo        # shard-relative row position
                outs.append(m)
        return {"n": len(outs)}, pickle.dumps(outs)

    def op_snap(self, h: dict, body: bytes):
        snaps = [self.prt.engine.snapshot_state(st)
                 for st in self.prt.lane_states[self.lane_lo:self.lane_hi]]
        return {"n": len(snaps)}, pickle.dumps(snaps)

    def op_restore(self, h: dict, body: bytes):
        for lane, snap in zip(range(self.lane_lo, self.lane_hi),
                              pickle.loads(body)):
            self.prt.lane_states[lane] = self.prt.engine.restore_state(snap)
        return {"ok": True}, b""

    def op_stats(self, h: dict, body: bytes):
        matches = sum(
            int(st["matches"])
            for st in self.prt.lane_states[self.lane_lo:self.lane_hi])
        return {"matches": matches, "pid": os.getpid()}, b""


def _serve(listener: socket.socket) -> None:
    """Single-connection serve loop: the parent pool is the only client.
    Every read arms a deadline (``scripts/check_socket_timeouts.py``)."""
    server = _LaneShardServer()
    listener.settimeout(_ACCEPT_POLL_S)
    conn = None
    while conn is None:
        try:
            conn, _ = listener.accept()
        except socket.timeout:
            continue
    conn.settimeout(io_timeout_s())
    while True:
        try:
            kind, h, body = recv_frame(conn, timeout=_STEP_TIMEOUT_S)
        except (ValueError, ConnectionError, OSError):
            return                          # parent gone: exit with it
        op = h.get("op", "")
        if op == "stop":
            return
        fn = getattr(server, f"op_{op}", None)
        try:
            if fn is None:
                raise WorkerOpError(f"unknown lane-pool op '{op}'")
            rh, rbody = fn(h, body)
            send_frame(conn, F_RES, rh, rbody)
        except Exception as e:   # noqa: BLE001 — fault becomes a frame
            try:
                send_frame(conn, F_ERR, {"error": f"{type(e).__name__}: {e}"})
            except OSError:
                return


def main() -> int:
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    print(f"PROCMESH_READY {json.dumps({'port': port, 'pid': os.getpid()})}",
          flush=True)
    try:
        _serve(listener)
    finally:
        listener.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
