"""Killable parent entrypoint for durable-fabric chaos tests.

``python -m siddhi_tpu.procmesh.parentmain --root DIR ...`` runs a
durable process-mode :class:`~siddhi_tpu.mesh.fabric.MeshFabric` as a
REAL parent process the test harness can SIGKILL mid-ingest (via the
``SIDDHI_CRASH_AT`` hooks in ``journal.py``) and then restart against the
same ``--root``. The runner is a crash-oblivious idempotent client of the
fabric's recovery contract:

- tenants deploy only if the journal did not already resurrect them;
- per-tenant sinks are append-only JSONL files keyed by the ``(epoch,
  idx)`` output identity — at-least-once delivery dedups offline
  (keep-first), exactly how an idempotent downstream would;
- the feed resumes from each tenant's recovered ``applied`` mark (chunk
  ``c`` carries seq ``c+1``), so a restarted run re-sends exactly the
  chunks the crash lost;
- the hand-shake line ``PARENT_DONE {json}`` carries the recovery stats,
  journal position and applied marks for the harness to assert on.

The chunk generator (:func:`chunk_rows`) is deterministic and importable
so tests compute solo oracles from the same bytes.
"""

from __future__ import annotations

import argparse
import json
import os

APP_TMPL = ("@app:name('t{i}')\n"
            "define stream S (dev string, v double);\n"
            "@info(name='q') from S[v > 1.0] select dev, v "
            "insert into Out;\n")


def chunk_rows(c: int, width: int):
    """Deterministic chunk ``c``: every row passes the ``v > 1.0`` filter,
    so the solo oracle is the rows themselves."""
    rows = [[f"d{c}_{w}", 1.5 + c + 0.001 * w] for w in range(width)]
    ts = [1000 + c] * width
    return rows, ts


def _sink(f, tid: str):
    def hook(entries):
        for e in entries:
            f.write(json.dumps(
                {"t": tid, "e": int(e[0]), "i": int(e[1]), "s": e[2],
                 "ts": e[3], "d": list(e[4])},
                separators=(",", ":")) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return hook


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True)
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--tenants", type=int, default=2)
    ap.add_argument("--chunks", type=int, default=4)
    ap.add_argument("--width", type=int, default=2)
    ap.add_argument("--migrate-at", type=int, default=-1)
    ap.add_argument("--snapshot-every", type=int, default=1)
    args = ap.parse_args(argv)

    from ..mesh.fabric import MeshConfig, MeshFabric
    cfg = MeshConfig(mode="process", durable=True,
                     snapshot_every_chunks=args.snapshot_every,
                     heartbeat_interval_s=0.3,
                     capacity_per_host=max(4, args.tenants + 1))
    fab = MeshFabric(args.hosts, args.root, config=cfg)
    tids = [f"t{i}" for i in range(args.tenants)]
    missing = [APP_TMPL.format(i=i) for i in range(args.tenants)
               if f"t{i}" not in fab.tenants]
    if missing:
        fab.add_tenants(missing)

    sinks = []
    for tid in tids:
        f = open(os.path.join(args.root, f"sink_{tid}.jsonl"), "a",
                 encoding="utf-8")
        sinks.append(f)
        fab.add_output_hook(tid, _sink(f, tid), streams=("Out",))
    # hooks are armed: journal-staged outputs from dead incarnations
    # replay now, re-adopted tenants re-snapshot
    fab.resume_output_delivery()

    for c in range(args.chunks):
        if args.migrate_at == c and args.hosts > 1:
            st0 = fab.tenants[tids[0]]
            dst = (st0.host + 1) % args.hosts
            if st0.host != dst:
                fab.migrate(tids[0], dst)
        rows, ts = chunk_rows(c, args.width)
        for tid in tids:
            if fab.tenants[tid].applied >= c + 1:
                continue                 # applied before the crash: skip
            fab.send(tid, "S", rows, ts)

    rep = fab.report()
    done = {"recovery": rep["recovery"], "journal": rep["journal"],
            "dup_chunks": rep["dup_chunks"],
            "supervisor": {i: {"adopted": w["adopted"], "pid": w["pid"]}
                           for i, w in rep["supervisor"]["workers"].items()},
            "applied": {tid: fab.tenants[tid].applied for tid in tids}}
    fab.close()
    for f in sinks:
        f.close()
    print("PARENT_DONE " + json.dumps(done, sort_keys=True), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
