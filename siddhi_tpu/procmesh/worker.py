"""procmesh host worker: one engine shard as its own OS process.

``python -m siddhi_tpu.procmesh.worker --index N`` boots an isolated
``SiddhiManager`` (so its own FleetManager → its own shared-plan cache →
its own GIL and its own JAX runtime) and serves the procmesh control
socket. The supervisor reads the ``PROCMESH_READY <port>`` handshake line
from stdout, then the fabric drives everything over
:mod:`~siddhi_tpu.procmesh.protocol` frames.

Exactly-once discipline (the fabric side is
``mesh/fabric.py._apply_locked``):

- every ingest op carries the tenant's monotone chunk ``seq``; the worker
  keeps its own applied mark and DEDUPS retried ops (a lost ack must not
  double-apply — the ``K_ROWS`` receiver discipline applied to control
  ops);
- output events land in a per-tenant cursored outbox; every reply ships
  the entries past the client's acked cursor, so a retried op re-delivers
  the same events with the same indices and the parent dedups by cursor —
  lost-ack retries are idempotent for outputs too;
- the parent delivers outputs only AFTER the chunk is durable in its
  snapshot store, so a child killed between apply and ack re-applies the
  chunk from the restored pre-chunk state and emits exactly once.

Every socket read in the serve loop arms a deadline
(``scripts/check_socket_timeouts.py`` pins the invariant); idle timeouts
re-check the stop flag, the DCN worker's serve pattern.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import socket
import sys
import threading
import time

from .protocol import (
    F_ERR,
    F_REQ,
    F_RES,
    io_timeout_s,
    recv_frame,
    send_frame,
    wire_counters,
)

log = logging.getLogger("siddhi_tpu.procmesh.worker")

_ACCEPT_POLL_S = 0.5


class _Tenant:
    """Worker-side state of one deployed tenant: the runtime, the ingest
    dedup mark, and the cursored output outbox."""

    __slots__ = ("rt", "applied", "out", "out_next", "subs")

    def __init__(self, rt):
        self.rt = rt
        self.applied = 0        # last applied chunk seq (op dedup mark)
        self.out = []           # [(idx, stream_id, ts, row), ...] retained
        self.out_next = 0       # next outbox index to assign
        self.subs = set()       # streams with capture armed (subscribe dedup)


class WorkerServer:
    """The child-process engine shard behind one control socket."""

    def __init__(self, index: int, playback: bool = True):
        from ..core.manager import SiddhiManager
        self.index = index
        self.playback = playback
        from ..observability.flight_recorder import FlightRecorder
        self.manager = SiddhiManager()
        # the shard's own control-plane timeline (deploy/restore/drain):
        # the parent tails it through op_flight and absorbs it into the
        # fabric's ring under the ``h{i}:`` site prefix
        self.flight = FlightRecorder(app_name=f"procmesh-w{index}")
        self.tenants: dict = {}            # tenant_id -> _Tenant
        self.rows_in = 0
        self.escalations: list = []        # SLO mesh_replace decisions
        # trace-journey shipping cursors: (tenant, (origin, trace_id)) ->
        # spans already shipped on an op_flight tail, so re-polls ship only
        # span growth (bounded: evicted oldest-first past the cap)
        from collections import OrderedDict
        self._trace_shipped: "OrderedDict" = OrderedDict()
        self._trace_shipped_cap = 4096
        self.dcn = None                    # optional worker-owned DCNWorker
        # boot identity: a restarted supervisor re-adopts a live worker only
        # if pid AND nonce match its runfile (pid reuse cannot spoof a shard)
        self.nonce = os.urandom(8).hex()
        self.started = time.monotonic()
        # gray-failure chaos hook (ISSUE 19): when armed (op_wedge or the
        # SIDDHI_PROCMESH_WEDGE_S env at boot), every SUBSTANTIVE op
        # stalls this many seconds BEFORE taking the dispatch lock — so
        # heartbeat pings keep answering while real work times out: the
        # alive-yet-wedged gray failure, as a real process
        try:
            self._wedge_s = float(
                os.environ.get("SIDDHI_PROCMESH_WEDGE_S", 0) or 0)
        except ValueError:
            self._wedge_s = 0.0
        self._lock = threading.RLock()     # all op handling (control rate)
        self._stop = threading.Event()
        self._listener = None
        self._threads: list = []
        self.port = None

    # -- lifecycle -----------------------------------------------------------
    def bind(self, port: int = 0) -> int:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(8)
        srv.settimeout(_ACCEPT_POLL_S)     # accept() re-checks stop
        self._listener = srv
        self.port = srv.getsockname()[1]
        return self.port

    def serve_forever(self) -> None:
        self._listener.settimeout(_ACCEPT_POLL_S)  # accept re-checks stop
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve, args=(conn,),
                                 name=f"procmesh-w{self.index}-conn",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        self._shutdown()

    def _shutdown(self) -> None:
        with self._lock:
            if self.dcn is not None:
                try:
                    self.dcn.close()
                except Exception:   # noqa: BLE001 — exiting anyway
                    pass
                self.dcn = None
            self.manager.shutdown()
            self.tenants.clear()
        try:
            self._listener.close()
        except OSError:
            pass

    # -- serve loop ----------------------------------------------------------
    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(io_timeout_s())
        try:
            while not self._stop.is_set():
                try:
                    frame = recv_frame(conn, timeout=_ACCEPT_POLL_S)
                except socket.timeout:
                    continue          # idle between frames; re-check stop
                except (OSError, ConnectionError):
                    return
                if frame is None:
                    return
                kind, header, body = frame
                if kind != F_REQ:
                    return            # protocol violation: drop the conn
                op = header.get("op", "")
                try:
                    rh, rbody = self._dispatch(op, header, body)
                    send_frame(conn, F_RES, rh, rbody)
                except Exception as e:   # noqa: BLE001 — one op's failure
                    # is a structured reply, not a dead control plane
                    log.exception("procmesh worker %d: op '%s' failed",
                                  self.index, op)
                    try:
                        send_frame(conn, F_ERR,
                                   {"error": f"{type(e).__name__}: {e}"})
                    except OSError:
                        return
                if op == "stop":
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, op: str, h: dict, body: bytes):
        fn = getattr(self, f"op_{op}", None)
        if fn is None:
            raise ValueError(f"unknown procmesh op '{op}'")
        if self._wedge_s > 0 and op not in ("ping", "wedge", "stop"):
            # stall OUTSIDE the dispatch lock: a wedge that held the lock
            # would also stall pings and read as a plain crash — the whole
            # point is heartbeats stay green while work times out
            time.sleep(self._wedge_s)
        with self._lock:
            return fn(h, body)

    # -- tenant helpers ------------------------------------------------------
    def _tenant(self, h: dict) -> _Tenant:
        t = self.tenants.get(h["tenant"])
        if t is None:
            raise KeyError(f"tenant '{h['tenant']}' not deployed")
        return t

    def _arm_slo_hook(self, rt) -> None:
        """Worker-side half of the fabric's cross-host SLO rung: the
        controller's ``mesh_replace`` decision lands in the escalation
        outbox the supervisor drains with each heartbeat."""
        for b in getattr(rt, "fleet_bridges", []):
            group = b.member.group
            if group is not None and group.slo is not None:
                group.slo.mesh_hook = self._escalate

    def _escalate(self, decision: dict) -> bool:
        self.escalations.append({
            k: v for k, v in decision.items()
            if isinstance(v, (str, int, float, bool, type(None)))})
        return True

    def _prune_out(self, t: _Tenant, ack: int) -> None:
        if ack >= 0 and t.out and t.out[0][0] <= ack:
            t.out = [e for e in t.out if e[0] > ack]

    def _out_tail(self, t: _Tenant, ack: int) -> list:
        self._prune_out(t, ack)
        return [list(e) for e in t.out]

    # -- ops -----------------------------------------------------------------
    def op_ping(self, h: dict, body: bytes):
        esc, self.escalations = self.escalations, []
        return {"pid": os.getpid(),
                "nonce": self.nonce,
                "index": self.index,
                "uptime_s": time.monotonic() - self.started,
                "tenants": len(self.tenants),
                "rows_in": self.rows_in,
                # the shard's wall-clock at reply build: the supervisor
                # estimates this process's clock offset from the request
                # RTT midpoint (refreshed on every adoption/restart)
                "unix_ns": time.time_ns(),
                # receiver-side wire-integrity detections (crc_rejected /
                # dup_frames_dropped): the exactly-once evidence the
                # chaos gauntlet reads back
                "wire": wire_counters(),
                "escalations": esc}, b""

    def op_wedge(self, h: dict, body: bytes):
        """Chaos op: arm (or clear, with 0) the gray-failure stall — every
        subsequent substantive op sleeps ``stall_s`` before dispatch while
        pings keep answering. The bench gauntlet and tests wedge a LIVE
        worker mid-run with this; production never calls it."""
        self._wedge_s = float(h.get("stall_s", 0) or 0)
        self.flight.record("procmesh", "chaos:wedge", f"w{self.index}",
                           detail={"stall_s": self._wedge_s})
        return {"stall_s": self._wedge_s}, b""

    def op_deploy(self, h: dict, body: bytes):
        tid = h["tenant"]
        if tid in self.tenants:
            return {"deployed": False}, b""      # idempotent retry
        rt = self.manager.create_siddhi_app_runtime(
            h["app_text"], playback=h.get("playback", self.playback))
        rt.start()
        self.tenants[tid] = _Tenant(rt)
        self._arm_slo_hook(rt)
        self.flight.record("procmesh", "deploy", f"w{self.index}",
                           detail={"tenant": tid})
        return {"deployed": True}, b""

    def op_undeploy(self, h: dict, body: bytes):
        t = self.tenants.pop(h["tenant"], None)
        if t is not None:
            t.rt.shutdown()
            self.manager.runtimes.pop(h["tenant"], None)
            self.flight.record("procmesh", "undeploy", f"w{self.index}",
                               detail={"tenant": h["tenant"]})
        return {"undeployed": t is not None}, b""

    def op_subscribe(self, h: dict, body: bytes):
        """Arm output capture for one stream: emissions append to the
        tenant's cursored outbox (idempotent per stream)."""
        from ..core.stream import StreamCallback
        t = self._tenant(h)
        sid = h["stream"]
        if sid in t.subs:
            # a restarted parent re-subscribes blindly; a second capture
            # would double-append every emission to the outbox
            return {}, b""
        t.subs.add(sid)

        def capture(evs, t=t, sid=sid):
            for e in evs:
                t.out.append((t.out_next, sid, e.timestamp, list(e.data)))
                t.out_next += 1
        t.rt.add_callback(sid, StreamCallback(capture))
        return {}, b""

    def op_ingest(self, h: dict, body: bytes):
        """Apply one seq-stamped chunk through the dedup mark. The reply
        carries the outbox tail past the client's ``ack`` cursor — dup ops
        (lost-ack retries) re-ship the same events, apply nothing.

        A sampled TraceContext may ride the header (hex-packed). Adoption
        happens ONLY inside the apply branch — the ``K_ROWS`` discipline:
        a lost-ack retry dedups on ``seq`` and never re-adopts, so spans
        stay exactly-once alongside the rows."""
        t = self._tenant(h)
        seq = int(h["seq"])
        applied = False
        if seq > t.applied:
            if h.get("enc") == "soa":
                from ..tpu.dcn import unpack_rows
                rows, tss = unpack_rows(body)
            else:
                rows, tss = h["rows"], h["ts"]
            rows = [list(r) for r in rows]
            tss = list(tss)
            self._apply_traced(t, h, rows, tss)
            t.applied = seq
            self.rows_in += len(rows)
            applied = True
        return {"applied": applied,
                "events": self._out_tail(t, int(h.get("ack", -1)))}, b""

    def _apply_traced(self, t: _Tenant, h: dict, rows: list,
                      tss: list) -> None:
        """Deliver an applied chunk, stitching a trace-context header into
        the tenant tracer's ring: the adopted trace gets a ``procmesh``
        transit span (dispatch wall-clock → apply, so retry delay counts as
        transit) and is ACTIVE while the engine runs, so device/sink spans
        land on the same journey. The transit also records into the
        ``phase.{stream}.procmesh_transit`` histogram — scraped by the
        parent through op_metrics for the federated breakdown."""
        ih = t.rt.input_handler(h["stream"])
        tracer = getattr(t.rt.ctx, "tracer", None)
        ctx_hex = h.get("trace")
        if tracer is None and ctx_hex:
            # the parent fabric samples traces even for tenant apps that
            # carry no @app:trace of their own — install an adopt-only
            # tracer (host=None: it never mints shippable local journeys;
            # the huge sample keeps the untraced send_rows path quiet)
            from ..observability.tracing import PipelineTracer
            tracer = t.rt.ctx.tracer = PipelineTracer(
                sample_n=1 << 20, ring_size=256, host=None)
        if tracer is None or not ctx_hex:
            ih.send_rows(rows, tss)
            return
        from ..observability.tracing import TraceContext
        try:
            ctx = TraceContext.unpack_from(bytes.fromhex(ctx_hex))
        except Exception:   # noqa: BLE001 — a malformed trace header
            ih.send_rows(rows, tss)       # must never drop the rows
            return
        now_unix = time.time_ns()
        transit_ns = max(0, now_unix - ctx.sent_unix_ns)
        tr = tracer.adopt(ctx)
        tr.add_span("procmesh", f"transit:w{self.index}", transit_ns,
                    batch_size=len(rows),
                    start_offset_ns=max(
                        0, ctx.sent_unix_ns - ctx.ingress_unix_ns))
        sm = t.rt.ctx.statistics_manager
        sm.latency_tracker(
            f"phase.{h['stream']}.procmesh_transit").record_seconds(
            transit_ns / 1e9, n=len(rows), exemplar=ctx.trace_id)
        # bypass send_rows' own sampler (it would mint a SIBLING trace and
        # split the journey) — same traced-ingress idiom, adopted trace
        t0 = time.perf_counter_ns()
        tracer.push(tr)
        try:
            ih._send_rows(rows, tss)
        finally:
            tracer.pop()
            tr.add_span("ingress", h["stream"],
                        time.perf_counter_ns() - t0, len(rows))

    def op_resync(self, h: dict, body: bytes):
        """Parent-recovery reconciliation: a restarted supervisor re-adopts
        this LIVE shard without restore. The reply carries the authoritative
        child-side applied mark (>= anything the parent journaled) plus the
        outbox tail past the journaled delivery cursor ``ack`` — entries the
        old parent delivered but never acked re-ship with their original
        indices, so idempotent sinks dedup them byte-exactly."""
        t = self.tenants.get(h["tenant"])
        if t is None:
            return {"present": False}, b""
        return {"present": True, "applied": t.applied,
                "events": self._out_tail(t, int(h.get("ack", -1)))}, b""

    def op_flush(self, h: dict, body: bytes):
        t = self._tenant(h)
        t.rt.flush_host()
        return {"events": self._out_tail(t, int(h.get("ack", -1)))}, b""

    def op_snapshot(self, h: dict, body: bytes):
        t = self._tenant(h)
        return {"applied": t.applied}, t.rt.snapshot()

    def op_restore(self, h: dict, body: bytes):
        """Restore the tenant from parent-store state bytes; the header's
        ``applied`` mark re-seeds the ingest dedup window (re-restore from
        the same revision is idempotent — the ``K_ADOPT`` discipline)."""
        t = self._tenant(h)
        t.rt.restore(body)
        t.applied = int(h.get("applied", 0))
        self._arm_slo_hook(t.rt)
        self.flight.record("procmesh", "restore", f"w{self.index}",
                           detail={"tenant": h["tenant"],
                                   "applied": t.applied})
        return {}, b""

    def op_evidence(self, h: dict, body: bytes):
        return {"evidence": {
            "tenants": len(self.tenants),
            "rows_in": self.rows_in,
            "pid": os.getpid(),
            "wire": wire_counters(),
            "compiled_programs":
                self.manager.fleet.plan_cache.stats()["size"],
            **self.manager.fleet.mesh_evidence(),
        }}, b""

    def op_metrics(self, h: dict, body: bytes):
        """Scrape every deployed runtime's trackers (name-spaced by
        tenant) for parent-side aggregation — the child's families never
        register in the parent's StatisticsManager directly, so a dead
        child can never leak zombie gauges there.

        Beyond the original gauge floats, the reply ships counters and
        FULL latency-histogram states (:meth:`LogHistogram.state` — fixed
        quarter-octave ladder, so the parent merges by summing counts):
        the federation plane's raw material. ``unix_ns`` stamps the scrape
        for parent-side freshness accounting."""
        gauges, counters, latency = {}, {}, {}
        for tid, t in self.tenants.items():
            sm = t.rt.ctx.statistics_manager
            snap = sm.snapshot_trackers()
            for name, tr in snap.get("gauges", {}).items():
                try:
                    gauges[f"{tid}.{name}"] = float(tr.value)
                except Exception:   # noqa: BLE001 — one bad gauge must not
                    continue        # take the scrape down
            for name, tr in snap.get("counters", {}).items():
                try:
                    counters[f"{tid}.{name}"] = int(tr.count)
                except Exception:   # noqa: BLE001
                    continue
            for name, tr in snap.get("latency", {}).items():
                hist = getattr(tr, "hist", None)
                if hist is None:
                    continue
                try:
                    latency[f"{tid}.{name}"] = hist.state()
                except Exception:   # noqa: BLE001
                    continue
        return {"gauges": gauges, "counters": counters,
                "latency": latency, "unix_ns": time.time_ns()}, b""

    def op_flight(self, h: dict, body: bytes):
        """Tail every runtime's flight-recorder ring past ``since_ns`` —
        the parent absorbs the entries into the fabric's ring (forwarding,
        not draining: the child keeps its own ring for local dumps)."""
        since = h.get("since_ns")
        entries = list(self.flight.export(since_ns=since))
        for tid, t in self.tenants.items():
            fl = getattr(t.rt.ctx, "flight", None)
            if fl is None:
                continue
            for e in fl.export(since_ns=since):
                e["tenant"] = tid
                entries.append(e)
        entries.sort(key=lambda e: e["t_ns"])
        return {"entries": entries, "traces": self._trace_tail()}, b""

    def _trace_tail(self) -> list:
        """Adopted-trace journeys that GREW since the last poll: each item
        ships only the new spans past the per-trace cursor, so the parent's
        stitch is append-only (and idempotent regardless — the parent
        dedups by span identity, so an overlap can never double a span)."""
        out = []
        for tid, t in self.tenants.items():
            tracer = getattr(t.rt.ctx, "tracer", None)
            if tracer is None:
                continue
            for key, tr in list(tracer._adopted.items()):
                spans = tr.spans_wire()
                cur = self._trace_shipped.get((tid, key), 0)
                if len(spans) <= cur:
                    continue
                out.append({"origin_host": key[0], "trace_id": key[1],
                            "stream": tr.stream, "tenant": tid,
                            "spans": spans[cur:]})
                self._trace_shipped[(tid, key)] = len(spans)
                self._trace_shipped.move_to_end((tid, key))
        while len(self._trace_shipped) > self._trace_shipped_cap:
            self._trace_shipped.popitem(last=False)
        return out

    def op_boot_dcn(self, h: dict, body: bytes):
        """Boot the worker-owned DCN data plane: a DCNWorker bound to its
        own ephemeral port, every lane group owned by this shard — bulk
        SoA ingest (``ingest_chunk``/``K_ROWS``) lands in the child
        without touching the control socket."""
        if self.dcn is not None:
            return {"port": self.dcn.port}, b""     # idempotent retry
        from ..tpu.dcn import DCNWorker, LaneTopology
        # single-owner topology: this shard owns every lane group (the
        # DCNWorker serves from __init__ — ephemeral port, no peers)
        topo = LaneTopology(int(h["num_lanes"]), 1)
        self.dcn = DCNWorker(
            0, topo, h["app_text"], h["key_attr"], 0, {},
            stream_id=h.get("stream_id", "S"),
            lane_batch=int(h.get("lane_batch", 256)))
        return {"port": self.dcn.port}, b""

    def op_dcn_report(self, h: dict, body: bytes):
        if self.dcn is None:
            return {"report": None}, b""
        return {"report": {"matches": self.dcn.match_count,
                           "port": self.dcn.port}}, b""

    def op_drain(self, h: dict, body: bytes):
        for t in self.tenants.values():
            t.rt.flush_host()
        return {}, b""

    def op_stop(self, h: dict, body: bytes):
        self._stop.set()
        return {}, b""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="procmesh host worker")
    ap.add_argument("--index", type=int, required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--playback", default="1")
    ap.add_argument("--rundir", default=None)
    args = ap.parse_args(argv)
    # restart-storm test hook: a worker that can never boot exercises the
    # supervisor's backoff/give-up ladder with a real dying process
    if os.environ.get("SIDDHI_PROCMESH_CRASH_ON_BOOT") == "1":
        print("PROCMESH_CRASH", flush=True)
        return 3
    srv = WorkerServer(args.index, playback=args.playback == "1")
    port = srv.bind(args.port)
    if args.rundir:
        # the runfile must be durable BEFORE the ready handshake: once the
        # parent proceeds, a parent crash + restart must find this shard
        from .protocol import write_runfile
        write_runfile(args.rundir, args.index, port, os.getpid(), srv.nonce)
    hello = {"port": port, "pid": os.getpid(), "nonce": srv.nonce,
             # wall-clock at hello: the supervisor's first (coarse) clock-
             # offset estimate for this shard, refined by ping RTT later
             "unix_ns": time.time_ns()}
    print(f"PROCMESH_READY {json.dumps(hello)}", flush=True)
    srv.serve_forever()
    if args.rundir:
        # clean stop: a restarted supervisor must not dial a retired shard
        from .protocol import runfile_path
        try:
            os.remove(runfile_path(args.rundir, args.index))
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
