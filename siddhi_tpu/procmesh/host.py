"""Fabric-side adapters for process-backed mesh hosts.

:class:`ProcMeshHost` duck-types the in-process ``MeshHost`` surface the
fabric dispatches against (``deploy``/``undeploy``/``evidence``/
``free_slots``/``kill``/``close``), and :class:`RuntimeProxy` duck-types
the slice of ``SiddhiAppRuntime`` the fabric's apply/snapshot/restore
path touches — so ``MeshFabric``'s placement/migration/rebalance ladder
runs unchanged in either mode, byte-compatible by construction.

The proxy's delivery contract differs from the in-process runtime in ONE
deliberate way: output events are buffered on the worker (cursored
outbox) and the fabric dispatches them parent-side only AFTER the chunk
is durable — so a child SIGKILLed between apply and ack re-applies from
the restored pre-chunk state and every output is delivered exactly once
(see ``worker.py``).
"""

from __future__ import annotations

import re
import socket
import threading
import time
from typing import Callable, Optional

from .protocol import (
    WorkerDown,
    connect,
    connect_timeout_s,
    io_timeout_s,
    op_deadline_s,
    request,
)

# The hedge allowlist (ISSUE 19): ONLY ops that are idempotent BY WIRE
# CONTRACT may race a second attempt — ingest dedups by seq, resync/flush
# reconcile by cursor, snapshot/metrics/evidence/flight/ping are reads.
# deploy/undeploy/restore/subscribe and every lifecycle op stay out: their
# idempotence is by-tenant convention, not by sequence number, and a
# hedged lifecycle op racing a migration would be a correctness bug.
# scripts/check_guard_coverage.py pins this set structurally.
HEDGE_SAFE_OPS = frozenset({
    "ingest", "snapshot", "metrics", "evidence", "ping", "resync", "flight",
})

_SLO_CLASS_RE = re.compile(r"slo\.class\s*=\s*'([A-Za-z]+)'")


def slo_class_of(app_text: Optional[str]) -> Optional[str]:
    """The tenant's SLO class from its ``@app:fleet(... slo.class='…')``
    annotation (None → standard budgets). A regex, not a parse: deadline
    derivation must not cost a grammar pass per deploy."""
    m = _SLO_CLASS_RE.search(app_text or "")
    return m.group(1) if m else None


def _soa_types(rows: list) -> Optional[str]:
    """Derive a DCN ``pack_rows`` types string when every value fits the
    SoA wire (bool before int: bool is an int subclass)."""
    if not rows:
        return None
    width = len(rows[0])
    kinds = []
    for c in range(width):
        k = None
        for r in rows:
            if len(r) != width:
                return None
            v = r[c]
            if v is None:
                continue
            if isinstance(v, bool):
                t = "b"
            elif isinstance(v, int):
                t = "l"
            elif isinstance(v, float):
                t = "d"
            elif isinstance(v, str):
                t = "s"
            else:
                return None
            if k is None:
                k = t
            elif k != t:
                return None
        kinds.append(k or "l")      # all-null column: any numeric lane
    return "".join(kinds)


class WorkerClient:
    """One persistent control connection to a worker, ops serialized under
    a lock (the control plane is low-rate; feeder threads of one host
    serialize here exactly like the per-host DCN ingest model). A dead
    socket reconnects ONCE per op — every procmesh op is idempotent
    (deploys dedup by tenant, ingests dedup by seq, restores re-restore
    the same revision), so the retry is the lost-ack recovery path, not a
    double-apply risk.

    Deadline-budgeted hedging (ISSUE 19): an op in :data:`HEDGE_SAFE_OPS`
    spends only ``hedge_fraction`` of its budget on the first attempt —
    once that elapses, the (possibly desynced) connection is dropped and
    a SECOND attempt goes out over a fresh connection with the remaining
    budget. Exactly-once is pinned by the ops' own dedup (seq for ingest,
    read-only for the rest); ops outside the allowlist structurally never
    get a shortened first deadline. ``observer(op, seconds, ok)`` fires
    once per user-level call with the final outcome — the supervisor's
    per-op latency evidence."""

    def __init__(self, port_fn: Callable[[], Optional[int]],
                 io_timeout_s: Optional[float] = None,
                 connect_timeout_s: Optional[float] = None,
                 hedge_fraction: Optional[float] = 0.45,
                 observer: Optional[Callable[[str, float, bool],
                                             None]] = None):
        self._port_fn = port_fn
        self._io_timeout_s = io_timeout_s       # None → env/module default
        self._connect_timeout_s = connect_timeout_s
        self.hedge_fraction = hedge_fraction    # None disables hedging
        self.observer = observer
        self.hedge_attempts = 0
        self.hedge_wins = 0
        self._sock = None
        self._lock = threading.Lock()

    def base_timeout_s(self) -> float:
        """The resolved base IO deadline (config > env > default)."""
        return io_timeout_s(self._io_timeout_s)

    def _socket(self):
        if self._sock is None:
            port = self._port_fn()
            if port is None:
                raise WorkerDown("worker has no live control port")
            self._sock = connect(port, timeout=connect_timeout_s(
                self._connect_timeout_s), io_timeout=self.base_timeout_s())
        return self._sock

    def drop(self) -> None:
        with self._lock:
            self._drop_locked()

    def _drop_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call(self, op: str, header: Optional[dict] = None,
             body: bytes = b"", timeout: Optional[float] = None):
        budget = timeout if timeout else self.base_timeout_s()
        # the structural hedge gate: only allowlisted (wire-idempotent)
        # ops ever get a shortened first deadline
        hedged = self.hedge_fraction is not None and op in HEDGE_SAFE_OPS
        first = budget * self.hedge_fraction if hedged else budget
        t0 = time.monotonic()
        ok = False
        try:
            with self._lock:
                try:
                    rv = request(self._socket(), op, header, body,
                                 timeout=first)
                    ok = True
                    return rv
                except WorkerDown as e:
                    # stale connection (worker restarted, idle RST) or a
                    # burned hedge fraction: one fresh-connection attempt
                    # with the remaining budget, then the op's own
                    # idempotence carries it
                    self._drop_locked()
                    hedge = hedged and isinstance(e.__cause__,
                                                  socket.timeout)
                    if hedge:
                        self.hedge_attempts += 1
                    remaining = max(budget - (time.monotonic() - t0), 0.05)
                    try:
                        rv = request(self._socket(), op, header, body,
                                     timeout=remaining)
                    except WorkerDown:
                        self._drop_locked()
                        raise
                    if hedge:
                        self.hedge_wins += 1
                    ok = True
                    return rv
        finally:
            if self.observer is not None:
                try:
                    self.observer(op, time.monotonic() - t0, ok)
                except Exception:   # noqa: BLE001 — evidence must never
                    pass            # fail the op it describes


class RuntimeProxy:
    """The fabric's handle to one tenant runtime living in a worker
    process — the ``SiddhiAppRuntime`` surface ``_apply_locked`` /
    ``_save_tenant_locked`` / ``_restore_on`` dispatch against."""

    procmesh_proxy = True

    def __init__(self, client: WorkerClient, tenant_id: str,
                 slo_class: Optional[str] = None):
        self.client = client
        self.tenant_id = tenant_id
        # the tenant's SLO class scales every per-op deadline budget
        # (ISSUE 19): premium fails over fast, besteffort waits longer
        self.slo_class = slo_class
        self.callbacks: dict = {}       # stream_id -> [StreamCallback]
        self.delivered = -1             # highest outbox idx dispatched
        self._pending: list = []        # undispatched (idx, sid, ts, row)
        # durable-fabric wiring (mesh/fabric.py journal): outbox indices
        # are namespaced by the tenant's dedup epoch so a restored
        # incarnation's fresh idx space never collides in idempotent sinks
        self.out_epoch = 0
        self.raw_hooks: list = []       # fn([(epoch, idx, sid, ts, row)...])
        self.on_delivered = None        # fn(highest_idx) — journal cursor

    def _deadline(self, op: str) -> float:
        """Per-op deadline budget: op class × SLO class × the client's
        resolved base (MeshConfig > env > default)."""
        return op_deadline_s(op, self.slo_class,
                             self.client.base_timeout_s())

    # -- ingest / outputs ----------------------------------------------------
    def send_chunk(self, seq: int, stream_id: str, rows: list,
                   ts: list, trace: Optional[str] = None) -> bool:
        """Ship one seq-stamped chunk; reply events buffer until the
        fabric confirms durability and calls :meth:`deliver_pending`.
        ``trace`` is a hex-packed TraceContext riding the header — the
        child adopts it only on actual apply (seq dedup), so a lost-ack
        retry carries the SAME context and never doubles a span."""
        from ..tpu.dcn import pack_rows
        h = {"tenant": self.tenant_id, "stream": stream_id, "seq": seq,
             "ack": self.delivered}
        if trace is not None:
            h["trace"] = trace
        types = _soa_types(rows)
        if types is not None:
            h["enc"] = "soa"
            rh, _ = self.client.call(
                "ingest", h, body=pack_rows(types, rows, ts),
                timeout=self._deadline("ingest"))
        else:
            h["rows"], h["ts"] = rows, ts
            rh, _ = self.client.call("ingest", h,
                                     timeout=self._deadline("ingest"))
        self._buffer(rh.get("events", ()))
        return bool(rh.get("applied"))

    def _buffer(self, events) -> None:
        seen = {e[0] for e in self._pending}
        for e in events:
            idx = e[0]
            if idx > self.delivered and idx not in seen:
                self._pending.append(tuple(e))

    def deliver_pending(self) -> None:
        """Dispatch buffered worker outputs to the parent-side callbacks,
        grouped into per-stream runs (order preserved). Raw hooks (durable
        sinks) see every entry with its ``(epoch, idx)`` identity FIRST —
        delivery is at-least-once across a parent crash (the window between
        dispatch and the journaled cursor re-ships), so sinks dedup by that
        pair."""
        from ..core.event import Event
        from .journal import crash_point
        pending, self._pending = sorted(self._pending), []
        if not pending:
            return
        for hook in self.raw_hooks:
            hook([(self.out_epoch, e[0], e[1], e[2], e[3]) for e in pending])
        i = 0
        while i < len(pending):
            sid = pending[i][1]
            j = i
            while j < len(pending) and pending[j][1] == sid:
                j += 1
            evs = [Event(e[2], e[3]) for e in pending[i:j]]
            for cb in self.callbacks.get(sid, ()):
                cb.receive(evs)
            self.delivered = max(self.delivered, pending[j - 1][0])
            i = j
        # delivered-but-not-journaled chaos window: a crash here re-ships
        # the batch on recovery (resync/staged replay) — sinks dedup
        crash_point("deliver.dispatched")
        if self.on_delivered is not None:
            self.on_delivered(self.delivered)

    def pending_outputs(self) -> list:
        """Undispatched outbox entries (journal-checkpoint form): the
        cursor record persists them so a dead-worker recovery can replay
        outputs the old incarnation emitted but the parent never
        dispatched."""
        return [list(e) for e in sorted(self._pending)]

    def resync(self, ack: int) -> dict:
        """Parent-recovery reconciliation against a re-adopted live worker
        (see ``worker.op_resync``): prunes the child outbox through the
        journaled delivery cursor ``ack``, buffers the undelivered tail,
        and returns the child's authoritative applied mark."""
        rh, _ = self.client.call("resync", {"tenant": self.tenant_id,
                                            "ack": ack},
                                 timeout=self._deadline("resync"))
        if rh.get("present"):
            self.delivered = max(self.delivered, int(ack))
            self._buffer(rh.get("events", ()))
        return rh

    def subscribe(self, stream_id: str) -> None:
        """Arm child-side output capture for a stream WITHOUT attaching a
        parent callback (raw-hook sinks read the outbox identity instead
        of events). Idempotent on both sides."""
        if stream_id not in self.callbacks:
            self.callbacks.setdefault(stream_id, [])
            self.client.call("subscribe", {"tenant": self.tenant_id,
                                           "stream": stream_id})

    # -- the runtime surface the fabric touches ------------------------------
    def add_callback(self, stream_id: str, callback) -> None:
        first = stream_id not in self.callbacks
        self.callbacks.setdefault(stream_id, []).append(callback)
        if first:
            self.client.call("subscribe", {"tenant": self.tenant_id,
                                           "stream": stream_id})

    def flush_host(self) -> None:
        rh, _ = self.client.call("flush", {"tenant": self.tenant_id,
                                           "ack": self.delivered},
                                 timeout=self._deadline("flush"))
        self._buffer(rh.get("events", ()))

    def snapshot(self) -> bytes:
        _, blob = self.client.call("snapshot", {"tenant": self.tenant_id},
                                   timeout=self._deadline("snapshot"))
        return blob

    def restore(self, blob: bytes, applied: int = 0) -> None:
        self.client.call("restore", {"tenant": self.tenant_id,
                                     "applied": applied}, body=blob,
                         timeout=self._deadline("restore"))

    def shutdown(self) -> None:     # parity with SiddhiAppRuntime.shutdown
        self.client.call("undeploy", {"tenant": self.tenant_id},
                         timeout=self._deadline("undeploy"))


class ProcMeshHost:
    """One process-backed engine shard, byte-compatible with ``MeshHost``
    for the fabric's dispatch surface. The OS process itself belongs to
    the supervisor (``handle``); this object is the fabric's view."""

    def __init__(self, handle, capacity: int, device: Optional[int] = None,
                 playback: bool = True):
        self.handle = handle            # supervisor's ProcWorkerHandle
        self.index = handle.index
        self.capacity = capacity
        self.device = device
        self.playback = playback
        self.runtimes: dict = {}        # tenant_id -> RuntimeProxy
        self.rows_in = 0
        self.reserved = 0
        self.alive = True
        # degrade-drain flag (ISSUE 19): a draining host serves its
        # current tenants but takes no new placements
        self.draining = False
        self._specs: dict = {}          # tenant_id -> TenantSpec (redeploy)
        self._sm = None
        self._scrape_cache: dict = {}
        self._scrape_counters: dict = {}
        self._scrape_latency: dict = {}     # name -> LogHistogram state
        self._scrape_t: Optional[float] = None  # monotonic of last GOOD scrape
        self._scrape_t0 = time.monotonic()
        self._last_child_evidence: dict = {}

    @property
    def client(self) -> WorkerClient:
        return self.handle.client

    @property
    def free_slots(self) -> int:
        return self.capacity - len(self.runtimes) - self.reserved

    @property
    def slot(self):
        from ..mesh.plan import HostSlot
        return HostSlot(self.index, self.capacity, self.device)

    # -- tenant lifecycle ----------------------------------------------------
    def deploy(self, spec) -> RuntimeProxy:
        klass = slo_class_of(spec.app_text)
        self.client.call("deploy", {"tenant": spec.tenant_id,
                                    "app_text": spec.app_text,
                                    "playback": self.playback},
                         timeout=max(op_deadline_s(
                             "deploy", klass,
                             self.client.base_timeout_s()), 60.0))
        proxy = RuntimeProxy(self.client, spec.tenant_id, slo_class=klass)
        self.runtimes[spec.tenant_id] = proxy
        self._specs[spec.tenant_id] = spec
        return proxy

    def adopt_runtime(self, spec) -> RuntimeProxy:
        """Attach a proxy to a tenant the worker ALREADY hosts (parent
        recovery re-adoption): no deploy op — the shard keeps its engine
        state; the caller reconciles cursors via :meth:`RuntimeProxy.
        resync`."""
        proxy = RuntimeProxy(self.client, spec.tenant_id,
                             slo_class=slo_class_of(spec.app_text))
        self.runtimes[spec.tenant_id] = proxy
        self._specs[spec.tenant_id] = spec
        return proxy

    def undeploy(self, tenant_id: str) -> None:
        rt = self.runtimes.pop(tenant_id, None)
        self._specs.pop(tenant_id, None)
        if rt is not None:
            rt.shutdown()

    def compiled_programs(self) -> int:
        try:
            rh, _ = self.client.call("evidence")
            return int(rh["evidence"].get("compiled_programs", 0))
        except WorkerDown:
            return 0

    def evidence(self) -> dict:
        """Parent-side routing view merged with the child's fleet-tier
        scrape; a freshly dead child serves the last good scrape so an
        evidence walk racing a crash never takes the control plane down."""
        try:
            rh, _ = self.client.call("evidence")
            self._last_child_evidence = dict(rh["evidence"])
        except WorkerDown:
            pass
        child = dict(self._last_child_evidence)
        child.pop("tenants", None)
        child.pop("rows_in", None)
        return {
            "host": self.index, "device": self.device,
            "alive": self.alive,
            "tenants": len(self.runtimes),
            "capacity": self.capacity,
            "rows_in": self.rows_in,
            "mode": "process",
            # restart churn feeds placement/rebalance scoring: a
            # respawned worker is a worse home until it proves stable
            "restarts": self.handle.restarts,
            **child,
        }

    # -- child metric aggregation -------------------------------------------
    def scrape_metrics(self) -> dict:
        """Pull the child's full tracker state over the control wire. On
        ``WorkerDown`` the last good scrape is KEPT but its age keeps
        growing (:meth:`scrape_age_s`) — the federation layer expires
        families past the staleness ceiling instead of rendering dead
        values as live (the ISSUE-18 staleness fix)."""
        try:
            rh, _ = self.client.call("metrics")
            self._scrape_cache = dict(rh.get("gauges", {}))
            self._scrape_counters = dict(rh.get("counters", {}))
            self._scrape_latency = dict(rh.get("latency", {}))
            self._scrape_t = time.monotonic()
        except WorkerDown:
            pass                        # keep the last scrape; age grows
        return self._scrape_cache

    def scrape_age_s(self) -> float:
        """Seconds since the last SUCCESSFUL child scrape (since host
        creation when none ever landed) — the exported freshness signal:
        a dead or gave-up worker's age grows without bound, and the
        federated exposition drops its families past the ceiling."""
        return time.monotonic() - (self._scrape_t if self._scrape_t
                                   is not None else self._scrape_t0)

    def counter_states(self) -> dict:
        return dict(self._scrape_counters)

    def latency_states(self) -> dict:
        """Last scraped ``{tenant.name: LogHistogram state}`` — the raw
        material the fabric merges into per-worker and fabric-level
        families."""
        return dict(self._scrape_latency)

    def register_child_metrics(self, sm) -> int:
        """(Re-)register the child's scraped gauge families under
        ``mesh.h{i}.child.*``. Idempotent by unregister-first, so a
        restarted child's fresh families replace the old generation —
        never leak beside it (tests/test_metrics.py pins the teardown).
        ``scrape_age_s`` rides the same prefix, so the freshness gauge
        tears down with the host."""
        self._sm = sm
        sm.unregister(f"mesh.h{self.index}.child.")
        names = sorted(self.scrape_metrics())
        for name in names:
            sm.gauge_tracker(
                f"mesh.h{self.index}.child.{name}",
                lambda name=name: self._scrape_cache.get(name, 0.0))
        sm.gauge_tracker(f"mesh.h{self.index}.child.scrape_age_s",
                         self.scrape_age_s)
        return len(names)

    def unregister_child_metrics(self) -> None:
        if self._sm is not None:
            self._sm.unregister(f"mesh.h{self.index}.child.")

    # -- flight-recorder forwarding -----------------------------------------
    def forward_flight(self, flight, tracer=None) -> int:
        """Absorb the child runtimes' control-plane transitions into the
        fabric's ring (site-prefixed ``h{i}:``), tailing by the ring's
        loss-free ``since_ns`` cursor. Child stamps are corrected by the
        supervisor's clock-offset estimate so the merged timeline is
        causally ordered; trace journeys riding the tail stitch into
        ``tracer`` (span-identity dedup — idempotent)."""
        try:
            rh, _ = self.client.call(
                "flight", {"since_ns": self.handle.flight_cursor})
        except WorkerDown:
            return 0
        entries = rh.get("entries", [])
        offset_ns = int(getattr(self.handle, "clock_offset_ns", 0))
        if entries:
            self.handle.flight_cursor = max(e["t_ns"] for e in entries)
        if tracer is not None:
            for tj in rh.get("traces", ()):
                try:
                    tracer.stitch(int(tj.get("origin_host", 0)),
                                  int(tj.get("trace_id", 0)),
                                  tj.get("spans", ()),
                                  offset_ns=offset_ns,
                                  stream=tj.get("stream", "procmesh"))
                except Exception:   # noqa: BLE001 — stitching must never
                    continue        # take the sync path down
        return flight.absorb(entries, site_prefix=f"h{self.index}:",
                             offset_ns=offset_ns)

    # -- crash / teardown ----------------------------------------------------
    def kill(self) -> None:
        """REAL host SIGKILL: the supervisor nukes the child process; the
        proxies die with it (state recovers from the parent's snapshot
        store, exactly like the simulated in-process kill)."""
        self.handle.kill()
        self.drop_runtimes()

    def drop_runtimes(self) -> None:
        self.runtimes.clear()
        self._specs.clear()
        self.client.drop()

    def close(self) -> None:
        self.alive = False
        self.unregister_child_metrics()
        try:
            self.client.call("stop", timeout=5.0)
        except WorkerDown:
            pass
        self.handle.reap(timeout=5.0)
        self.runtimes.clear()
        self._specs.clear()
