"""procmesh control-socket wire format.

One frame per control operation, the DCN tier's length-prefixed framing
(``tpu/dcn.py``'s ``>BI`` header) with a JSON header + optional binary
body instead of fixed structs — control ops are low-rate and schema-rich
(deploy carries app text, snapshot/restore carry state blobs, ingest
carries row chunks), so the header stays readable while blobs stay raw:

``frame  := kind u8 · length u32 · payload``
``payload:= hdr_len u32 · json header · body bytes``

Kinds: ``F_REQ`` (supervisor/fabric → worker), ``F_RES`` (success reply),
``F_ERR`` (structured failure reply — the op raised; the connection stays
usable). Every request carries ``{"op": ...}``; replies echo nothing (the
protocol is strictly one-in-flight per connection, so responses pair by
order).

Deadline discipline: every blocking read arms a socket timeout first —
``_recv_exact`` refuses a timeout-less socket outright, the invariant
``scripts/check_socket_timeouts.py`` pins across the package. A timeout
at a frame boundary means *idle* (pollers continue); a timeout or close
mid-frame means the stream can never resync and raises
``ConnectionError``.

Ingest rows ride either JSON (``enc='json'``, any row shape) or the DCN
SoA wire (``enc='soa'`` — :func:`~siddhi_tpu.tpu.dcn.pack_rows` bytes in
the body, the worker-owned bulk hand-off decoded by ``unpack_rows`` on
the child), chosen per chunk by whether a types string covers the rows.
An ingest header may additionally carry ``trace`` — a hex-packed
:class:`~siddhi_tpu.observability.tracing.TraceContext` the child adopts
only on actual apply (seq dedup ⇒ exactly-once spans).

Observability federation (ISSUE 18): the ``metrics`` op reply ships FULL
tracker state — ``gauges`` (floats), ``counters`` (ints), ``latency``
(serialized :meth:`LogHistogram.state` dumps, mergeable by summing
counts on the fixed quarter-octave ladder) — plus a ``unix_ns`` scrape
stamp; ``ping`` replies and the ``PROCMESH_READY`` hello carry
``unix_ns`` so the supervisor can estimate each shard's wall-clock
offset; ``flight`` replies carry a ``traces`` tail of grown trace
journeys for parent-side stitching.
"""

from __future__ import annotations

import json
import os
import socket
import struct
from typing import Optional

_HDR = struct.Struct(">BI")     # frame kind + payload length (the DCN wire)
_JLEN = struct.Struct(">I")     # json header length inside the payload

F_REQ, F_RES, F_ERR = 1, 2, 3

CONNECT_TIMEOUT_S = 5.0
# ops include deploys (parse + numpy plan compile on the child) and
# chunk-cadence snapshots; generous next to the DCN data-plane deadline
IO_TIMEOUT_S = 30.0
# child boot = interpreter + siddhi_tpu import + socket bind, under
# fork-storm contention on a saturated CI container
READY_TIMEOUT_S = 120.0

MAX_FRAME = 256 * 1024 * 1024   # desync guard: one tenant snapshot tops out
# far below this; a larger length prefix means a corrupt stream


def runfile_path(run_dir: str, index: int) -> str:
    return os.path.join(run_dir, f"w{index}.run")


def write_runfile(run_dir: str, index: int, port: int, pid: int,
                  nonce: str) -> None:
    """Persist a worker's boot identity (atomic rename, fsynced): the
    handshake artifact a restarted supervisor scans to re-adopt live
    shards. Written by the child before it prints ``PROCMESH_READY``."""
    os.makedirs(run_dir, exist_ok=True)
    path = runfile_path(run_dir, index)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"index": index, "port": port, "pid": pid,
                   "nonce": nonce}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_runfile(run_dir: str, index: int) -> Optional[dict]:
    """Load one worker's runfile; None when absent or unreadable (a torn
    tmp never lands on the final name — ``os.replace`` is atomic)."""
    try:
        with open(runfile_path(run_dir, index), encoding="utf-8") as f:
            rf = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(rf, dict) or "port" not in rf or "pid" not in rf:
        return None
    return rf


def child_env(base: Optional[dict] = None) -> dict:
    """Spawn env for a worker/lane child: the parent may have found
    ``siddhi_tpu`` via a ``sys.path`` insert (script-style embedding) that a
    fresh interpreter won't repeat, so prepend the package's parent dir to
    PYTHONPATH."""
    env = dict(os.environ if base is None else base)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    parts = [pkg_root] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                          if p and p != pkg_root]
    env["PYTHONPATH"] = os.pathsep.join(parts)
    return env


class WorkerDown(ConnectionError):
    """The worker's control socket is gone (crash, SIGKILL, stop): the op
    did not complete and the caller must spill/retry through recovery."""


class WorkerOpError(RuntimeError):
    """The worker executed the op and reports a structured failure (the
    connection itself is fine)."""


def send_frame(sock: socket.socket, kind: int, header: dict,
               body: bytes = b"") -> None:
    j = json.dumps(header, separators=(",", ":")).encode()
    payload = _JLEN.pack(len(j)) + j + body
    sock.sendall(_HDR.pack(kind, len(payload)) + payload)


def recv_frame(sock: socket.socket, timeout: float = IO_TIMEOUT_S):
    """Returns ``(kind, header, body)`` or None on a cleanly closed
    connection. Arms the deadline; idle timeouts surface as
    ``socket.timeout`` only at a frame boundary."""
    sock.settimeout(timeout)
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    kind, n = _HDR.unpack(hdr)
    if n > MAX_FRAME:
        raise ConnectionError(f"oversized frame ({n} bytes): desynced")
    payload = _recv_exact(sock, n) if n else b""
    if payload is None or len(payload) < _JLEN.size:
        raise ConnectionError("connection closed mid-frame")
    (jn,) = _JLEN.unpack_from(payload, 0)
    header = json.loads(payload[_JLEN.size:_JLEN.size + jn].decode())
    return kind, header, payload[_JLEN.size + jn:]


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    if sock.gettimeout() is None:
        # every blocking recv in this package must carry a deadline
        # (scripts/check_socket_timeouts.py pins the same invariant in CI)
        raise ValueError("blocking recv on a socket without a timeout")
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if buf:
                # a half-read frame can never resync
                raise ConnectionError(
                    "connection timed out mid-frame") from None
            raise
        if not chunk:
            if buf:
                raise ConnectionError("connection closed mid-frame")
            return None
        buf += chunk
    return buf


def request(sock: socket.socket, op: str, header: Optional[dict] = None,
            body: bytes = b"", timeout: float = IO_TIMEOUT_S):
    """One synchronous control op: send ``F_REQ``, block for the paired
    reply. Returns ``(header, body)``; raises :class:`WorkerOpError` on an
    ``F_ERR`` reply and :class:`WorkerDown` when the socket dies."""
    h = dict(header or ())
    h["op"] = op
    try:
        send_frame(sock, F_REQ, h, body)
        res = recv_frame(sock, timeout=timeout)
    except socket.timeout as e:
        raise WorkerDown(f"worker op '{op}' timed out") from e
    except (OSError, ConnectionError) as e:
        raise WorkerDown(f"worker op '{op}' failed: {e}") from e
    if res is None:
        raise WorkerDown(f"worker closed during op '{op}'")
    kind, rh, rbody = res
    if kind == F_ERR:
        raise WorkerOpError(rh.get("error", "worker op failed"))
    if kind != F_RES:
        raise WorkerDown(f"unexpected frame kind {kind} for op '{op}'")
    return rh, rbody


def connect(port: int, timeout: float = CONNECT_TIMEOUT_S
            ) -> socket.socket:
    """Dial a worker's control port (loopback only — procmesh children are
    co-resident by construction) with connect + IO deadlines armed. A
    refused/unreachable dial means the process is gone: ``WorkerDown``."""
    try:
        sock = socket.create_connection(
            ("127.0.0.1", port), timeout=timeout)
    except (OSError, socket.timeout) as e:
        raise WorkerDown(f"worker port {port} unreachable: {e}") from e
    sock.settimeout(IO_TIMEOUT_S)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass                        # best-effort: control ops are small
    return sock
