"""procmesh control-socket wire format.

One frame per control operation, the DCN tier's length-prefixed framing
(``tpu/dcn.py``'s ``>BI`` header) widened with integrity fields — a JSON
header + optional binary body instead of fixed structs — control ops are
low-rate and schema-rich (deploy carries app text, snapshot/restore
carry state blobs, ingest carries row chunks), so the header stays
readable while blobs stay raw:

``frame  := kind u8 · length u32 · crc32 u32 · seq u32 · payload``
``payload:= hdr_len u32 · json header · body bytes``

Kinds: ``F_REQ`` (supervisor/fabric → worker), ``F_RES`` (success reply),
``F_ERR`` (structured failure reply — the op raised; the connection stays
usable). Every request carries ``{"op": ...}``; replies echo nothing (the
protocol is strictly one-in-flight per connection, so responses pair by
order).

Gray-failure hardening (ISSUE 19): ``crc32`` covers the payload — a
mismatch means the stream is corrupt and can never resync, so the
receiver raises ``ConnectionError`` (the client drops the connection and
idempotent ops retry over a fresh one). ``seq`` is a per-connection
per-direction monotone counter — a frame whose seq is ≤ the last one
seen is a duplicate delivery and is dropped silently (the receiver reads
the next frame). Both faults are injectable deterministically through
:class:`WireChaos`; detections count in :data:`WIRE_COUNTERS`.

Deadline discipline: every blocking read arms a socket timeout first —
``_recv_exact`` refuses a timeout-less socket outright, the invariant
``scripts/check_socket_timeouts.py`` pins across the package. A timeout
at a frame boundary means *idle* (pollers continue); a timeout or close
mid-frame means the stream can never resync and raises
``ConnectionError``. Deadlines are no longer module constants: they
resolve through :func:`io_timeout_s` / :func:`connect_timeout_s`
(explicit override > ``SIDDHI_PROCMESH_IO_TIMEOUT_S`` /
``SIDDHI_PROCMESH_CONNECT_TIMEOUT_S`` env > default), and per-op
budgets derive from the tenant's SLO class via :func:`op_deadline_s`.

Ingest rows ride either JSON (``enc='json'``, any row shape) or the DCN
SoA wire (``enc='soa'`` — :func:`~siddhi_tpu.tpu.dcn.pack_rows` bytes in
the body, the worker-owned bulk hand-off decoded by ``unpack_rows`` on
the child), chosen per chunk by whether a types string covers the rows.
An ingest header may additionally carry ``trace`` — a hex-packed
:class:`~siddhi_tpu.observability.tracing.TraceContext` the child adopts
only on actual apply (seq dedup ⇒ exactly-once spans).

Observability federation (ISSUE 18): the ``metrics`` op reply ships FULL
tracker state — ``gauges`` (floats), ``counters`` (ints), ``latency``
(serialized :meth:`LogHistogram.state` dumps, mergeable by summing
counts on the fixed quarter-octave ladder) — plus a ``unix_ns`` scrape
stamp; ``ping`` replies and the ``PROCMESH_READY`` hello carry
``unix_ns`` so the supervisor can estimate each shard's wall-clock
offset; ``flight`` replies carry a ``traces`` tail of grown trace
journeys for parent-side stitching.
"""

from __future__ import annotations

import json
import os
import random
import socket
import struct
import time
import weakref
import zlib
from typing import Optional

_HDR = struct.Struct(">BIII")   # kind + payload length + crc32 + seq
_JLEN = struct.Struct(">I")     # json header length inside the payload

F_REQ, F_RES, F_ERR = 1, 2, 3

CONNECT_TIMEOUT_S = 5.0
# ops include deploys (parse + numpy plan compile on the child) and
# chunk-cadence snapshots; generous next to the DCN data-plane deadline
IO_TIMEOUT_S = 30.0
# child boot = interpreter + siddhi_tpu import + socket bind, under
# fork-storm contention on a saturated CI container
READY_TIMEOUT_S = 120.0

MAX_FRAME = 256 * 1024 * 1024   # desync guard: one tenant snapshot tops out
# far below this; a larger length prefix means a corrupt stream


def io_timeout_s(override: Optional[float] = None) -> float:
    """Control-op IO deadline: explicit override (``MeshConfig``) >
    ``SIDDHI_PROCMESH_IO_TIMEOUT_S`` env > module default."""
    if override is not None:
        return float(override)
    env = os.environ.get("SIDDHI_PROCMESH_IO_TIMEOUT_S")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return IO_TIMEOUT_S


def connect_timeout_s(override: Optional[float] = None) -> float:
    """Dial deadline: explicit override > env > module default."""
    if override is not None:
        return float(override)
    env = os.environ.get("SIDDHI_PROCMESH_CONNECT_TIMEOUT_S")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return CONNECT_TIMEOUT_S


# Per-op deadline budgets as multiples of the base IO deadline: data-plane
# and read-only ops get tight budgets (they are hedge-safe and retried),
# deploys/restores get room (parse + plan compile on the child). A
# tenant's SLO class scales the whole budget — premium tenants would
# rather fail over fast than wait out a generous deadline, besteffort
# tenants prefer patience over churn.
OP_BUDGET_SCALE = {
    "ping": 0.25,
    "ingest": 0.5, "resync": 0.5, "flight": 0.5,
    "metrics": 0.5, "evidence": 0.5, "subscribe": 0.5,
    "snapshot": 1.0, "flush": 1.0, "undeploy": 1.0,
    "deploy": 2.0, "restore": 2.0,
}
SLO_CLASS_SCALE = {"premium": 0.5, "standard": 1.0, "besteffort": 1.5}


def op_deadline_s(op: str, slo_class: Optional[str] = None,
                  base_s: Optional[float] = None) -> float:
    """Per-op deadline budget: ``base × op-class scale × SLO-class scale``
    (ISSUE 19 — replaces the one-size ``IO_TIMEOUT_S`` on proxy ops)."""
    base = io_timeout_s(base_s)
    return (base * OP_BUDGET_SCALE.get(op, 1.0)
            * SLO_CLASS_SCALE.get(slo_class or "standard", 1.0))


def runfile_path(run_dir: str, index: int) -> str:
    return os.path.join(run_dir, f"w{index}.run")


def write_runfile(run_dir: str, index: int, port: int, pid: int,
                  nonce: str) -> None:
    """Persist a worker's boot identity (atomic rename, fsynced): the
    handshake artifact a restarted supervisor scans to re-adopt live
    shards. Written by the child before it prints ``PROCMESH_READY``."""
    os.makedirs(run_dir, exist_ok=True)
    path = runfile_path(run_dir, index)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"index": index, "port": port, "pid": pid,
                   "nonce": nonce}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_runfile(run_dir: str, index: int) -> Optional[dict]:
    """Load one worker's runfile; None when absent or unreadable (a torn
    tmp never lands on the final name — ``os.replace`` is atomic)."""
    try:
        with open(runfile_path(run_dir, index), encoding="utf-8") as f:
            rf = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(rf, dict) or "port" not in rf or "pid" not in rf:
        return None
    return rf


def child_env(base: Optional[dict] = None) -> dict:
    """Spawn env for a worker/lane child: the parent may have found
    ``siddhi_tpu`` via a ``sys.path`` insert (script-style embedding) that a
    fresh interpreter won't repeat, so prepend the package's parent dir to
    PYTHONPATH."""
    env = dict(os.environ if base is None else base)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    parts = [pkg_root] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                          if p and p != pkg_root]
    env["PYTHONPATH"] = os.pathsep.join(parts)
    return env


class WorkerDown(ConnectionError):
    """The worker's control socket is gone (crash, SIGKILL, stop): the op
    did not complete and the caller must spill/retry through recovery."""


class WorkerOpError(RuntimeError):
    """The worker executed the op and reports a structured failure (the
    connection itself is fine)."""


# ---------------------------------------------------------------------------
# wire integrity: per-connection frame seqs + detection counters

# Per-socket monotone frame counters, one per direction. Keyed weakly on
# the socket object so a dropped connection (the one recovery path for a
# corrupt stream) resets both streams for free.
_SEND_SEQ: "weakref.WeakKeyDictionary[socket.socket, int]" = \
    weakref.WeakKeyDictionary()
_RECV_SEQ: "weakref.WeakKeyDictionary[socket.socket, int]" = \
    weakref.WeakKeyDictionary()

# Process-wide detections (receiver side). A worker surfaces its copy in
# ``ping``/``evidence`` replies; the parent's copy feeds bench evidence.
WIRE_COUNTERS = {"crc_rejected": 0, "dup_frames_dropped": 0}


def wire_counters() -> dict:
    return dict(WIRE_COUNTERS)


class WireChaos:
    """Deterministic wire-level fault interposer (ISSUE 19).

    Seeded per-site exactly like :class:`~siddhi_tpu.resilience.chaos
    .ChaosInjector` — ``Random((seed << 32) ^ crc32(site))`` — so a
    given (seed, site) pair replays the same fault schedule regardless
    of unrelated traffic. Sites are op names (``ingest``, ``snapshot``;
    replies roll on the same op site via :func:`request`).

    Faults, all injected in the PARENT process (children never install
    an interposer):

    - ``delay_p`` / ``delay_ms``: hold the frame before sending;
    - ``drop_send_p``: one-direction partition parent→worker — the
      request never leaves, the caller times out against its budget;
    - ``drop_recv_p``: one-direction partition worker→parent — the reply
      is consumed off the wire then discarded, surfacing as
      ``socket.timeout`` (the caller must treat the connection as
      desynced, exactly like a real lost reply);
    - ``corrupt_p``: flip one payload byte AFTER the CRC is computed —
      the receiver's CRC check must reject the frame;
    - ``dup_p``: send the frame twice — the receiver's seq dedup must
      drop the second copy.

    ``ops`` (a set) restricts faults to those op sites; ``fault_budget``
    caps total injected faults (deterministic single-fault tests).
    Mutable mid-run, like ``ChaosInjector``.
    """

    def __init__(self, seed: int = 0, delay_ms: float = 0.0,
                 delay_p: float = 0.0, drop_send_p: float = 0.0,
                 drop_recv_p: float = 0.0, corrupt_p: float = 0.0,
                 dup_p: float = 0.0, ops: Optional[set] = None,
                 fault_budget: Optional[int] = None):
        self.seed = int(seed)
        self.delay_ms = float(delay_ms)
        self.delay_p = float(delay_p)
        self.drop_send_p = float(drop_send_p)
        self.drop_recv_p = float(drop_recv_p)
        self.corrupt_p = float(corrupt_p)
        self.dup_p = float(dup_p)
        self.ops = set(ops) if ops is not None else None
        self.fault_budget = fault_budget
        self._rngs: dict = {}
        self.counters = {"delayed": 0, "dropped_send": 0,
                         "dropped_recv": 0, "corrupted": 0,
                         "duplicated": 0}

    def _rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(
                (self.seed << 32) ^ zlib.crc32(site.encode()))
        return rng

    def _roll(self, site: str, p: float) -> bool:
        if p <= 0.0:
            return False
        return self._rng(site).random() < p

    def _take(self, kind: str) -> bool:
        """Consume one unit of fault budget; False when exhausted."""
        if self.fault_budget is not None:
            if self.fault_budget <= 0:
                return False
            self.fault_budget -= 1
        self.counters[kind] += 1
        return True

    def _applies(self, site: str) -> bool:
        return self.ops is None or site in self.ops

    def on_send(self, site: str, frame: bytes,
                payload_off: int) -> Optional[bytes]:
        """Transform an outbound frame; None means partitioned (dropped
        on the floor — the caller's deadline does the detecting)."""
        if not self._applies(site):
            return frame
        if self._roll(site, self.delay_p) and self._take("delayed"):
            time.sleep(self.delay_ms / 1000.0)
        if self._roll(site, self.drop_send_p) and self._take("dropped_send"):
            return None
        if self._roll(site, self.corrupt_p) and self._take("corrupted"):
            # flip a payload byte AFTER the CRC was stamped: the receiver
            # must detect this, never deliver it
            i = payload_off + self._rng(site).randrange(
                max(len(frame) - payload_off, 1))
            i = min(i, len(frame) - 1)
            frame = frame[:i] + bytes([frame[i] ^ 0xFF]) + frame[i + 1:]
        if self._roll(site, self.dup_p) and self._take("duplicated"):
            frame = frame + frame    # same seq twice: dedup must drop one
        return frame

    def on_recv(self, site: str) -> bool:
        """True → discard the just-received reply (worker→parent
        partition); the caller sees a timeout."""
        if not self._applies(site):
            return False
        return self._roll(site, self.drop_recv_p) \
            and self._take("dropped_recv")

    def report(self) -> dict:
        return {"seed": self.seed,
                "probabilities": {"delay": self.delay_p,
                                  "drop_send": self.drop_send_p,
                                  "drop_recv": self.drop_recv_p,
                                  "corrupt": self.corrupt_p,
                                  "dup": self.dup_p},
                "counters": dict(self.counters)}


_WIRE_CHAOS: Optional[WireChaos] = None


def install_wire_chaos(chaos: Optional[WireChaos]) -> Optional[WireChaos]:
    """Install (or clear, with None) the process-wide interposer; returns
    the previous one so tests can restore it in a finally."""
    global _WIRE_CHAOS
    prev, _WIRE_CHAOS = _WIRE_CHAOS, chaos
    return prev


def send_frame(sock: socket.socket, kind: int, header: dict,
               body: bytes = b"", site: Optional[str] = None) -> None:
    j = json.dumps(header, separators=(",", ":")).encode()
    payload = _JLEN.pack(len(j)) + j + body
    seq = (_SEND_SEQ.get(sock, 0) + 1) & 0xFFFFFFFF
    _SEND_SEQ[sock] = seq
    frame = _HDR.pack(kind, len(payload), zlib.crc32(payload), seq) + payload
    chaos = _WIRE_CHAOS
    if chaos is not None:
        out = chaos.on_send(site or f"k{kind}", frame, _HDR.size)
        if out is None:
            return              # partitioned: never hits the wire
        frame = out
    sock.sendall(frame)


def recv_frame(sock: socket.socket, timeout: Optional[float] = None,
               site: Optional[str] = None):
    """Returns ``(kind, header, body)`` or None on a cleanly closed
    connection. Arms the deadline; idle timeouts surface as
    ``socket.timeout`` only at a frame boundary. Verifies the payload
    CRC (mismatch ⇒ the stream is corrupt ⇒ ``ConnectionError``) and
    drops duplicate frames (seq ≤ last seen) silently."""
    sock.settimeout(io_timeout_s() if timeout is None else timeout)
    while True:
        hdr = _recv_exact(sock, _HDR.size)
        if hdr is None:
            return None
        kind, n, crc, seq = _HDR.unpack(hdr)
        if n > MAX_FRAME:
            raise ConnectionError(f"oversized frame ({n} bytes): desynced")
        payload = _recv_exact(sock, n) if n else b""
        if payload is None or len(payload) < _JLEN.size:
            raise ConnectionError("connection closed mid-frame")
        if zlib.crc32(payload) != crc:
            WIRE_COUNTERS["crc_rejected"] += 1
            raise ConnectionError(
                "frame crc mismatch: corrupt stream, cannot resync")
        last = _RECV_SEQ.get(sock, 0)
        if seq <= last:
            # duplicate delivery: drop and read the next frame — the
            # one-in-flight pairing stays intact
            WIRE_COUNTERS["dup_frames_dropped"] += 1
            continue
        _RECV_SEQ[sock] = seq
        chaos = _WIRE_CHAOS
        if chaos is not None and kind != F_REQ \
                and chaos.on_recv(site or "recv"):
            # reply partitioned worker→parent: to the caller this IS a
            # lost reply — surface the same way (deadline expiry)
            raise socket.timeout("wire chaos: reply partitioned")
        (jn,) = _JLEN.unpack_from(payload, 0)
        header = json.loads(payload[_JLEN.size:_JLEN.size + jn].decode())
        return kind, header, payload[_JLEN.size + jn:]


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    if sock.gettimeout() is None:
        # every blocking recv in this package must carry a deadline
        # (scripts/check_socket_timeouts.py pins the same invariant in CI)
        raise ValueError("blocking recv on a socket without a timeout")
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if buf:
                # a half-read frame can never resync
                raise ConnectionError(
                    "connection timed out mid-frame") from None
            raise
        if not chunk:
            if buf:
                raise ConnectionError("connection closed mid-frame")
            return None
        buf += chunk
    return buf


def request(sock: socket.socket, op: str, header: Optional[dict] = None,
            body: bytes = b"", timeout: Optional[float] = None):
    """One synchronous control op: send ``F_REQ``, block for the paired
    reply. Returns ``(header, body)``; raises :class:`WorkerOpError` on an
    ``F_ERR`` reply and :class:`WorkerDown` when the socket dies.

    The op's deadline is scoped to the op: the socket's prior timeout is
    restored on every exit path, so a generous snapshot budget never
    becomes the next op's idle deadline (ISSUE 19 satellite)."""
    h = dict(header or ())
    h["op"] = op
    if timeout is None:
        timeout = io_timeout_s()
    try:
        prev = sock.gettimeout()
    except OSError:
        prev = None
    try:
        send_frame(sock, F_REQ, h, body, site=op)
        res = recv_frame(sock, timeout=timeout, site=op)
    except socket.timeout as e:
        raise WorkerDown(f"worker op '{op}' timed out") from e
    except (OSError, ConnectionError) as e:
        raise WorkerDown(f"worker op '{op}' failed: {e}") from e
    finally:
        if prev is not None:
            try:
                sock.settimeout(prev)
            except OSError:
                pass            # socket already dead: nothing to restore
    if res is None:
        raise WorkerDown(f"worker closed during op '{op}'")
    kind, rh, rbody = res
    if kind == F_ERR:
        raise WorkerOpError(rh.get("error", "worker op failed"))
    if kind != F_RES:
        raise WorkerDown(f"unexpected frame kind {kind} for op '{op}'")
    return rh, rbody


def connect(port: int, timeout: Optional[float] = None,
            io_timeout: Optional[float] = None) -> socket.socket:
    """Dial a worker's control port (loopback only — procmesh children are
    co-resident by construction) with connect + IO deadlines armed. A
    refused/unreachable dial means the process is gone: ``WorkerDown``."""
    timeout = connect_timeout_s(timeout)
    try:
        sock = socket.create_connection(
            ("127.0.0.1", port), timeout=timeout)
    except (OSError, socket.timeout) as e:
        raise WorkerDown(f"worker port {port} unreachable: {e}") from e
    sock.settimeout(io_timeout_s(io_timeout))
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass                        # best-effort: control ops are small
    return sock
