"""Fabric control-plane journal: the parent's durable memory.

PR 16 made worker death a bounded event; this module does the same for the
*parent*. Every control-plane mutation — tenant deploy/undeploy, migration
intent → commit, recovery decisions, per-tenant apply/delivery cursors,
worker restart attempts — is appended here *before* (intents) or
immediately after (progress cursors) it actuates, so a SIGKILLed
supervisor process can be restarted and replayed back to a consistent
view of the mesh (``MeshFabric`` resume path: re-adopt live workers,
snapshot-restore dead ones).

The byte layer is the flow WAL's segment/CRC format
(:mod:`siddhi_tpu.flow.records` — ``u32 len | u32 crc | u64 lsn |
payload``), with JSON payloads instead of SoA rows: control mutations are
low-rate and schema-rich. Segments are named by first LSN
(``%020d.jnl``); a :meth:`checkpoint` rolls a fresh segment, writes the
full compacted state as its first record and drops every earlier segment
(acked-segment truncation — the checkpoint covers them). On open, the
active segment's torn tail is truncated back to the last intact record,
the same crash-tail discipline as the WAL.

Record payloads are ``{"k": kind, ...fields}``. :meth:`replay` returns
the newest checkpoint state (if any) plus every intact record after it,
in LSN order; semantic replay ordering (intent-without-commit resolution,
cursor merging) belongs to the fabric.

This module also owns :func:`crash_point`, the ``SIDDHI_CRASH_AT`` chaos
hook: parent-kill tests set ``SIDDHI_CRASH_AT=<site>[:N]`` and the parent
SIGKILLs *itself* the Nth time that site is reached — placed at every
journal/actuate boundary so recovery is provably correct on both sides of
each write.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
from typing import Iterator, Optional, Tuple

from ..flow.records import REC_HDR, pack_record, scan_file

log = logging.getLogger("siddhi_tpu.procmesh.journal")

_SEG_FMT = "%020d.jnl"
CKPT_KIND = "ckpt"

# -- SIDDHI_CRASH_AT -----------------------------------------------------------

_crash_hits: dict = {}
_crash_lock = threading.Lock()


def crash_point(site: str) -> None:
    """Chaos hook: if ``SIDDHI_CRASH_AT=<site>[:N]`` names this site,
    SIGKILL the current process the Nth time it is reached (default first).
    A no-op unless armed — the production cost is one getenv."""
    spec = os.environ.get("SIDDHI_CRASH_AT")
    if not spec:
        return
    want, _, nth = spec.partition(":")
    if want != site:
        return
    with _crash_lock:
        hits = _crash_hits.get(site, 0) + 1
        _crash_hits[site] = hits
    if hits >= int(nth or 1):
        log.warning("SIDDHI_CRASH_AT: killing self at site %r (hit %d)",
                    site, hits)
        os.kill(os.getpid(), signal.SIGKILL)


class FabricJournal:
    """Append-only segmented journal of fabric control-plane records."""

    def __init__(self, base_dir: str, segment_bytes: int = 256 * 1024,
                 fsync: bool = False):
        self.dir = base_dir
        os.makedirs(self.dir, exist_ok=True)
        self.segment_bytes = max(64, int(segment_bytes))
        self.fsync = fsync
        self._lock = threading.Lock()
        self._fh = None
        self._active: Optional[str] = None
        self._active_size = 0
        self.next_lsn = 1
        self.records_appended = 0
        self.records_since_ckpt = 0
        self._recover_tail()

    # -- open / crash-tail recovery -------------------------------------------
    def _segments(self) -> list:
        return sorted(f for f in os.listdir(self.dir) if f.endswith(".jnl"))

    def _recover_tail(self) -> None:
        segs = self._segments()
        if not segs:
            return
        path = os.path.join(self.dir, segs[-1])
        last_lsn = None
        scan = scan_file(path)
        for lsn, _payload in scan:
            last_lsn = lsn
        if scan.torn:
            log.warning("journal %s: truncating torn tail (%d -> %d bytes)",
                        path, len(scan.buf), scan.good_end)
            with open(path, "r+b") as f:
                f.truncate(scan.good_end)
        self.next_lsn = (last_lsn + 1 if last_lsn is not None
                         else int(segs[-1].split(".")[0]))

    # -- append ----------------------------------------------------------------
    def _roll_locked(self) -> None:
        if self._fh is not None:
            self._fh.close()
        self._active = _SEG_FMT % self.next_lsn
        self._fh = open(os.path.join(self.dir, self._active), "ab")
        self._active_size = self._fh.tell()

    def _write_locked(self, rec: dict) -> int:
        if self._fh is None or self._active_size >= self.segment_bytes:
            self._roll_locked()
        lsn = self.next_lsn
        payload = json.dumps(rec, separators=(",", ":")).encode()
        self._fh.write(pack_record(payload, lsn))
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
        self._active_size += REC_HDR.size + len(payload)
        self.next_lsn = lsn + 1
        self.records_appended += 1
        return lsn

    def append(self, kind: str, **fields) -> int:
        """Durably log one control-plane record; returns its LSN. The
        record is flushed to the OS before return, so a SIGKILL after
        ``append`` never loses it (fsync is opt-in for media-crash
        durability)."""
        rec = {"k": kind}
        rec.update(fields)
        with self._lock:
            lsn = self._write_locked(rec)
            self.records_since_ckpt += 1
        # "journaled but not actuated" is the canonical chaos window: the
        # hook fires AFTER the record is durable, BEFORE the caller acts
        crash_point("journal." + kind)
        return lsn

    # -- checkpoint + truncation -----------------------------------------------
    def checkpoint(self, state: dict) -> int:
        """Write a full compacted state record into a FRESH segment and drop
        every earlier segment — replay afterwards starts from this record."""
        with self._lock:
            self._roll_locked()
            lsn = self._write_locked({"k": CKPT_KIND, "state": state})
            # every earlier segment (including the one just sealed) is now
            # covered by the checkpoint record
            for name in self._segments():
                if name != self._active:
                    os.remove(os.path.join(self.dir, name))
            self.records_since_ckpt = 0
        crash_point("journal.checkpoint")
        return lsn

    # -- replay ----------------------------------------------------------------
    def _iter_records(self) -> Iterator[Tuple[int, dict]]:
        segs = self._segments()
        for i, name in enumerate(segs):
            scan = scan_file(os.path.join(self.dir, name))
            for lsn, payload in scan:
                yield lsn, json.loads(payload.decode())
            if scan.torn:
                # torn tail of the ACTIVE segment is a normal crash tail;
                # anywhere else is mid-log corruption — stop either way to
                # preserve LSN contiguity
                later = len(segs) - i - 1
                log.warning(
                    "journal %s: torn/corrupt record at byte %d — replay "
                    "stopped%s", os.path.join(self.dir, name), scan.good_end,
                    f"; {later} later segment(s) skipped" if later else "")
                return

    def replay(self) -> Tuple[Optional[dict], list]:
        """Returns ``(checkpoint_state, tail)``: the newest intact
        checkpoint's state (or None) and every record after it, each as
        ``{"lsn": ..., "k": ..., ...fields}`` in LSN order."""
        state, tail = None, []
        for lsn, rec in self._iter_records():
            if rec.get("k") == CKPT_KIND:
                state, tail = rec.get("state"), []
                continue
            rec = dict(rec)
            rec["lsn"] = lsn
            tail.append(rec)
        return state, tail

    # -- introspection ---------------------------------------------------------
    def position(self) -> dict:
        with self._lock:
            segs = self._segments()
            total = 0
            for name in segs:
                try:
                    total += os.path.getsize(os.path.join(self.dir, name))
                except OSError:
                    pass
            return {"lsn": self.next_lsn - 1, "segments": len(segs),
                    "bytes": total,
                    "records_since_checkpoint": self.records_since_ckpt}

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
