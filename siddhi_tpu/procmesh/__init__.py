"""procmesh — process-per-host mesh runtime.

Each mesh host runs as its OWN OS process (its own interpreter, GIL and
JAX runtime); ``MeshFabric`` becomes a control plane over length-prefixed
sockets. ``mesh(mode='process')`` arms it; the fabric's placement/
migration/rebalance/``mesh_replace`` ladder is byte-compatible with the
in-process mode, and a SIGKILLed child recovers through the SAME
``kill_host``/``recover_tenant`` path the simulated chaos tests exercise.

Layers:

- :mod:`.protocol` — the frame wire (kind u8 · len u32 · json+body) plus
  deadline discipline every read arms;
- :mod:`.worker` — the child entrypoint: SiddhiManager + FleetManager +
  optional DCN worker behind one control socket, seq-deduped ingest and
  a cursored output outbox for exactly-once under lost acks;
- :mod:`.supervisor` — spawns/monitors/restarts workers (PeerHealth
  heartbeats, exponential backoff with a windowed give-up budget);
- :mod:`.journal` — the durable control plane: a CRC-framed mutation
  journal (intent logged BEFORE actuation) + checkpoint/compaction, so a
  SIGKILLed *parent* restarts, re-adopts still-live workers via their
  runfiles and resolves in-flight migrations to exactly one owner;
- :mod:`.host` — the fabric-side ``MeshHost``/runtime duck types;
- :mod:`.lanepool` — ``@app:host_batch(workers.mode='process')``:
  lane-shard children for the columnar host tier.
"""

from __future__ import annotations

from .host import ProcMeshHost, RuntimeProxy, WorkerClient
from .journal import FabricJournal
from .lanepool import LanePoolError, ProcessLanePool
from .protocol import (
    CONNECT_TIMEOUT_S,
    IO_TIMEOUT_S,
    READY_TIMEOUT_S,
    WorkerDown,
    WorkerOpError,
)
from .supervisor import ProcMeshSupervisor, SupervisorConfig, WorkerSpawnError

__all__ = [
    "CONNECT_TIMEOUT_S",
    "IO_TIMEOUT_S",
    "READY_TIMEOUT_S",
    "FabricJournal",
    "LanePoolError",
    "ProcMeshHost",
    "ProcMeshSupervisor",
    "ProcessLanePool",
    "RuntimeProxy",
    "SupervisorConfig",
    "WorkerClient",
    "WorkerDown",
    "WorkerOpError",
    "WorkerSpawnError",
]
